#!/usr/bin/env python3
"""Driver benchmark entry point: prints ONE JSON line
`{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}`.

Data-proof staging (VERDICT r2 #1): the benchmark is split into
independently-timed children so a wedged TPU tunnel can never destroy the
CPU numbers, and a per-stage status record explains exactly what ran:

  1. `--stage cpu`    CPU-native + numpy baselines, run hermetically
                      (PALLAS_AXON_POOL_IPS unset, JAX_PLATFORMS=cpu) —
                      cannot touch the TPU tunnel, always yields the
                      vs_baseline denominator.
  2. `--stage device` ONE long-warm child: backend init (`jax.devices()`
                      has been observed to need minutes through the axon
                      tunnel — r1-r3 gave it only 150 s and got zero TPU
                      data) and the benches run in the SAME process, so
                      the warm is never thrown away. Budget ≥600 s per
                      VERDICT r3 #1. Only if that child times out or dies
                      is the stage re-run hermetically on the CPU jax
                      backend (clearly marked platform=cpu + error), so
                      the metric still carries measured data.

Environment knobs:
  CEPH_TPU_BENCH_TIMEOUT  total budget in seconds (default 2400)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from ceph_tpu.utils import tracer  # noqa: E402
TOTAL_BUDGET = int(os.environ.get("CEPH_TPU_BENCH_TIMEOUT", "2400"))
# reactor shard knob: the cluster_tpu stage sweeps 1/2/4 shards up to
# this cap, and the attribution stage profiles the sharded runtime
# (same guarded parse as bench_driver._reactor_shards_knob — a
# malformed value must not kill the bench before any stage runs)
try:
    REACTOR_SHARDS = max(1, int(
        os.environ.get("CEPH_TPU_REACTOR_SHARDS", "4")))
except ValueError:
    REACTOR_SHARDS = 4
# process-backed reactor knob: the cluster_tpu stage sweeps 1/2 worker
# PROCESSES up to this cap (the true GIL escape; same guarded parse)
try:
    REACTOR_PROCS = max(1, int(
        os.environ.get("CEPH_TPU_REACTOR_PROCS", "2")))
except ValueError:
    REACTOR_PROCS = 2
CPU_TIMEOUT = 420
DEVICE_TIMEOUT = 900  # single long warm: backend init + benches, one child
CLUSTER_TPU_TIMEOUT = 860  # in-situ EC-over-tpu cluster stage: body
#                            (240) + datapath (120) + reactor shard
#                            curve (180) + process-backed curve (240)
#                            + scaling child headroom
ATTRIBUTION_TIMEOUT = 240  # hermetic attribution-profiler stage
FAILURE_STORM_TIMEOUT = 500  # kill/revive resilience + repair-ratio stage
#                              (280) + cross-process flight-recorder
#                              drill (170) + headroom
SWARM_TIMEOUT = 320  # 200-client multi-tenant fairness + SLO pipeline stage
QOS_STORM_TIMEOUT = 560  # 1000-client sharded storm, scheduler A/B +
#                          recovery-under-storm + shed phase (520 body)
INTERLEAVE_TIMEOUT = 440  # seed-swept schedule explorer + sanitizer AND
#                           flight-recorder overhead (3 modes x 2 reps)
METRIC = "ec_encode_k8m3_1MiB_chunk"

_deadline = time.monotonic() + TOTAL_BUDGET


def _budget(want: float) -> float:
    return max(10.0, min(want, _deadline - time.monotonic()))


def _hermetic_env() -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # axon sitecustomize trigger
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["CEPH_TPU_REACTOR_SHARDS"] = str(REACTOR_SHARDS)
    env["CEPH_TPU_REACTOR_PROCS"] = str(REACTOR_PROCS)
    return env


def _tpu_env() -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["CEPH_TPU_REACTOR_SHARDS"] = str(REACTOR_SHARDS)
    env["CEPH_TPU_REACTOR_PROCS"] = str(REACTOR_PROCS)
    return env


def run_stage(stage: str, env: dict, timeout: float) -> dict:
    """Run one bench_driver stage; returns {"status", "elapsed_s", ...data}."""
    with tracer.span(f"bench:{stage}") as sp:
        out = _run_stage_child(stage, env, timeout)
        if sp is not None:
            sp.set_tag("status", out.get("status"))
            sp.set_tag("platform", out.get("platform"))
        return out


def _run_stage_child(stage: str, env: dict, timeout: float) -> dict:
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "ceph_tpu.tools.bench_driver",
             "--stage", stage],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired as e:
        stderr = e.stderr
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
        # a wedged stage is EXACTLY where leaked tasks show up: count
        # them here too, or tail_clean would pass in the worst case
        return {"status": f"timeout after {timeout:.0f}s",
                "elapsed_s": round(time.monotonic() - t0, 1),
                "destroyed_tasks": (stderr or "").count(
                    "Task was destroyed but it is pending"),
                "stderr_tail": (stderr or "")[-800:]}
    except OSError as e:
        return {"status": f"launch failed: {e}",
                "elapsed_s": round(time.monotonic() - t0, 1)}
    sys.stderr.write(proc.stderr)
    # bench-tail cleanliness gate: a stage that destroys pending
    # event-loop tasks ("Task was destroyed but it is pending!", the
    # BENCH_r05 _dispatch_loop spam) is recorded per stage and rolled
    # into the top-level `tail_clean` verdict
    destroyed = proc.stderr.count("Task was destroyed but it is pending")
    for candidate in reversed(proc.stdout.strip().splitlines()):
        candidate = candidate.strip()
        if candidate.startswith("{"):
            try:
                data = json.loads(candidate)
            except json.JSONDecodeError:
                break
            data["status"] = "ok"
            data["elapsed_s"] = round(time.monotonic() - t0, 1)
            data["destroyed_tasks"] = destroyed
            return data
    return {"status": f"no JSON from child (rc={proc.returncode})",
            "elapsed_s": round(time.monotonic() - t0, 1),
            "destroyed_tasks": destroyed,
            "stderr_tail": proc.stderr[-800:]}


def main() -> int:
    stages: dict[str, object] = {}
    # per-stage spans: the breakdown rides the output JSON as `trace`
    # and prints alongside the GB/s lines
    tracer.enable()

    # Stage 1: CPU baselines — hermetic, hang-proof by construction.
    cpu = run_stage("cpu", _hermetic_env(), _budget(CPU_TIMEOUT))
    stages["cpu"] = cpu

    # Stage 1b: in-situ cluster throughput (rados-bench analog) —
    # hermetic CPU, measures the framework end to end.
    cluster = run_stage("cluster", _hermetic_env(), _budget(240))
    stages["cluster"] = cluster

    # Stage 2: ONE long-warm device child — backend init and benches in
    # the same process so the (potentially minutes-long) axon warm is
    # never discarded. Falls back to hermetic cpu-jax only if the warmed
    # child itself dies or times out.
    device = run_stage("device", _tpu_env(), _budget(DEVICE_TIMEOUT))
    stages["device"] = device
    tpu_live = device.get("status") == "ok" and device.get("platform") == "tpu"
    if device.get("status") != "ok":
        fallback = run_stage("device", _hermetic_env(),
                             _budget(_deadline - time.monotonic()))
        stages["device_fallback"] = fallback
        if fallback.get("status") == "ok":
            device = fallback

    # Stage 3: cluster-EC-over-tpu — the in-situ data path on the device
    # plugin, offload-batched vs per-op inline dispatch (k=8,m=3). Tries
    # the real device first; falls back hermetic so the batching numbers
    # exist either way (platform is recorded inside the stage output).
    cluster_tpu = run_stage("cluster_tpu", _tpu_env(),
                            _budget(CLUSTER_TPU_TIMEOUT))
    stages["cluster_tpu"] = cluster_tpu
    if cluster_tpu.get("status") != "ok":
        fallback = run_stage("cluster_tpu", _hermetic_env(),
                             _budget(min(CLUSTER_TPU_TIMEOUT,
                                         _deadline - time.monotonic())))
        stages["cluster_tpu_fallback"] = fallback
        if fallback.get("status") == "ok":
            cluster_tpu = fallback

    # Stage 4: data-path attribution — the "where the 450x goes"
    # waterfall (queue-wait/copy/H2D/kernel/D2H/commit from real spans,
    # copy amplification, loop busy fraction, per-device utilization).
    # Hermetic: it profiles the FRAMEWORK's data path, and the loop/
    # copy numbers must not hinge on tunnel health.
    attribution = run_stage("attribution", _hermetic_env(),
                            _budget(ATTRIBUTION_TIMEOUT))
    stages["attribution"] = attribution

    # Stage 5: failure storm — kill m=3 of 11 OSDs under sustained EC
    # (clay k=8,m=3) client load, degraded reads served throughout,
    # revive, time-to-clean + recovery MB/s + backfill p99, then the
    # single-shard repair-bytes ratio vs the full-stripe baseline.
    # Hermetic: it measures degraded OPERATION, not codec speed.
    storm = run_stage("failure_storm", _hermetic_env(),
                      _budget(FAILURE_STORM_TIMEOUT))
    stages["failure_storm"] = storm

    # Stage 6: many-client swarm — >= 200 concurrent librados clients
    # (mixed sizes, zipfian hot keys, slow-reader overload) against an
    # EC pool with per-client SLO accounting armed: aggregate MB/s,
    # per-client p99 spread, fairness ratio (max/median p99), and the
    # client-observability pipeline verified live (ceph_client_*
    # scrape + SLO_VIOLATIONS fire/mute). Hermetic: it measures
    # multi-tenant FAIRNESS, not codec speed.
    swarm = run_stage("swarm", _hermetic_env(), _budget(SWARM_TIMEOUT))
    stages["swarm"] = swarm

    # Stage 6b: QoS storm — the dmclock scheduler graded A/B under a
    # 1000-client sharded swarm with three adversarial tenants and a
    # paced victim band: fairness ratio + victim p99 + goodput with
    # the arbiter ON vs the legacy WRR path, recovery progressing
    # through its reservation during the storm, and the overload/shed
    # admission-control phase (MOSDOpThrottle + flight crumbs +
    # per-tenant ceph_qos_* counters). Hermetic: it measures
    # arbitration, not codec speed.
    qos = run_stage("qos_storm", _hermetic_env(),
                    _budget(QOS_STORM_TIMEOUT))
    stages["qos_storm"] = qos

    # Stage 7: interlock qa sweep — seeded schedule exploration over a
    # pipelined EC cluster, explorer-only vs explorer+sanitizer
    # (generation guards, lockset recorder): seeds run, distinct
    # schedules explored, and the sanitizer-mode overhead % the trend
    # guard watches. Hermetic: it measures the qa tier's cost, not
    # codec speed.
    ilv = run_stage("interleave", _hermetic_env(),
                    _budget(INTERLEAVE_TIMEOUT))
    stages["interleave"] = ilv

    detail = {k: v for k, v in cpu.items()
              if k not in ("status", "elapsed_s", "stderr_tail")}
    detail.update({k: v for k, v in cluster.items()
                   if k not in ("status", "elapsed_s", "stderr_tail")})
    detail.update({k: v for k, v in cluster_tpu.items()
                   if k not in ("status", "elapsed_s", "stderr_tail",
                                "offload_status")})
    detail.update({k: v for k, v in attribution.items()
                   if k not in ("status", "elapsed_s", "stderr_tail",
                                "attribution")})
    detail.update({k: v for k, v in storm.items()
                   if k not in ("status", "elapsed_s", "stderr_tail")})
    detail.update({k: v for k, v in swarm.items()
                   if k not in ("status", "elapsed_s", "stderr_tail")})
    detail.update({k: v for k, v in qos.items()
                   if k not in ("status", "elapsed_s", "stderr_tail")})
    detail.update({k: v for k, v in ilv.items()
                   if k not in ("status", "elapsed_s", "stderr_tail")})
    detail.update({k: v for k, v in device.items()
                   if k not in ("status", "elapsed_s", "stderr_tail")})

    baseline = detail.get("cpu_native_encode") or 0.0
    baseline_name = "cpu_native_encode (C++ AVX2 split-table, isa stand-in)"
    if not baseline:
        baseline = detail.get("cpu_numpy_encode") or 0.0
        baseline_name = "cpu_numpy_encode (native codec unavailable)"

    value = detail.get("tpu_encode") or 0.0
    vs = round(value / baseline, 3) if baseline > 0 else 0.0
    out = {
        "metric": METRIC,
        "value": value,
        "unit": "GB/s",
        "vs_baseline": vs,
        # cluster observability snapshot (status, check codes,
        # per-daemon report ages) from the cluster stage's health probe
        "health": detail.pop("health", None),
        # the attribution waterfall: queue-wait/copy/H2D/kernel/D2H/
        # commit buckets from real spans, copy amplification, loop
        # busy fraction, per-device utilization
        "attribution": attribution.get("attribution"),
        "baseline": baseline_name,
        "platform": device.get("platform", "none"),
        "reactor_shards": REACTOR_SHARDS,
        "reactor_procs": REACTOR_PROCS,
        "detail": detail,
        "stages": {name: {k: s.get(k) for k in
                          ("status", "elapsed_s", "platform", "backend_init_s",
                           "destroyed_tasks", "stderr_tail")
                          if k in s}
                   for name, s in stages.items()},
        # no stage may leak pending event-loop tasks at teardown — the
        # assertion form of the BENCH_r05 "Task was destroyed" tail fix
        "tail_clean": all(s.get("destroyed_tasks", 0) == 0
                          for s in stages.values()),
    }
    if not out["tail_clean"]:
        leaky = {n: s["destroyed_tasks"] for n, s in stages.items()
                 if s.get("destroyed_tasks")}
        sys.stderr.write(f"bench tail NOT clean: destroyed pending "
                         f"tasks per stage: {leaky}\n")
    if not tpu_live:
        out["error"] = ("tpu backend did not come up inside the "
                        f"{DEVICE_TIMEOUT}s long-warm device child; device "
                        "numbers are the hermetic cpu-jax fallback")
    # bench trend guard: compare device codec GB/s against the newest
    # committed BENCH_r*.json so a silent slide (the r4->r5 35.2->31.96
    # encode drop) becomes a loud regression_pct the round it happens
    from ceph_tpu.tools.bench_driver import trend_guard
    trend = trend_guard(detail, out["platform"], REPO)
    if trend is not None:
        out["trend"] = trend
        out["regression_pct"] = trend.get("regression_pct", 0.0)
        if "warning" in trend:
            sys.stderr.write("bench trend: " + trend["warning"] + "\n")
    # per-stage wall-clock breakdown from the stage spans
    spans = [s for s in tracer.collector().spans()
             if s["name"].startswith("bench:")]
    out["trace"] = [{"stage": s["name"][len("bench:"):],
                     "seconds": round(s["duration_us"] / 1e6, 1),
                     "status": s["tags"].get("status"),
                     "platform": s["tags"].get("platform")}
                    for s in spans]
    sys.stderr.write("stage breakdown: " + " | ".join(
        f"{t['stage']} {t['seconds']}s ({t['status']})"
        for t in out["trace"]) + "\n")
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
