#!/usr/bin/env python3
"""Driver benchmark entry point: prints ONE JSON line
`{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}`.

Hang-proof by construction (VERDICT r1 #1): all JAX work happens in a child
process (`ceph_tpu.tools.bench_driver`) under a hard wall-clock timeout, so
a wedged backend init produces an error JSON line instead of a silent
rc=124. The child prints its JSON on stdout; this wrapper validates it and
re-emits exactly one line.

Environment knobs:
  CEPH_TPU_BENCH_TIMEOUT   seconds before the child is killed (default 1200)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
TIMEOUT = int(os.environ.get("CEPH_TPU_BENCH_TIMEOUT", "1200"))


def fail(reason: str, detail: str = "") -> None:
    print(json.dumps({
        "metric": "ec_encode_k8m3_1MiB_chunk",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
        "error": reason,
        "detail": detail[-2000:],
    }))


def main() -> int:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "ceph_tpu.tools.bench_driver"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=TIMEOUT)
    except subprocess.TimeoutExpired as e:
        fail(f"benchmark child timed out after {TIMEOUT}s",
             (e.stderr or b"").decode(errors="replace")
             if isinstance(e.stderr, bytes) else (e.stderr or ""))
        return 0
    except OSError as e:
        fail(f"could not launch benchmark child: {e}")
        return 0

    sys.stderr.write(proc.stderr)
    line = ""
    for candidate in reversed(proc.stdout.strip().splitlines()):
        candidate = candidate.strip()
        if candidate.startswith("{"):
            line = candidate
            break
    if not line:
        fail(f"child produced no JSON (rc={proc.returncode})",
             proc.stderr)
        return 0
    try:
        parsed = json.loads(line)
    except json.JSONDecodeError:
        fail("child JSON unparsable", line)
        return 0
    print(json.dumps(parsed))
    return 0


if __name__ == "__main__":
    sys.exit(main())
