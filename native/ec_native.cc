// Host-CPU GF(2^8) region codec + crc32c — the native runtime kernels.
//
// Role: the reference accelerates its erasure-code hot loop with vendored
// SIMD libraries (isa-l's ec_encode_data, reference
// src/erasure-code/isa/ErasureCodeIsa.cc:129; jerasure/gf-complete SSE
// region ops) and its checksums with runtime-dispatched crc32c kernels
// (reference src/common/crc32c.cc:17).  This file provides the same two
// capabilities for the TPU framework's host side, written from the standard
// published techniques (split-nibble PSHUFB multiply tables; CRC32C via the
// SSE4.2 instruction with a table-driven fallback) — no reference code.
//
// It is used as (a) the honest host-CPU baseline the TPU path is measured
// against, and (b) the host verify/fallback path when no accelerator is up.
//
// Exposed C ABI (consumed via ctypes from ceph_tpu.native):
//   gf256_encode(M, m, k, tables, data, out, n)   out = M @ data over GF(2^8)
//   gf256_region_xor(src, dst, n)                 dst ^= src
//   crc32c(crc, data, n) -> uint32_t              Castagnoli CRC
//   crc32c_blocks(data, nblocks, bs, seed, out)   per-block CRCs (Checksummer)
//   frame_pack(...)                               msgr2 frame codec: preamble
//   frame_verify_body(...)                        + segment crc in one call
//   ec_native_have_avx2() / ec_native_have_sse42()

#include <cstdint>
#include <cstddef>
#include <cstring>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// GF(2^8) region multiply-accumulate: dst ^= c * src
// Split-nibble tables: c*x == TLO[x & 15] ^ THI[x >> 4]  (linearity over GF2).
// `tab` points at 32 bytes: TLO[0..15] then THI[0..15] for this coefficient.
// ---------------------------------------------------------------------------

void mul_xor_scalar(const uint8_t* tab, const uint8_t* src, uint8_t* dst,
                    size_t n) {
  const uint8_t* tlo = tab;
  const uint8_t* thi = tab + 16;
  for (size_t i = 0; i < n; i++)
    dst[i] ^= (uint8_t)(tlo[src[i] & 15] ^ thi[src[i] >> 4]);
}

void xor_scalar(const uint8_t* src, uint8_t* dst, size_t n) {
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    uint64_t a, b;
    memcpy(&a, dst + i, 8);
    memcpy(&b, src + i, 8);
    a ^= b;
    memcpy(dst + i, &a, 8);
  }
  for (; i < n; i++) dst[i] ^= src[i];
}

#if defined(__x86_64__)
__attribute__((target("avx2")))
void mul_xor_avx2(const uint8_t* tab, const uint8_t* src, uint8_t* dst,
                  size_t n) {
  const __m256i lo =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)tab));
  const __m256i hi =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)(tab + 16)));
  const __m256i mask = _mm256_set1_epi8(0x0f);
  size_t i = 0;
  for (; i + 32 <= n; i += 32) {
    __m256i s = _mm256_loadu_si256((const __m256i*)(src + i));
    __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
    __m256i l = _mm256_shuffle_epi8(lo, _mm256_and_si256(s, mask));
    __m256i h = _mm256_shuffle_epi8(
        hi, _mm256_and_si256(_mm256_srli_epi16(s, 4), mask));
    _mm256_storeu_si256((__m256i*)(dst + i),
                        _mm256_xor_si256(d, _mm256_xor_si256(l, h)));
  }
  if (i < n) mul_xor_scalar(tab, src + i, dst + i, n - i);
}

__attribute__((target("avx2")))
void xor_avx2(const uint8_t* src, uint8_t* dst, size_t n) {
  size_t i = 0;
  for (; i + 32 <= n; i += 32) {
    __m256i s = _mm256_loadu_si256((const __m256i*)(src + i));
    __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
    _mm256_storeu_si256((__m256i*)(dst + i), _mm256_xor_si256(d, s));
  }
  if (i < n) xor_scalar(src + i, dst + i, n - i);
}

bool have_avx2() { return __builtin_cpu_supports("avx2"); }
bool have_sse42() { return __builtin_cpu_supports("sse4.2"); }
#else
bool have_avx2() { return false; }
bool have_sse42() { return false; }
#endif

void mul_xor(const uint8_t* tab, const uint8_t* src, uint8_t* dst, size_t n) {
#if defined(__x86_64__)
  if (have_avx2()) { mul_xor_avx2(tab, src, dst, n); return; }
#endif
  mul_xor_scalar(tab, src, dst, n);
}

void region_xor(const uint8_t* src, uint8_t* dst, size_t n) {
#if defined(__x86_64__)
  if (have_avx2()) { xor_avx2(src, dst, n); return; }
#endif
  xor_scalar(src, dst, n);
}

// ---------------------------------------------------------------------------
// crc32c (Castagnoli, poly 0x1EDC6F41 reflected = 0x82F63B78)
// ---------------------------------------------------------------------------

uint32_t crc32c_table[8][256];
bool crc32c_table_ready = false;

void crc32c_init_table() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int j = 0; j < 8; j++)
      c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : (c >> 1);
    crc32c_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = crc32c_table[0][i];
    for (int s = 1; s < 8; s++) {
      c = crc32c_table[0][c & 0xff] ^ (c >> 8);
      crc32c_table[s][i] = c;
    }
  }
  crc32c_table_ready = true;
}

uint32_t crc32c_sw(uint32_t crc, const uint8_t* data, size_t n) {
  if (!crc32c_table_ready) crc32c_init_table();
  // slice-by-8
  while (n >= 8) {
    uint64_t v;
    memcpy(&v, data, 8);
    v ^= crc;
    crc = crc32c_table[7][v & 0xff] ^
          crc32c_table[6][(v >> 8) & 0xff] ^
          crc32c_table[5][(v >> 16) & 0xff] ^
          crc32c_table[4][(v >> 24) & 0xff] ^
          crc32c_table[3][(v >> 32) & 0xff] ^
          crc32c_table[2][(v >> 40) & 0xff] ^
          crc32c_table[1][(v >> 48) & 0xff] ^
          crc32c_table[0][(v >> 56) & 0xff];
    data += 8;
    n -= 8;
  }
  while (n--) crc = crc32c_table[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
  return crc;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2")))
uint32_t crc32c_hw(uint32_t crc, const uint8_t* data, size_t n) {
  uint64_t c = crc;
  while (n >= 8) {
    uint64_t v;
    memcpy(&v, data, 8);
    c = _mm_crc32_u64(c, v);
    data += 8;
    n -= 8;
  }
  uint32_t c32 = (uint32_t)c;
  while (n--) c32 = _mm_crc32_u8(c32, *data++);
  return c32;
}
#endif

}  // namespace

extern "C" {

int ec_native_have_avx2() { return have_avx2() ? 1 : 0; }
int ec_native_have_sse42() { return have_sse42() ? 1 : 0; }

// out(m,n) = M(m,k) @ data(k,n) over GF(2^8); `tables` is the 256x32 split
// table block: tables[c*32 + v] = mul(c, v) for v<16, mul(c, (v-16)<<4) else.
void gf256_encode(const uint8_t* M, int m, int k, const uint8_t* tables,
                  const uint8_t* data, uint8_t* out, size_t n) {
  for (int i = 0; i < m; i++) {
    uint8_t* dst = out + (size_t)i * n;
    memset(dst, 0, n);
    for (int j = 0; j < k; j++) {
      uint8_t c = M[(size_t)i * k + j];
      if (c == 0) continue;
      const uint8_t* src = data + (size_t)j * n;
      if (c == 1)
        region_xor(src, dst, n);
      else
        mul_xor(tables + (size_t)c * 32, src, dst, n);
    }
  }
}

void gf256_region_xor(const uint8_t* src, uint8_t* dst, size_t n) {
  region_xor(src, dst, n);
}

uint32_t crc32c(uint32_t crc, const uint8_t* data, size_t n) {
#if defined(__x86_64__)
  if (have_sse42()) return crc32c_hw(crc, data, n);
#endif
  return crc32c_sw(crc, data, n);
}

// Per-block CRCs over a contiguous buffer of nblocks x block_size bytes —
// the Checksummer batch shape (reference src/common/Checksummer.h:195-234).
void crc32c_blocks(const uint8_t* data, size_t nblocks, size_t block_size,
                   uint32_t seed, uint32_t* out) {
  for (size_t b = 0; b < nblocks; b++)
    out[b] = crc32c(seed, data + b * block_size, block_size);
}

// ---------------------------------------------------------------------------
// msgr2 frame codec (the hot path of ceph_tpu/msg/frames.py): one C call
// builds the whole wire frame — little-endian preamble (magic u16, tag u8,
// seg_count u8, seg_len u32*, preamble crc u32) followed by each segment's
// bytes and its trailing crc32c — instead of 2+nseg ctypes round trips and a
// Python scatter loop per frame. Segments arrive as a FLATTENED part list
// (seg_parts[i] parts belong to segment i) so scatter-gather payloads (the
// sub-op batch envelope's concatenated message datas) pack without an
// intermediate join: each part is copied exactly once, straight into the
// wire blob, with the segment crc chained across its parts. Layout is
// bit-identical to the pure-Python path in frames.py, which stays the
// fallback when this library is unavailable.
// ---------------------------------------------------------------------------

static inline void put_u16le(uint8_t* p, uint16_t v) {
  p[0] = (uint8_t)v;
  p[1] = (uint8_t)(v >> 8);
}

static inline void put_u32le(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)v;
  p[1] = (uint8_t)(v >> 8);
  p[2] = (uint8_t)(v >> 16);
  p[3] = (uint8_t)(v >> 24);
}

// Pack one frame into `out` (caller sizes it: 4 + 4*nseg + 4 +
// sum(seg_len + 4)). Returns total bytes written.
uint64_t frame_pack(uint32_t magic, uint32_t tag, int nseg,
                    const uint64_t* seg_parts,       // parts per segment
                    const uint8_t* const* parts,     // flattened part ptrs
                    const uint64_t* part_lens,       // flattened part lens
                    uint8_t* out) {
  uint8_t* p = out;
  put_u16le(p, (uint16_t)magic);
  p[2] = (uint8_t)tag;
  p[3] = (uint8_t)nseg;
  p += 4;
  size_t part = 0;
  for (int s = 0; s < nseg; s++) {
    uint64_t len = 0;
    for (uint64_t j = 0; j < seg_parts[s]; j++)
      len += part_lens[part + j];
    part += seg_parts[s];
    put_u32le(p, (uint32_t)len);
    p += 4;
  }
  put_u32le(p, crc32c(0, out, (size_t)(p - out)));
  p += 4;
  part = 0;
  for (int s = 0; s < nseg; s++) {
    uint32_t crc = 0;
    for (uint64_t j = 0; j < seg_parts[s]; j++) {
      size_t n = (size_t)part_lens[part + j];
      if (n) {
        memcpy(p, parts[part + j], n);
        crc = crc32c(crc, p, n);
        p += n;
      }
    }
    part += seg_parts[s];
    put_u32le(p, crc);
    p += 4;
  }
  return (uint64_t)(p - out);
}

// Verify a frame body (nseg runs of [seg bytes | crc32c u32]) in one call.
// Returns -1 when every segment checks out, else the index of the first
// segment whose trailing crc mismatches. The caller has already validated
// the preamble (its crc covers the lengths used here).
int frame_verify_body(const uint8_t* body, const uint64_t* seg_lens,
                      int nseg) {
  const uint8_t* p = body;
  for (int s = 0; s < nseg; s++) {
    size_t n = (size_t)seg_lens[s];
    uint32_t want = (uint32_t)p[n] | ((uint32_t)p[n + 1] << 8) |
                    ((uint32_t)p[n + 2] << 16) | ((uint32_t)p[n + 3] << 24);
    if (crc32c(0, p, n) != want) return s;
    p += n + 4;
  }
  return -1;
}

}  // extern "C"
