"""OSDMap: epoch-versioned cluster map driving placement.

Re-creation of the reference's OSDMap essentials (src/osd/OSDMap.{h,cc}):
osd up/down + in/out states and reweights, pools (replicated or erasure,
pg_num, size/min_size, crush rule, EC profile name), and the placement
pipeline `pg_to_up_acting_osds` (:2923) = raw CRUSH mapping (:2670
`_pg_to_raw_osds`: x = stable_mod seed, crush.do_rule with the reweight
vector) + pg_temp overrides. Epochs advance through `Incremental` deltas
(`apply_incremental`) so daemons converge on identical maps from any
starting epoch; full-map encode/decode exists for bootstrap.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Iterable

from ceph_tpu.crush.crush import CRUSH_NONE, CrushMap


def stable_mod(x: int, b: int, bmask: int) -> int:
    """OSDMap::calc_pg_masks stable modulo: pgid -> [0, pg_num) staying
    stable as pg_num grows through powers of two (src/osd/osd_types.h)."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def _pg_seed(pool: int, ps: int) -> int:
    # placement seed fed to CRUSH; pool mixed in so pools diverge
    from ceph_tpu.crush.crush import _mix
    return _mix(0x2A, pool, ps) & 0x7FFFFFFF


@dataclasses.dataclass(frozen=True, order=True)
class PG:
    pool: int
    ps: int

    def __str__(self) -> str:
        return f"{self.pool}.{self.ps:x}"


@dataclasses.dataclass
class Pool:
    id: int
    name: str
    type: str = "replicated"          # replicated | erasure
    size: int = 3
    min_size: int = 2
    pg_num: int = 32
    crush_rule: int = 0
    ec_profile: str = ""
    stripe_width: int = 0
    # snapshots (pg_pool_t snap_seq/snaps/removed_snaps): snap ids are
    # allocated from snap_seq; pool_snaps names the pool-level ones
    # (str keys: the record round-trips through JSON); removed ids are
    # what OSD snaptrim consumes
    snap_seq: int = 0
    pool_snaps: dict = dataclasses.field(default_factory=dict)
    removed_snaps: list = dataclasses.field(default_factory=list)

    def pg_mask(self) -> int:
        return (1 << (self.pg_num - 1).bit_length()) - 1 if self.pg_num else 0

    def raw_pg_to_pg(self, ps: int) -> int:
        return stable_mod(ps, self.pg_num, self.pg_mask())


@dataclasses.dataclass
class OsdState:
    up: bool = False
    in_cluster: bool = True
    weight: float = 1.0               # reweight in [0,1]
    addr: str = ""


@dataclasses.dataclass
class Incremental:
    """Delta between OSDMap epoch-1 and epoch (OSDMap::Incremental,
    src/osd/OSDMap.h): daemons at any older epoch apply the chain of
    incrementals the monitor publishes and converge on an identical map
    without refetching the full map each time.

    Fields left at their sentinel are "no change". new_pools carries full
    Pool records (pool mutations are rare and small); new_pg_temp maps a
    PG to its override list, [] meaning "erase the override".
    """
    epoch: int = 0                               # the epoch this produces
    new_up: dict[int, str] = dataclasses.field(default_factory=dict)
    # osd -> addr of the newly-up daemon
    new_down: list[int] = dataclasses.field(default_factory=list)
    new_in: list[int] = dataclasses.field(default_factory=list)
    new_out: list[int] = dataclasses.field(default_factory=list)
    new_weights: dict[int, float] = dataclasses.field(default_factory=dict)
    new_osds: dict[int, str] = dataclasses.field(default_factory=dict)
    new_pools: dict[int, Pool] = dataclasses.field(default_factory=dict)
    new_pg_temp: dict[PG, list[int]] = dataclasses.field(default_factory=dict)
    # full crush dump when the hierarchy changed (the reference also ships
    # a whole crush blob in Incremental::crush, OSDMap.h) and new/updated
    # EC profiles (profiles are cluster state living in the OSDMap)
    new_crush: dict | None = None
    new_ec_profiles: dict[str, dict] = dataclasses.field(default_factory=dict)

    def empty(self) -> bool:
        return not (self.new_up or self.new_down or self.new_in
                    or self.new_out or self.new_weights or self.new_osds
                    or self.new_pools or self.new_pg_temp
                    or self.new_crush or self.new_ec_profiles)

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "new_up": {str(o): a for o, a in self.new_up.items()},
            "new_down": self.new_down,
            "new_in": self.new_in,
            "new_out": self.new_out,
            "new_weights": {str(o): w for o, w in self.new_weights.items()},
            "new_osds": {str(o): a for o, a in self.new_osds.items()},
            "new_pools": {str(p): dataclasses.asdict(pool)
                          for p, pool in self.new_pools.items()},
            "new_pg_temp": {str(pg): osds
                            for pg, osds in self.new_pg_temp.items()},
            "new_crush": self.new_crush,
            "new_ec_profiles": self.new_ec_profiles,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Incremental":
        inc = cls(epoch=d["epoch"])
        inc.new_up = {int(o): a for o, a in d.get("new_up", {}).items()}
        inc.new_down = list(d.get("new_down", []))
        inc.new_in = list(d.get("new_in", []))
        inc.new_out = list(d.get("new_out", []))
        inc.new_weights = {int(o): w
                           for o, w in d.get("new_weights", {}).items()}
        inc.new_osds = {int(o): a for o, a in d.get("new_osds", {}).items()}
        inc.new_pools = {int(p): Pool(**pool)
                         for p, pool in d.get("new_pools", {}).items()}
        for key, osds in d.get("new_pg_temp", {}).items():
            pool_s, ps_s = key.split(".")
            inc.new_pg_temp[PG(int(pool_s), int(ps_s, 16))] = list(osds)
        inc.new_crush = d.get("new_crush")
        inc.new_ec_profiles = dict(d.get("new_ec_profiles", {}))
        return inc


class OSDMap:
    def __init__(self, crush: CrushMap | None = None):
        self.epoch = 0
        self.crush = crush or CrushMap()
        self.osds: dict[int, OsdState] = {}
        self.pools: dict[int, Pool] = {}
        self.pool_names: dict[str, int] = {}
        self.pg_temp: dict[PG, list[int]] = {}
        self.ec_profiles: dict[str, dict] = {}
        # raw-placement memo: the full straw2 walk per op showed up as
        # ~7% of a busy OSD loop (every client submit and every sub-op
        # handler recomputes its PG's mapping). Raw placement depends
        # only on the crush map + pool defs + weight vector — all of
        # which change with the epoch or through the explicit mutators
        # below, each of which drops the memo. Up/down state is NOT
        # part of raw placement (pg_to_up_acting filters it per call),
        # so mark-downs stay visible instantly with a warm memo.
        self._raw_memo: dict[PG, list[int]] = {}
        self._raw_memo_epoch = -1

    def _placement_changed(self) -> None:
        """Drop the raw-placement memo (weights/pools/crush mutated)."""
        self._raw_memo.clear()
        self._raw_memo_epoch = self.epoch

    # -- membership ----------------------------------------------------------

    def add_osd(self, osd: int, addr: str = "") -> None:
        self.osds[osd] = OsdState(addr=addr)
        self._placement_changed()

    def set_up(self, osd: int, up: bool, addr: str | None = None) -> None:
        state = self.osds[osd]
        state.up = up
        if addr is not None:
            state.addr = addr

    def set_in(self, osd: int, in_cluster: bool) -> None:
        self.osds[osd].in_cluster = in_cluster
        self._placement_changed()

    def reweight(self, osd: int, weight: float) -> None:
        self.osds[osd].weight = max(0.0, min(1.0, weight))
        self._placement_changed()

    def is_up(self, osd: int) -> bool:
        return osd in self.osds and self.osds[osd].up

    def get_addr(self, osd: int) -> str:
        return self.osds[osd].addr

    # -- pools ---------------------------------------------------------------

    def create_pool(self, name: str, **kwargs) -> Pool:
        if name in self.pool_names:
            raise ValueError(f"pool {name!r} exists")
        pid = max(self.pools, default=0) + 1
        pool = Pool(id=pid, name=name, **kwargs)
        self.pools[pid] = pool
        self.pool_names[name] = pid
        self._placement_changed()
        return pool

    def get_pool(self, ref: int | str) -> Pool:
        pid = self.pool_names[ref] if isinstance(ref, str) else ref
        return self.pools[pid]

    # -- placement -----------------------------------------------------------

    def object_to_pg(self, pool_ref: int | str, name: str) -> PG:
        from ceph_tpu.crush.crush import _mix
        pool = self.get_pool(pool_ref)
        raw_ps = _mix(0x5F, *name.encode()) & 0x7FFFFFFF
        return PG(pool.id, pool.raw_pg_to_pg(raw_ps))

    def _weights(self) -> dict[int, float]:
        """CRUSH weight vector: out or missing osds weigh 0."""
        return {osd: (s.weight if s.in_cluster else 0.0)
                for osd, s in self.osds.items()}

    def pg_to_raw_osds(self, pg: PG) -> list[int]:
        if self._raw_memo_epoch != self.epoch:
            # epoch moved (incrementals, load_dict, mon commits): any
            # of crush/pools/weights may have changed with it
            self._raw_memo.clear()
            self._raw_memo_epoch = self.epoch
        raw = self._raw_memo.get(pg)
        if raw is None:
            pool = self.pools[pg.pool]
            x = _pg_seed(pg.pool, pg.ps)
            raw = self._raw_memo[pg] = self.crush.do_rule(
                pool.crush_rule, x, pool.size, self._weights())
        return raw

    def pg_to_up_acting_osds(self, pg: PG) -> tuple[list[int], list[int]]:
        """(up, acting): raw mapping with down osds removed (holes stay for
        EC pools), then pg_temp overrides acting (OSDMap.cc:2923)."""
        pool = self.pools[pg.pool]
        raw = self.pg_to_raw_osds(pg)
        if pool.type == "erasure":
            up = [o if o != CRUSH_NONE and self.is_up(o) else CRUSH_NONE
                  for o in raw]
        else:
            up = [o for o in raw if o != CRUSH_NONE and self.is_up(o)]
        acting = self.pg_temp.get(pg, up)
        return up, acting

    def primary(self, pg: PG) -> int:
        _, acting = self.pg_to_up_acting_osds(pg)
        for osd in acting:
            if osd != CRUSH_NONE:
                return osd
        return CRUSH_NONE

    # -- epochs --------------------------------------------------------------

    def inc_epoch(self) -> int:
        self.epoch += 1
        return self.epoch

    def apply_incremental(self, inc: Incremental) -> None:
        """Advance this map by one epoch delta (OSDMap::apply_incremental,
        src/osd/OSDMap.cc). Raises if the delta isn't for epoch+1 —
        callers must fetch intervening incrementals (or a full map) first.
        """
        if inc.epoch != self.epoch + 1:
            raise ValueError(
                f"incremental for epoch {inc.epoch} cannot apply to "
                f"map at epoch {self.epoch}")
        for osd, addr in inc.new_osds.items():
            if osd not in self.osds:
                self.add_osd(osd, addr=addr)
        for osd, addr in inc.new_up.items():
            self.set_up(osd, True, addr=addr)
        for osd in inc.new_down:
            self.set_up(osd, False)
        for osd in inc.new_in:
            self.set_in(osd, True)
        for osd in inc.new_out:
            self.set_in(osd, False)
        for osd, w in inc.new_weights.items():
            self.reweight(osd, w)
        if inc.new_pools:
            self.pools.update(inc.new_pools)
            # rebuild rather than insert: a renamed pool must drop its old
            # name or incremental-appliers diverge from full-map bootstrap
            self.pool_names = {pool.name: pid
                               for pid, pool in self.pools.items()}
        for pg, osds in inc.new_pg_temp.items():
            if osds:
                self.pg_temp[pg] = list(osds)
            else:
                self.pg_temp.pop(pg, None)
        if inc.new_crush is not None:
            self.crush = CrushMap.from_dict(inc.new_crush)
        self.ec_profiles.update(inc.new_ec_profiles)
        self.epoch = inc.epoch

    # -- encode/decode (wire form for map distribution) ----------------------

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "osds": {str(o): dataclasses.asdict(s)
                     for o, s in self.osds.items()},
            "pools": {str(p): dataclasses.asdict(pool)
                      for p, pool in self.pools.items()},
            "pg_temp": {str(pg): osds for pg, osds in self.pg_temp.items()},
            "crush": self.crush.to_dict(),
            "ec_profiles": self.ec_profiles,
        }

    def dumps(self) -> bytes:
        return json.dumps(self.to_dict(), sort_keys=True).encode()

    def load_dict(self, d: dict) -> None:
        self.epoch = d["epoch"]
        self.osds = {int(o): OsdState(**s) for o, s in d["osds"].items()}
        self.pools = {int(p): Pool(**pool) for p, pool in d["pools"].items()}
        self.pool_names = {pool.name: pid for pid, pool in self.pools.items()}
        self.pg_temp = {}
        for key, osds in d.get("pg_temp", {}).items():
            pool_s, ps_s = key.split(".")
            self.pg_temp[PG(int(pool_s), int(ps_s, 16))] = osds
        if d.get("crush") is not None:
            self.crush = CrushMap.from_dict(d["crush"])
        self.ec_profiles = dict(d.get("ec_profiles", {}))


def apply_map_payload(osdmap: "OSDMap", payload: dict) -> bool:
    """Apply a mon osdmap-subscription payload (full map and/or
    incremental chain) to `osdmap` in place; returns True if the epoch
    advanced. Shared by every map consumer (client/mgr/...) so the
    update protocol lives in ONE place."""
    import json as _json
    before = osdmap.epoch
    full = payload.get("full")
    if full is not None and full["epoch"] > osdmap.epoch:
        osdmap.load_dict(full)
    for raw in payload.get("incrementals", []):
        inc = Incremental.from_dict(
            _json.loads(raw) if isinstance(raw, str) else raw)
        if inc.epoch == osdmap.epoch + 1:
            osdmap.apply_incremental(inc)
    return osdmap.epoch > before
