"""CRUSH placement (straw2 buckets + rule engine) and OSDMap."""
from ceph_tpu.crush.crush import CrushMap, Bucket, Rule, Step, CRUSH_NONE
from ceph_tpu.crush.osdmap import OSDMap, Pool, PG, Incremental

__all__ = ["CrushMap", "Bucket", "Rule", "Step", "CRUSH_NONE",
           "OSDMap", "Pool", "PG", "Incremental"]
