"""CRUSH: deterministic pseudo-random placement on a weighted hierarchy.

Re-creation of the reference's CRUSH core (src/crush/mapper.c): straw2
bucket selection (`bucket_straw2_choose`, mapper.c:342 — each item draws
ln(hash)/weight and the max wins, giving weight-proportional, minimally-
disruptive placement) and the rule engine (`crush_do_rule`, take →
choose/chooseleaf {firstn|indep} → emit, with collision/failure retries
and R'-style replacement for indep). Device health enters through a
weight vector (reweights, 0 = out) exactly like the reference's
crush_do_rule weight argument.

Deliberate divergence: the hash is a splitmix64-based mix rather than
rjenkins1, and straw2 uses float ln rather than the fixed-point log table
— placements are deterministic and stable across runs/platforms but not
byte-identical to a real ceph cluster's.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Iterable

CRUSH_NONE = -0x7FFFFFFF  # CRUSH_ITEM_NONE: an unfilled (hole) slot

DEVICE = 0  # bucket type id 0 = device (osd)


def _mix(*values: int) -> int:
    """Deterministic 64-bit mix (splitmix64 finalizer over the args)."""
    h = 0x9E3779B97F4A7C15
    for v in values:
        h ^= (v & 0xFFFFFFFFFFFFFFFF) + 0x9E3779B97F4A7C15 + (h << 6) + (h >> 2)
        h &= 0xFFFFFFFFFFFFFFFF
        h ^= h >> 30
        h = (h * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        h ^= h >> 27
        h = (h * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        h ^= h >> 31
    return h


def _straw2_draw(x: int, item: int, r: int, weight: float) -> float:
    """ln(u)/w draw — the straw2 race (mapper.c:342 semantics)."""
    if weight <= 0:
        return -math.inf
    u = (_mix(x, item, r) & 0xFFFFFFFFFFFF) / float(1 << 48)
    u = max(u, 1e-18)
    return math.log(u) / weight


@dataclasses.dataclass
class Bucket:
    id: int                      # negative for buckets, >= 0 for devices
    type: int                    # 0=device, 1=host, 2=rack, ... (type ids)
    name: str
    items: list[int] = dataclasses.field(default_factory=list)
    weights: list[float] = dataclasses.field(default_factory=list)

    def weight(self) -> float:
        return sum(self.weights)


@dataclasses.dataclass
class Step:
    op: str                      # take | choose | chooseleaf | emit
    num: int = 0                 # replicas to pick (0 = pool size)
    type: int = 0                # bucket type to descend to
    mode: str = "firstn"         # firstn | indep
    arg: str = ""                # take target name


@dataclasses.dataclass
class Rule:
    id: int
    name: str
    steps: list[Step]


class CrushMap:
    def __init__(self):
        self._buckets: dict[int, Bucket] = {}
        self._names: dict[str, int] = {}
        self._rules: dict[int, Rule] = {}
        self._type_names: dict[int, str] = {0: "osd", 1: "host", 2: "rack",
                                            3: "row", 10: "root"}
        self._next_bucket_id = -1
        self.tries = 50          # choose_total_tries

    # -- building ------------------------------------------------------------

    def add_bucket(self, type: int, name: str) -> int:
        if name in self._names:
            raise ValueError(f"bucket {name!r} exists")
        bid = self._next_bucket_id
        self._next_bucket_id -= 1
        self._buckets[bid] = Bucket(bid, type, name)
        self._names[name] = bid
        return bid

    def add_item(self, parent: int | str, item: int, weight: float,
                 name: str | None = None) -> None:
        """Add a device or bucket under `parent` with the given weight."""
        bucket = self._bucket(parent)
        if item in bucket.items:
            raise ValueError(f"item {item} already in {bucket.name}")
        bucket.items.append(item)
        bucket.weights.append(weight)
        if name is not None:
            self._names[name] = item

    def reweight_item(self, parent: int | str, item: int,
                      weight: float) -> None:
        bucket = self._bucket(parent)
        idx = bucket.items.index(item)
        bucket.weights[idx] = weight

    def _bucket(self, ref: int | str) -> Bucket:
        bid = self._names[ref] if isinstance(ref, str) else ref
        return self._buckets[bid]

    def bucket_of(self, ref: int | str) -> Bucket:
        return self._bucket(ref)

    # -- rules ---------------------------------------------------------------

    def add_rule(self, rule: Rule) -> None:
        if rule.id in self._rules:
            raise ValueError(f"rule {rule.id} exists")
        self._rules[rule.id] = rule

    def make_simple_rule(self, rule_id: int, name: str, root: str,
                         failure_domain_type: int,
                         mode: str = "firstn") -> Rule:
        """replicated/EC default rule: take root; chooseleaf n of domain;
        emit (CrushWrapper::add_simple_rule / ErasureCode::create_rule —
        EC uses mode='indep')."""
        rule = Rule(rule_id, name, [
            Step("take", arg=root),
            Step("chooseleaf", num=0, type=failure_domain_type, mode=mode),
            Step("emit"),
        ])
        self.add_rule(rule)
        return rule

    # -- mapping -------------------------------------------------------------

    def _choose_one(self, bucket: Bucket, x: int, r: int,
                    weights: dict[int, float]) -> int:
        """straw2 winner among bucket items for replica rank r."""
        best, best_draw = CRUSH_NONE, -math.inf
        for item, w in zip(bucket.items, bucket.weights):
            if item >= 0:
                w *= weights.get(item, 1.0)  # reweight/out factor
            draw = _straw2_draw(x, item, r, w)
            if draw > best_draw:
                best, best_draw = item, draw
        return best

    def _descend(self, start: int, x: int, r: int, target_type: int,
                 weights: dict[int, float]) -> int:
        """Walk from `start` down to an item of target_type via straw2."""
        node = start
        for _ in range(32):
            if target_type == DEVICE:
                if node >= 0:
                    return node
            bucket = self._buckets.get(node)
            if bucket is None:
                return CRUSH_NONE
            if bucket.type == target_type:
                return node
            node = self._choose_one(bucket, x, r, weights)
            if node == CRUSH_NONE:
                return CRUSH_NONE
            if node >= 0 and target_type != DEVICE:
                return CRUSH_NONE  # hit a device before the target type
        return CRUSH_NONE

    def _leaf_under(self, node: int, x: int, r: int,
                    weights: dict[int, float]) -> int:
        return self._descend(node, x, r, DEVICE, weights)

    def do_rule(self, rule_id: int, x: int, num_rep: int,
                weights: dict[int, float] | None = None) -> list[int]:
        """Map input x to an ordered list of devices (crush_do_rule).

        firstn: failures are skipped (result may be short).
        indep: failures leave CRUSH_NONE holes at their rank — EC shard
        ranks are positional (mapper.c indep semantics).
        """
        weights = weights or {}
        rule = self._rules[rule_id]
        working: list[int] = []
        out: list[int] = []
        for step in rule.steps:
            if step.op == "take":
                working = [self._names[step.arg]]
            elif step.op in ("choose", "chooseleaf"):
                n = step.num if step.num > 0 else num_rep
                chosen: list[int] = []
                for parent in working:
                    chosen.extend(self._choose_n(
                        parent, x, n, step, weights))
                working = chosen
            elif step.op == "emit":
                out.extend(working)
                working = []
            else:
                raise ValueError(f"unknown step op {step.op!r}")
        return out[:num_rep] if rule.steps[-1].op == "emit" else out

    def _choose_n(self, parent: int, x: int, n: int, step: Step,
                  weights: dict[int, float]) -> list[int]:
        firstn = step.mode == "firstn"
        result: list[int] = []
        seen: set[int] = set()
        for rank in range(n):
            placed = CRUSH_NONE
            for attempt in range(self.tries):
                r = rank + attempt * n  # r' sequence: distinct draws per retry
                node = self._descend(parent, x, r, step.type, weights)
                if node == CRUSH_NONE:
                    continue
                if step.op == "chooseleaf":
                    leaf = self._leaf_under(node, x, r, weights)
                    if leaf == CRUSH_NONE or leaf in seen:
                        continue
                    if weights.get(leaf, 1.0) <= 0:
                        continue
                    placed = leaf
                    break
                if node in seen:
                    continue
                if node >= 0 and weights.get(node, 1.0) <= 0:
                    continue
                placed = node
                break
            if placed != CRUSH_NONE:
                seen.add(placed)
                result.append(placed)
            elif not firstn:
                result.append(CRUSH_NONE)  # indep keeps the hole at rank
        return result
