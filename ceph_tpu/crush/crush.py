"""CRUSH: deterministic pseudo-random placement on a weighted hierarchy.

Re-creation of the reference's CRUSH core (src/crush/mapper.c): straw2
bucket selection (`bucket_straw2_choose`, mapper.c:342 — each item draws
ln(hash)/weight and the max wins, giving weight-proportional, minimally-
disruptive placement) and the rule engine (`crush_do_rule`, take →
choose/chooseleaf {firstn|indep} → emit, with collision/failure retries
and R'-style replacement for indep). Device health enters through a
weight vector (reweights, 0 = out) exactly like the reference's
crush_do_rule weight argument.

Deliberate divergence: the hash is a splitmix64-based mix rather than
rjenkins1, and straw2 uses float ln rather than the fixed-point log table
— placements are deterministic and stable across runs/platforms but not
byte-identical to a real ceph cluster's.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Iterable

CRUSH_NONE = -0x7FFFFFFF  # CRUSH_ITEM_NONE: an unfilled (hole) slot

DEVICE = 0  # bucket type id 0 = device (osd)


def _mix(*values: int) -> int:
    """Deterministic 64-bit mix (splitmix64 finalizer over the args)."""
    h = 0x9E3779B97F4A7C15
    for v in values:
        h ^= (v & 0xFFFFFFFFFFFFFFFF) + 0x9E3779B97F4A7C15 + (h << 6) + (h >> 2)
        h &= 0xFFFFFFFFFFFFFFFF
        h ^= h >> 30
        h = (h * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        h ^= h >> 27
        h = (h * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        h ^= h >> 31
    return h


def _straw2_draw(x: int, item: int, r: int, weight: float) -> float:
    """ln(u)/w draw — the straw2 race (mapper.c:342 semantics)."""
    if weight <= 0:
        return -math.inf
    u = (_mix(x, item, r) & 0xFFFFFFFFFFFF) / float(1 << 48)
    u = max(u, 1e-18)
    return math.log(u) / weight


@dataclasses.dataclass
class Bucket:
    id: int                      # negative for buckets, >= 0 for devices
    type: int                    # 0=device, 1=host, 2=rack, ... (type ids)
    name: str
    items: list[int] = dataclasses.field(default_factory=list)
    weights: list[float] = dataclasses.field(default_factory=list)

    def weight(self) -> float:
        return sum(self.weights)


@dataclasses.dataclass
class Step:
    op: str                      # take | choose | chooseleaf | emit
    num: int = 0                 # replicas to pick (0 = pool size)
    type: int = 0                # bucket type to descend to
    mode: str = "firstn"         # firstn | indep
    arg: str = ""                # take target name


@dataclasses.dataclass
class Rule:
    id: int
    name: str
    steps: list[Step]


class CrushMap:
    def __init__(self):
        self._buckets: dict[int, Bucket] = {}
        self._names: dict[str, int] = {}
        self._rules: dict[int, Rule] = {}
        self._type_names: dict[int, str] = {0: "osd", 1: "host", 2: "rack",
                                            3: "row", 10: "root"}
        self._next_bucket_id = -1
        self._domain_counts: dict[tuple[int, int], int] = {}
        self.tries = 50          # choose_total_tries
        # firstn only: when live failure domains are exhausted, place the
        # remaining replicas on already-used domains (never reusing a
        # device) instead of returning a short result like mapper.c does.
        # Keeps replica count at the cost of domain separation in the
        # degraded case; set False for strict reference semantics, where a
        # short result is what signals degraded placement to the caller.
        self.relax_firstn_on_exhaustion = True

    # -- building ------------------------------------------------------------

    def add_bucket(self, type: int, name: str) -> int:
        if name in self._names:
            raise ValueError(f"bucket {name!r} exists")
        bid = self._next_bucket_id
        self._next_bucket_id -= 1
        self._buckets[bid] = Bucket(bid, type, name)
        self._names[name] = bid
        return bid

    def add_item(self, parent: int | str, item: int, weight: float,
                 name: str | None = None) -> None:
        """Add a device or bucket under `parent` with the given weight."""
        bucket = self._bucket(parent)
        if item in bucket.items:
            raise ValueError(f"item {item} already in {bucket.name}")
        bucket.items.append(item)
        bucket.weights.append(weight)
        self._domain_counts.clear()
        if name is not None:
            self._names[name] = item

    def reweight_item(self, parent: int | str, item: int,
                      weight: float) -> None:
        bucket = self._bucket(parent)
        idx = bucket.items.index(item)
        bucket.weights[idx] = weight

    def _bucket(self, ref: int | str) -> Bucket:
        bid = self._names[ref] if isinstance(ref, str) else ref
        return self._buckets[bid]

    def bucket_of(self, ref: int | str) -> Bucket:
        return self._bucket(ref)

    # -- rules ---------------------------------------------------------------

    def add_rule(self, rule: Rule) -> None:
        if rule.id in self._rules:
            raise ValueError(f"rule {rule.id} exists")
        self._rules[rule.id] = rule

    def make_simple_rule(self, rule_id: int, name: str, root: str,
                         failure_domain_type: int,
                         mode: str = "firstn") -> Rule:
        """replicated/EC default rule: take root; chooseleaf n of domain;
        emit (CrushWrapper::add_simple_rule / ErasureCode::create_rule —
        EC uses mode='indep')."""
        rule = Rule(rule_id, name, [
            Step("take", arg=root),
            Step("chooseleaf", num=0, type=failure_domain_type, mode=mode),
            Step("emit"),
        ])
        self.add_rule(rule)
        return rule

    # -- mapping -------------------------------------------------------------

    def _choose_one(self, bucket: Bucket, x: int, r: int,
                    weights: dict[int, float]) -> int:
        """straw2 winner among bucket items for replica rank r."""
        best, best_draw = CRUSH_NONE, -math.inf
        for item, w in zip(bucket.items, bucket.weights):
            if item >= 0:
                w *= weights.get(item, 1.0)  # reweight/out factor
            draw = _straw2_draw(x, item, r, w)
            if draw > best_draw:
                best, best_draw = item, draw
        return best

    def _descend(self, start: int, x: int, r: int, target_type: int,
                 weights: dict[int, float]) -> int:
        """Walk from `start` down to an item of target_type via straw2."""
        node = start
        for _ in range(32):
            if target_type == DEVICE:
                if node >= 0:
                    return node
            bucket = self._buckets.get(node)
            if bucket is None:
                return CRUSH_NONE
            if bucket.type == target_type:
                return node
            node = self._choose_one(bucket, x, r, weights)
            if node == CRUSH_NONE:
                return CRUSH_NONE
            if node >= 0 and target_type != DEVICE:
                return CRUSH_NONE  # hit a device before the target type
        return CRUSH_NONE

    def _leaf_under(self, node: int, x: int, r: int,
                    weights: dict[int, float]) -> int:
        return self._descend(node, x, r, DEVICE, weights)

    def do_rule(self, rule_id: int, x: int, num_rep: int,
                weights: dict[int, float] | None = None) -> list[int]:
        """Map input x to an ordered list of devices (crush_do_rule).

        firstn: failures are skipped (result may be short).
        indep: failures leave CRUSH_NONE holes at their rank — EC shard
        ranks are positional (mapper.c indep semantics).
        """
        weights = weights or {}
        rule = self._rules[rule_id]
        working: list[int] = []
        out: list[int] = []
        for step in rule.steps:
            if step.op == "take":
                working = [self._names[step.arg]]
            elif step.op in ("choose", "chooseleaf"):
                n = step.num if step.num > 0 else num_rep
                chosen: list[int] = []
                for parent in working:
                    chosen.extend(self._choose_n(
                        parent, x, n, step, weights))
                working = chosen
            elif step.op == "emit":
                out.extend(working)
                working = []
            else:
                raise ValueError(f"unknown step op {step.op!r}")
        return out[:num_rep] if rule.steps[-1].op == "emit" else out

    # -- wire form (ships inside OSDMap; crushtool-style dump) ---------------

    def to_dict(self) -> dict:
        return {
            "buckets": [dataclasses.asdict(b) for b in
                        sorted(self._buckets.values(), key=lambda b: -b.id)],
            "rules": [dataclasses.asdict(r) for r in
                      sorted(self._rules.values(), key=lambda r: r.id)],
            "names": {name: ref for name, ref in self._names.items()},
            "type_names": {str(t): n for t, n in self._type_names.items()},
            "tries": self.tries,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CrushMap":
        cm = cls()
        for bd in d["buckets"]:
            b = Bucket(**bd)
            cm._buckets[b.id] = b
            cm._next_bucket_id = min(cm._next_bucket_id, b.id - 1)
        for rd in d["rules"]:
            rd = dict(rd)   # never mutate the caller's dict: it may be a
            # stored incremental that other appliers will replay
            steps = [Step(**s) for s in rd.pop("steps")]
            cm._rules[rd["id"]] = Rule(steps=steps, **rd)
        cm._names = dict(d["names"])
        cm._type_names = {int(t): n for t, n in d["type_names"].items()}
        cm.tries = d.get("tries", 50)
        return cm

    def _choose_n(self, parent: int, x: int, n: int, step: Step,
                  weights: dict[int, float]) -> list[int]:
        """Pick n items of step.type under parent (crush_choose_{firstn,indep}).

        Two-phase design (deliberate divergence from mapper.c that makes
        indep's positional stability a *guarantee* rather than best-effort):

        Phase A assigns each rank a failure-domain bucket using draws that
        do not look at leaf liveness (bucket straw2 weights are static, and
        `_choose_one` only applies the live-weight vector to devices), with
        domain-level collision checks — mapper.c rejects `out[i] == item`
        at the bucket level too, which is what keeps two replicas off one
        host.  Because these draws ignore device deaths, the assignment is
        bit-identical between a healthy and a degraded run.

        Phase B picks a live leaf under each assigned domain.  A domain
        whose leaves are all dead leaves its rank unfilled — without
        disturbing any other rank, since assignments were already fixed.

        Phase C (repair) retries unfilled ranks attempt-major over domains
        nobody claimed.  In mapper.c the retrying rank re-draws from the
        full pool and can steal a domain that a later surviving rank would
        have kept (observable rank churn under host death); here survivors
        are immovable by construction.

        firstn additionally relaxes domain distinctness once domains are
        exhausted (phase D) so the replica count is met — the reference
        instead returns a short result; we prefer keeping redundancy and
        document the divergence.  indep never relaxes: failed ranks keep
        their CRUSH_NONE hole so EC shard ids stay positional.
        """
        indep = step.mode == "indep"
        domains = [CRUSH_NONE] * n   # assigned failure-domain node per rank
        leaves = [CRUSH_NONE] * n
        claimed: set[int] = set()
        used_leaves: set[int] = set()

        def draw_domain(rank: int, t: int, allow_claimed: bool = False) -> int:
            node = self._descend(parent, x, rank + t * n, step.type, weights)
            if node == CRUSH_NONE or (node in claimed and not allow_claimed):
                return CRUSH_NONE
            if node >= 0 and weights.get(node, 1.0) <= 0:
                return CRUSH_NONE
            return node

        def pick_leaf(rank: int, node: int) -> int:
            if node >= 0:   # domain is a device (choose/chooseleaf type 0)
                return node if node not in used_leaves else CRUSH_NONE
            if step.op == "choose":
                # intermediate bucket: the result IS the bucket; later rule
                # steps descend further (crush_choose without recurse_to_leaf)
                return node if node not in used_leaves else CRUSH_NONE
            for t in range(self.tries):
                leaf = self._leaf_under(node, x, rank + t * n, weights)
                if (leaf != CRUSH_NONE and leaf not in used_leaves
                        and weights.get(leaf, 1.0) > 0):
                    return leaf
            return CRUSH_NONE

        # Phase A: domain assignment. indep is attempt-major (a rank that
        # can place at pass t does so before any rank's pass-t+1 retry);
        # firstn is rank-major like crush_choose_firstn.
        if indep:
            for t in range(self.tries):
                unfilled = [i for i in range(n) if domains[i] == CRUSH_NONE]
                if not unfilled:
                    break
                for rank in unfilled:
                    node = draw_domain(rank, t)
                    if node != CRUSH_NONE:
                        domains[rank] = node
                        claimed.add(node)
        else:
            for rank in range(n):
                for t in range(self.tries):
                    node = draw_domain(rank, t)
                    if node != CRUSH_NONE:
                        domains[rank] = node
                        claimed.add(node)
                        break

        # Phase B: leaf under each assigned domain. Dead domains stay
        # claimed so repair draws don't waste tries re-visiting them.
        for rank in range(n):
            if domains[rank] == CRUSH_NONE:
                continue
            leaf = pick_leaf(rank, domains[rank])
            if leaf != CRUSH_NONE:
                leaves[rank] = leaf
                used_leaves.add(leaf)

        def repair_pass(t_offset: int, allow_claimed: bool) -> None:
            """Attempt-major retries for unfilled ranks."""
            for t in range(self.tries):
                unfilled = [i for i in range(n) if leaves[i] == CRUSH_NONE]
                if not unfilled:
                    return
                for rank in unfilled:
                    node = draw_domain(rank, t_offset + t, allow_claimed)
                    if node == CRUSH_NONE:
                        continue
                    leaf = pick_leaf(rank, node)
                    if leaf == CRUSH_NONE:
                        continue
                    domains[rank] = node
                    leaves[rank] = leaf
                    claimed.add(node)
                    used_leaves.add(leaf)

        # Phase C: repair unfilled ranks over unclaimed domains only —
        # skipped outright when every domain under parent is claimed, so a
        # degraded mapping doesn't burn tries on guaranteed-futile draws.
        if any(leaf == CRUSH_NONE for leaf in leaves) and \
                len(claimed) < self._count_domains(parent, step.type):
            repair_pass(self.tries, allow_claimed=False)

        if indep:
            return leaves  # failed ranks keep their CRUSH_NONE hole

        # Phase D (firstn only): domains exhausted — allow domain reuse but
        # never leaf reuse, then compact.
        if self.relax_firstn_on_exhaustion:
            repair_pass(2 * self.tries, allow_claimed=True)
        return [leaf for leaf in leaves if leaf != CRUSH_NONE]

    def _count_domains(self, parent: int, target_type: int) -> int:
        """Number of distinct items of target_type in the subtree of parent.
        Cached per (parent, type); invalidated when topology changes."""
        key = (parent, target_type)
        cached = self._domain_counts.get(key)
        if cached is not None:
            return cached
        count = 0
        stack = [parent]
        while stack:
            node = stack.pop()
            if target_type == DEVICE:
                if node >= 0:
                    count += 1
                    continue
            bucket = self._buckets.get(node)
            if bucket is None:
                continue
            if bucket.type == target_type:
                count += 1
                continue
            stack.extend(bucket.items)
        self._domain_counts[key] = count
        return count
