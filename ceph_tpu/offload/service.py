"""Process-wide device offload service: mesh-parallel dynamic batching
for EC + crc.

The round-5 verdict's core complaint: the raw TPU kernel encodes at
~32 GB/s, yet the in-situ cluster data path crawls at tens of MB/s,
because every PG op dispatches its own tiny synchronous encode — each
one paying the full launch + H2D round trip (~2 ms through the transfer
tunnel) for a few KiB of work, serialized on the event loop. That is
the per-op software overhead that dominates online erasure coding in
real systems (arXiv:1709.05365); the cure is the admission-queue /
continuous-batching discipline of an inference server (arXiv:2108.02692
uses the same staging shape for XOR-network kernels).

This module is that admission queue, one front end per event loop —
one per vstart-style cluster in the single-loop world, one per reactor
SHARD under the sharded runtime (utils/reactor.py), where the device
topology, per-chip circuit breakers, and serving mesh are a single
pool-shared object so every shard sees one rotation decision per chip
while admission/batching/staging stay loop-local (cross-shard callers
hand jobs over through `submit_threadsafe`'s call_soon_threadsafe
handoff):

  * submit(): callers hand over an `EncodeJob`/`DecodeJob`/`CrcJob`
    (numpy batch + codec identity) and await a future. Admission is
    gated by a byte-budget `Throttle` — when the queue is full the
    caller waits, so a wedged device backpressures the write path
    instead of buffering unboundedly.
  * size-bucketed dynamic batcher: jobs coalesce per bucket key
    (op kind + coding matrix + chunk geometry — only shape-compatible
    work can share a device dispatch). A bucket flushes when its bytes
    reach `ec_offload_max_batch_bytes` or when the oldest job has
    lingered `ec_offload_linger_ms` (continuous batching's flush rule).
  * mesh fan-out: every visible accelerator is a dispatch slot with its
    own pipeline semaphore, double-buffered staging pool, and circuit
    breaker. Flushed buckets route DEVICE-AFFINE — same bucket key,
    same chip, so each chip's XLA compile cache and pinned bitmatrix
    stay warm — spilling to the least-busy slot when the preferred one
    backs up (`ec_offload_device_spill_threshold`). Batches at or past
    `ec_offload_device_shard_bytes` skip the single-chip queue entirely
    and run stripe-sharded over the whole (stripe, shard) mesh built at
    init from `parallel.make_mesh` (bit-identical output: same field,
    same matrices).
  * zero-copy staging discipline: coalesced jobs stack into a REUSED
    per-slot staging array (steady-state pages, no allocator churn —
    the link_h2d microstage's reused-buffer rate), lone jobs hand their
    array through by reference; the copytrack ledger records which.
  * per-device circuit breaker: one chip failing fails over its
    in-flight batch to the next healthy chip (host GF(2^8) codec —
    bit-identical — only when every chip is out of rotation) and
    removes just that chip until a half-open probe clears it. The
    service is `degraded` (TPU_OFFLOAD_DEGRADED on the mgr) only when
    NO device remains in rotation.

Observability: tracer spans `offload_queue_wait` (admission -> dispatch)
and `offload_batch` (ops/bytes/device tags) nest under the submitting
op's trace; perf counters under the process-wide "offload" logger
(queue depth gauge, batch-size/bytes histograms, coalesced-op/fallback/
spill/mesh counters) ride `perf dump`, the mgr report stream, and the
admin-socket `ec offload status` command; per-device busy/bytes/batches
ride the MgrClient device_metrics path into `ceph_device`-labeled
exporter families.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import contextvars
import os
import threading
import time
from typing import Any, Callable

import numpy as np

from ceph_tpu.qa import faultinject, interleave
from ceph_tpu.utils import copytrack, flight, sanitizer, tracer
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.perf_counters import (TYPE_GAUGE, TYPE_HISTOGRAM,
                                          PerfCountersCollection)
from ceph_tpu.utils.throttle import Throttle

# -- module-wide defaults (mirrored by the ec_offload_* config options) ------

_DEFAULTS: dict[str, Any] = {
    "enabled": True,
    "max_batch_bytes": 8 << 20,
    "linger_ms": 2.0,
    "max_queue_bytes": 64 << 20,
    "pipeline_depth": 2,
    "breaker_threshold": 1,
    "breaker_reset_s": 30.0,
    "crc_device": False,
    "device_count": 0,
    "device_shard_bytes": 32 << 20,
    "device_spill_threshold": 2,
    "device_peak_gbps": 0.0,
}

#: one service per event loop: a loop is one cluster's world (tests and
#: benches run many clusters through sequential asyncio.run calls, and a
#: service holds loop-bound primitives). Under the sharded reactor each
#: shard's loop gets its own service FRONT END (admission queue,
#: buckets, staging pools — all loop-bound), while the device topology
#: (breaker state per chip, serving mesh) is ONE shared object hung off
#: the reactor pool, so four shards see one rotation decision per chip.
_instances_lock = threading.Lock()
_instances: dict[Any, "OffloadService"] = {}

_pool: concurrent.futures.ThreadPoolExecutor | None = None


def _executor() -> concurrent.futures.ThreadPoolExecutor:
    global _pool
    if _pool is None:
        # enough workers for every mesh slot's transfer/compute overlap
        # plus the host lane; threads spawn on demand, so single-device
        # deployments never create the rest. The per-slot pipeline
        # semaphores bound how many batches can occupy the pool.
        workers = max(4, min(16, (os.cpu_count() or 2) + 2))
        _pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="ec-offload")
    return _pool


def _device_partition() -> tuple[int, int] | None:
    """(worker_ordinal, num_workers) from
    CEPH_TPU_OFFLOAD_DEVICE_PARTITION ("j/W", set by the process-backed
    reactor at worker spawn): each worker process serves a disjoint
    round-robin slice of the visible chips, so per-chip XLA-compile and
    pinned-bitmatrix warmth stays process-local instead of every worker
    re-warming (and contending for) the full set."""
    raw = os.environ.get("CEPH_TPU_OFFLOAD_DEVICE_PARTITION")
    if not raw:
        return None
    try:
        j, w = raw.split("/", 1)
        j, w = int(j), int(w)
    except ValueError:
        return None
    if w < 1 or j < 0:
        return None
    return j % w, w


_perf_lock = threading.Lock()


def _perf():
    coll = PerfCountersCollection.instance()
    with _perf_lock:
        # shard loops race the first-use registration; the lock also
        # keeps a second caller from seeing a half-added counter set
        pc = coll.get("offload")
        if pc is not None:
            return pc
        pc = coll.create("offload")
        pc.add("jobs", description="ops submitted to the offload queue")
        pc.add("batches", description="device batches dispatched")
        pc.add("coalesced_ops",
               description="ops that shared a device batch with others")
        pc.add("fallback_ops",
               description="ops served by the host codec fallback")
        pc.add("breaker_trips",
               description="circuit-breaker trips (device -> degraded)")
        pc.add("device_spills",
               description="batches routed off their affine device to "
                           "the least-busy one (load spillover)")
        pc.add("device_failovers",
               description="in-flight batches failed over from a "
                           "tripped device to another healthy device")
        pc.add("mesh_batches",
               description="oversized batches stripe-sharded across "
                           "the whole device mesh")
        pc.add("batch_ops", type=TYPE_HISTOGRAM,
               description="ops coalesced per device batch")
        pc.add("batch_bytes", type=TYPE_HISTOGRAM,
               description="bytes per device batch")
        pc.add("queue_wait_us", type=TYPE_HISTOGRAM,
               description="admission-to-dispatch queue wait (µs)")
        pc.add("queue_bytes", type=TYPE_GAUGE,
               description="bytes admitted and not yet completed")
        pc.add("inflight_batches", type=TYPE_GAUGE,
               description="batches occupying staging slots")
        # per-kernel achieved bandwidth (EWMA over device batches) and
        # its fraction of the configured device peak — the roofline
        # gauges the metrics history trends per daemon. enc/dec/crc/rep
        # mirror the _Bucket key kinds.
        for kind in ("enc", "dec", "crc", "rep"):
            pc.add(f"kernel_{kind}_gbps", type=TYPE_GAUGE,
                   description=f"{kind} kernel achieved GB/s "
                               f"(EWMA over device batches)")
            pc.add(f"kernel_{kind}_roofline_pct", type=TYPE_GAUGE,
                   description=f"{kind} kernel GB/s as % of "
                               f"ec_offload_device_peak_gbps (0 when "
                               f"no peak is configured)")
    return pc


class _InjectedDeviceFailure(RuntimeError):
    """faultinject device fault: deterministic — the batch goes
    straight to the host fallback (one armed failure = one fallback
    batch), never retried across chips."""


class _Job:
    """One submitted op: a stripe/block batch plus its completion.
    `data` is one array, or a LIST of row-compatible arrays (a scatter
    job — e.g. per-shard csum fragments): the fragments stack straight
    into the staging pages at batch build, never through an
    intermediate join on the submit path."""

    __slots__ = ("data", "rows", "nbytes", "fut", "span", "t_submit")

    def __init__(self, data, fut: asyncio.Future):
        self.data = data
        if isinstance(data, list):
            self.rows = sum(f.shape[0] for f in data)
            self.nbytes = int(sum(f.nbytes for f in data))
        else:
            self.rows = data.shape[0]
            self.nbytes = int(data.nbytes)
        self.fut = fut
        self.span = tracer.start_span("offload_queue_wait")
        self.t_submit = time.perf_counter()


class _Bucket:
    """Pending jobs that can share one device dispatch."""

    __slots__ = ("key", "jobs", "nbytes", "dispatch", "fallback",
                 "shard_dispatch", "linger_task", "uses_device")

    def __init__(self, key: tuple, dispatch: Callable, fallback: Callable,
                 uses_device: bool, shard_dispatch: Callable | None = None):
        self.key = key
        self.jobs: list[_Job] = []
        self.nbytes = 0
        self.dispatch = dispatch
        self.fallback = fallback
        #: mesh-wide stripe-sharded dispatch for oversized batches
        #: (None for job kinds with no sharded kernel, e.g. crc/repair)
        self.shard_dispatch = shard_dispatch
        self.linger_task: asyncio.Task | None = None
        # host-native buckets (e.g. CrcJobs with crc_device off) bypass
        # the circuit breaker entirely: their success says nothing about
        # the device, and must not close a tripped breaker
        self.uses_device = uses_device


class _DeviceState:
    """Process-shared identity + circuit-breaker state for one
    accelerator. Under a reactor pool every shard's service holds a
    slot onto the SAME state, so breaker evidence (which arrives
    concurrently from every shard loop) feeds one rotation decision
    per chip; transitions take `lock`."""

    __slots__ = ("label", "jdev", "lock", "degraded", "degraded_since",
                 "consec_failures", "probe_owner", "last_error")

    def __init__(self, label: str, jdev):
        self.label = label
        self.jdev = jdev                 # jax device, or None = host lane
        # lockset-recorded (sanitizer TSan-lite): breaker evidence
        # arrives from every shard thread, and the recorder proves the
        # "transitions take lock" contract at runtime
        self.lock = sanitizer.make_lock(f"devstate:{label}")
        self.degraded = False
        self.degraded_since = 0.0
        self.consec_failures = 0
        # half-open probe claim: the claimant batch's token, or None.
        # Owner-checked (release_probe) so a batch that merely passed
        # through the device can never free another batch's claim.
        self.probe_owner: object | None = None
        self.last_error = ""


class _Topology:
    """The cross-shard half of the service: device states, the serving
    mesh, and the mesh breaker. One per reactor pool (shared by every
    shard's service) or one per unpooled service (the pre-shard
    behavior, unchanged)."""

    def __init__(self):
        self.lock = sanitizer.make_lock("offload_topology")
        self.states: list[_DeviceState] | None = None
        self.mesh = None
        self.mesh_fns: dict[tuple, Callable] = {}
        self.mesh_degraded = False
        self.mesh_degraded_since = 0.0
        self.mesh_probe_inflight = False

    def note(self, field: str, write: bool) -> None:
        """Lockset-recorder tap: every shard thread touches this
        topology, so each field access feeds the sanitizer's TSan-lite
        conflict analysis (no-op unless recording is armed)."""
        sanitizer.note_shared_access(self, field, write)

    def reset(self) -> None:
        with self.lock:
            self.note("states", write=True)
            self.states = None
            self.mesh = None
            self.mesh_fns.clear()
            self.mesh_degraded = False
            self.mesh_probe_inflight = False

    def device_states(self, device_count: int) -> list[_DeviceState]:
        """Build (once) the shared device list; later callers — other
        shards' services — reuse it. The expensive half (jax import,
        device enumeration, mesh build) runs OUTSIDE the lock: shard
        event loops take this lock synchronously in _mesh_allowed, and
        holding it across a multi-second backend init would freeze
        every shard (a racing duplicate build is discarded, which is
        benign)."""
        with self.lock:
            self.note("states", write=False)
            if self.states is not None:
                return self.states
        states: list[_DeviceState] = []
        try:
            import jax
            devs = list(jax.devices())
        except Exception:
            devs = []
        part = _device_partition()
        if part is not None and devs:
            # device-affine partition for a process-backed shard worker:
            # slice FIRST (the partition defines this process's visible
            # set), then let the count knob cap within it
            j, w = part
            devs = devs[j::w] or devs[:1]
        if device_count > 0:
            devs = devs[:device_count]
        for d in devs:
            states.append(_DeviceState(f"{d.platform}:{d.id}", d))
        if not states:
            states.append(_DeviceState("device:0", None))
        mesh = None
        if len(states) >= 2:
            try:
                from ceph_tpu.parallel import mesh as mesh_lib
                # stripe-only serving mesh (see _topology docstring)
                mesh = mesh_lib.make_mesh(
                    len(states), stripe=len(states), shard_max=1)
                dout("offload", 5,
                     f"offload mesh up: {len(states)} devices, "
                     f"shape {dict(mesh.shape)}")
            except Exception as e:
                dout("offload", 1, f"offload mesh unavailable "
                                   f"({type(e).__name__}: {e}); "
                                   f"single-device dispatch only")
        with self.lock:
            self.note("states", write=True)
            if self.states is None:       # first finisher publishes
                self.states = states
                self.mesh = mesh
            return self.states

    def mesh_fn(self, cache_key: tuple, M: np.ndarray) -> Callable:
        """The cached stripe-sharded kernel for matrix `M` — one
        compile per pool, shared by every shard. The XLA compile runs
        outside the lock (same reasoning as device_states; a racing
        double-compile loses to setdefault)."""
        with self.lock:
            self.note("mesh_fns", write=False)
            fn = self.mesh_fns.get(cache_key)
            mesh = self.mesh
        if fn is None:
            from ceph_tpu.parallel import mesh as mesh_lib
            built = mesh_lib.sharded_apply_fn(mesh, M)
            with self.lock:
                self.note("mesh_fns", write=True)
                fn = self.mesh_fns.setdefault(cache_key, built)
        return fn


class _DeviceSlot:
    """One shard's dispatch handle onto a device: the per-shard
    pipeline semaphore and reusable staging buffers (loop-bound, never
    shared) plus a reference to the cross-shard `_DeviceState` breaker.
    Breaker fields proxy through so routing/dispatch code (and tests)
    keep the flat slot API."""

    __slots__ = ("state", "sem", "depth", "inflight", "staging")

    def __init__(self, state: _DeviceState, depth: int):
        self.state = state
        self.depth = max(1, depth)
        self.sem = asyncio.Semaphore(self.depth)
        self.inflight = 0                # batches routed here, not done
        # pinned-in-spirit staging: reused flat uint8 arrays (the warm
        # pages the link bench's reused-buffer rate measures); at most
        # `depth` buffers — the double-buffer pair at depth 2. Per
        # SHARD: staging arrays are written on this shard's dispatch
        # path only, so they never need a lock.
        self.staging: list[np.ndarray] = []

    @property
    def label(self) -> str:
        return self.state.label

    @property
    def jdev(self):
        return self.state.jdev

    @property
    def degraded(self) -> bool:
        return self.state.degraded

    @degraded.setter
    def degraded(self, v: bool) -> None:
        self.state.degraded = v

    @property
    def degraded_since(self) -> float:
        return self.state.degraded_since

    @degraded_since.setter
    def degraded_since(self, v: float) -> None:
        self.state.degraded_since = v

    @property
    def consec_failures(self) -> int:
        return self.state.consec_failures

    @consec_failures.setter
    def consec_failures(self, v: int) -> None:
        self.state.consec_failures = v

    @property
    def probe_owner(self):
        return self.state.probe_owner

    @probe_owner.setter
    def probe_owner(self, v) -> None:
        self.state.probe_owner = v

    @property
    def last_error(self) -> str:
        return self.state.last_error

    @last_error.setter
    def last_error(self, v: str) -> None:
        self.state.last_error = v

    @property
    def probe_inflight(self) -> bool:
        return self.state.probe_owner is not None

    def release_probe(self, token) -> None:
        """Release the half-open probe claim IFF `token` owns it."""
        state = self.state
        with state.lock:
            if token is not None and state.probe_owner is token:
                state.probe_owner = None

    def get_staging(self, nbytes: int) -> np.ndarray:
        best = -1
        for i, a in enumerate(self.staging):
            if a.nbytes >= nbytes and (
                    best < 0 or a.nbytes < self.staging[best].nbytes):
                best = i
        if best >= 0:
            buf = self.staging.pop(best)
        else:
            buf = np.empty(1 << max(12, (nbytes - 1).bit_length()),
                           dtype=np.uint8)
        if sanitizer.view_guards_active():
            # generation-track the page: views handed out against this
            # hand-out go stale at the put_staging recycle point
            sanitizer.register_buffer(buf, "staging")
        return buf

    def put_staging(self, buf: np.ndarray) -> None:
        if sanitizer.view_guards_active():
            # recycle point: the finished batch's views over this page
            # are dead from here — a straggler access raises instead of
            # reading the next batch's stripe
            sanitizer.recycle_buffer(buf)
        self.staging.append(buf)
        while len(self.staging) > self.depth:
            # keep the largest buffers (they satisfy every batch size).
            # Evict by INDEX: list.remove(array) compares elementwise
            # and raises on mixed shapes — pipelined PGs return
            # different-sized staging pages concurrently
            smallest = min(range(len(self.staging)),
                           key=lambda i: self.staging[i].nbytes)
            del self.staging[smallest]


class OffloadService:
    """The per-loop admission queue + batcher + mesh router (module doc)."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self.enabled = bool(_DEFAULTS["enabled"])
        self.max_batch_bytes = int(_DEFAULTS["max_batch_bytes"])
        self.linger_ms = float(_DEFAULTS["linger_ms"])
        self.pipeline_depth = max(1, int(_DEFAULTS["pipeline_depth"]))
        self.breaker_threshold = max(1, int(_DEFAULTS["breaker_threshold"]))
        self.breaker_reset_s = float(_DEFAULTS["breaker_reset_s"])
        self.crc_device = bool(_DEFAULTS["crc_device"])
        self.device_count = int(_DEFAULTS["device_count"])
        self.device_shard_bytes = int(_DEFAULTS["device_shard_bytes"])
        self.device_spill_threshold = max(
            1, int(_DEFAULTS["device_spill_threshold"]))
        self.device_peak_gbps = float(_DEFAULTS["device_peak_gbps"])
        self._throttle = Throttle("ec_offload_queue",
                                  int(_DEFAULTS["max_queue_bytes"]))
        self._space = asyncio.Event()
        self._buckets: dict[tuple, _Bucket] = {}
        self._tasks: set[asyncio.Task] = set()
        self.perf = _perf()
        # per-instance stats (the shared perf logger spans every cluster
        # the process ever booted; these are this loop's numbers)
        self.stats = {"jobs": 0, "batches": 0, "coalesced_ops": 0,
                      "fallback_ops": 0, "breaker_trips": 0,
                      "batched_ops": 0, "mesh_batches": 0,
                      "device_spills": 0, "device_failovers": 0}
        # per-device utilization: busy wall time / bytes / batches per
        # dispatch target; fallback and host-native batches are
        # attributed to "host". Keys are the slot labels plus "host".
        self.device_stats: dict[str, dict] = {}
        # guards device_stats against admin-socket-thread readers
        # (`ec offload status` / the MgrClient device_cb) racing the
        # loop's first-seen-device key inserts: unlike self.stats, the
        # key set grows at runtime
        self._dev_lock = threading.Lock()
        # dispatch topology (built lazily on first use: importing jax /
        # enumerating devices must not tax service construction on
        # paths that never touch a device). The device/breaker/mesh
        # half lives in `_topo` — ONE shared object across every shard
        # of a reactor pool, private for unpooled loops — while the
        # slots (pipeline semaphores + staging pools) stay per shard.
        # Resolved per ACCESS (the _topo property): services are cached
        # per loop across ShardPool lifetimes, and a service created
        # before its loop joined a pool must re-bind to the pool-shared
        # topology or shard 0 would run a private breaker world.
        self._topo_pool = None
        self._topo_obj: _Topology | None = None
        self._slots: list[_DeviceSlot] | None = None
        self._host_slot = _DeviceSlot(_DeviceState("host", None),
                                      self.pipeline_depth)
        self._last_error = ""
        # per-kernel-kind achieved-GB/s EWMA backing the roofline gauges
        self._kernel_gbps: dict[str, float] = {}

    @property
    def _topo(self) -> _Topology:
        try:
            from ceph_tpu.utils import reactor
            pool = reactor.pool_for(self._loop)
        except Exception:
            pool = None
        if pool is not None and \
                getattr(pool, "backend", "thread") != "thread":
            # process-backed shards share no memory: shared() is
            # structurally absent there, and each worker process keeps
            # its OWN topology over its partition of the chips (the
            # parent's control loop likewise stays private)
            pool = None
        if self._topo_obj is None or pool is not self._topo_pool:
            self._topo_pool = pool
            self._topo_obj = pool.shared("offload_topology", _Topology) \
                if pool is not None else _Topology()
            # slots reference the previous topology's device states:
            # rebuild them onto the new one at next dispatch
            self._slots = None
        return self._topo_obj

    # -- config --------------------------------------------------------------

    @property
    def max_queue_bytes(self) -> int:
        return self._throttle.max

    def apply_setting(self, name: str, value: Any) -> None:
        """Apply one ec_offload_* option (config-observer hot path)."""
        if name == "ec_offload_enabled":
            self.enabled = bool(value)
        elif name == "ec_offload_max_batch_bytes":
            self.max_batch_bytes = int(value)
        elif name == "ec_offload_linger_ms":
            self.linger_ms = float(value)
        elif name == "ec_offload_max_queue_bytes":
            self._throttle.reset_max(int(value))
            # observers can fire from an admin-socket thread: the waiter
            # event is loop-bound, so hop onto the loop to rotate it
            try:
                on_loop = asyncio.get_running_loop() is self._loop
            except RuntimeError:
                on_loop = False
            if on_loop:
                self._wake_waiters()
            elif not self._loop.is_closed():
                self._loop.call_soon_threadsafe(self._wake_waiters)
        elif name == "ec_offload_breaker_threshold":
            self.breaker_threshold = max(1, int(value))
        elif name == "ec_offload_breaker_reset_s":
            self.breaker_reset_s = float(value)
        elif name == "ec_offload_crc_device":
            self.crc_device = bool(value)
        elif name == "ec_offload_device_count":
            self.device_count = int(value)
            # in-flight batches keep their slot refs; new flushes see
            # the rebuilt topology (shared reset: the observer applies
            # the change to every shard's service, each of which drops
            # its own slot list here)
            self._slots = None
            self._topo.reset()
        elif name == "ec_offload_device_shard_bytes":
            self.device_shard_bytes = int(value)
        elif name == "ec_offload_device_spill_threshold":
            self.device_spill_threshold = max(1, int(value))
        elif name == "ec_offload_device_peak_gbps":
            self.device_peak_gbps = max(0.0, float(value))

    # -- dispatch topology ---------------------------------------------------

    def _topology(self) -> list[_DeviceSlot]:
        """This shard's device slots (built on first use): one per
        visible accelerator (capped by ec_offload_device_count), plus
        the mesh for stripe-sharded oversized batches — the stripe-only
        serving mesh where every chip does full-rate data-parallel work
        (the (stripe, shard) shape stays the dryrun/TP-validation
        config; its shard axis pays an all-gather plus padded parity
        rows, a net loss at m=3). Device identity/breaker state and the
        mesh are the SHARED topology; the slot objects (pipeline
        semaphore, staging pool) are this loop's own. Without jax — or
        with no devices — a single anonymous slot dispatches on the
        caller's default placement, preserving the pre-mesh behavior."""
        if self._slots is not None:
            return self._slots
        states = self._topo.device_states(self.device_count)
        self._slots = [_DeviceSlot(st, self.pipeline_depth)
                       for st in states]
        return self._slots

    @property
    def _mesh(self):
        return self._topo.mesh

    def _slot_available(self, slot: _DeviceSlot) -> bool:
        """In rotation: healthy, or cooled down enough for a probe."""
        if not slot.degraded:
            return True
        return (time.monotonic() - slot.degraded_since
                >= self.breaker_reset_s) and not slot.probe_inflight

    def _route(self, bucket_key: tuple,
               exclude: set | None = None,
               claimant: object | None = None) -> _DeviceSlot | None:
        """Device-affine routing with least-busy spillover: the bucket
        key hashes to a preferred slot (compile-cache + pinned-matrix
        warmth), abandoned only when that slot is out of rotation or
        `device_spill_threshold` batches busier than the least-busy
        one. None when every device is out of rotation.

        A degraded-but-cooled slot is CLAIMED for its half-open probe
        here, at routing time, for `claimant` — claiming only at
        dispatch would let every batch routed in the window pile onto
        a possibly-still-dead chip instead of the single designed
        probe batch. The claim clears via _slot_success/_slot_failure
        (dispatch outcome = breaker evidence), or owner-checked via
        release_probe on paths where neither ran (cancellation, the
        mesh detour)."""
        slots = self._topology()
        spill_counted = False
        while True:
            allowed = [s for s in slots
                       if self._slot_available(s)
                       and (exclude is None or s not in exclude)]
            if not allowed:
                return None
            pref = slots[hash(bucket_key) % len(slots)]
            least = min(allowed, key=lambda s: s.inflight)
            chosen = least
            if pref in allowed:
                if pref.inflight - least.inflight < \
                        self.device_spill_threshold:
                    chosen = pref
                elif least is not pref and not spill_counted:
                    # a true load spill: the preferred chip was healthy
                    # but backed up (an unavailable/excluded pref is
                    # failover territory, not a balance signal). One
                    # routing decision = at most one spill, however
                    # many probe-claim re-route iterations it takes.
                    spill_counted = True
                    self.perf.inc("device_spills")
                    self.stats["device_spills"] += 1
            if chosen.degraded:
                # half-open probe claim, ATOMIC across shards (anonymous
                # token when the caller has none, so the window still
                # admits only one batch). Losing the claim race to
                # another shard's batch means the slot just left the
                # allowed set — re-route around it.
                state = chosen.state
                with state.lock:
                    if state.degraded and state.probe_owner is not None:
                        exclude = (set() if exclude is None
                                   else set(exclude)) | {chosen}
                        continue
                    if state.degraded:
                        state.probe_owner = claimant \
                            if claimant is not None else object()
            return chosen

    # -- public job API ------------------------------------------------------

    async def encode(self, ec_impl, stripes: np.ndarray) -> np.ndarray:
        """(S, k, C) data stripes -> (S, m, C) parity via the plugin's
        batched device API, coalesced with concurrent callers."""
        key = ("enc", ec_impl.coding_matrix.tobytes(), stripes.shape[2])

        def dispatch(batch: np.ndarray) -> np.ndarray:
            return np.asarray(ec_impl.encode_stripes(batch))

        def fallback(batch: np.ndarray) -> np.ndarray:
            return _host_apply(ec_impl.coding_matrix, batch)

        def shard_dispatch(batch: np.ndarray) -> np.ndarray:
            return self._mesh_apply(key[:2], ec_impl.coding_matrix, batch)

        return await self._submit(key, stripes, dispatch, fallback,
                                  shard_dispatch=shard_dispatch)

    async def decode(self, ec_impl, avail_ids: tuple[int, ...],
                     want_ids: tuple[int, ...],
                     chunks: np.ndarray) -> np.ndarray:
        """(S, k, C) available chunks (stacked in avail_ids order) ->
        (S, len(want), C) reconstructed chunks. Jobs coalesce only with
        the same erasure pattern — a different survivor set is a
        different recovery matrix, hence a different bucket."""
        avail_ids, want_ids = tuple(avail_ids), tuple(want_ids)
        key = ("dec", ec_impl.coding_matrix.tobytes(), avail_ids, want_ids,
               chunks.shape[2])

        def dispatch(batch: np.ndarray) -> np.ndarray:
            return np.asarray(ec_impl.decode_stripes(avail_ids, want_ids,
                                                     batch))

        def _recovery():
            from ceph_tpu.ops import rs_codec
            return rs_codec.recovery_matrix(ec_impl.coding_matrix,
                                            avail_ids, want_ids)

        def fallback(batch: np.ndarray) -> np.ndarray:
            return _host_apply(_recovery(), batch)

        def shard_dispatch(batch: np.ndarray) -> np.ndarray:
            return self._mesh_apply(key[:4], _recovery(), batch)

        return await self._submit(key, chunks, dispatch, fallback,
                                  shard_dispatch=shard_dispatch)

    async def crc32c_blocks(self, blocks, block_size: int) -> np.ndarray:
        """(N, block_size) uint8 — or a LIST of such arrays (a scatter
        job, e.g. one EC write's per-shard buffers) — -> (N,) uint32
        per-block crc32c. Scatter fragments stack directly into the
        warm staging pages at batch build instead of the caller paying
        an intermediate join. Host-native by default (the H2D tunnel
        makes device crc a loss for host-resident buffers; flip
        ec_offload_crc_device on hardware where the link is wide) —
        either way the work leaves the event loop and coalesces across
        callers."""
        key = ("crc", bool(self.crc_device), block_size)
        use_device = self.crc_device

        def dispatch(batch: np.ndarray) -> np.ndarray:
            if use_device:
                from ceph_tpu.ops import crc32c as crc_dev
                return np.asarray(crc_dev.get_device_crc(block_size)(batch))
            return _host_crc(batch, block_size)

        def fallback(batch: np.ndarray) -> np.ndarray:
            return _host_crc(batch, block_size)

        if isinstance(blocks, (list, tuple)):
            blocks = [np.ascontiguousarray(b).reshape(-1, block_size)
                      for b in blocks]
        else:
            blocks = np.ascontiguousarray(blocks)
        return await self._submit(key, blocks, dispatch, fallback,
                                  uses_device=use_device)

    async def repair(self, ec_impl, helpers: tuple[int, ...],
                     want: tuple[int, ...], frags: np.ndarray,
                     chunk_size: int) -> np.ndarray:
        """Sub-chunk regenerating repair units (the CLAY single-shard
        rebuild): (N, d, repair_per_chunk) helper fragment planes ->
        (N, chunk_size) rebuilt chunks, coalesced per (codec, erasure
        pattern, geometry) bucket like any DecodeJob. Host-staged
        (uses_device=False): the regenerating transform is the plugin's
        own multi-phase kernel and its success says nothing about the
        accelerator — the win here is coalescing + leaving the event
        loop, and the ~qx smaller fetch already happened at the
        gather."""
        helpers, want = tuple(helpers), tuple(want)
        # codec identity by PROFILE, not instance: every PG backend
        # holds its own plugin object, and keying on id() would defeat
        # the cross-PG coalescing this job exists for (same profile =>
        # same deterministic repair math, so any member's impl serves
        # the whole bucket)
        try:
            ident = tuple(sorted(ec_impl.get_profile().items()))
        except Exception:
            ident = id(ec_impl)
        key = ("rep", type(ec_impl).__name__, ident, helpers, want,
               frags.shape[2], chunk_size)

        def dispatch(batch: np.ndarray) -> np.ndarray:
            out = np.empty((batch.shape[0], chunk_size), dtype=np.uint8)
            for u in range(batch.shape[0]):
                chunks = {h: batch[u, j].tobytes()
                          for j, h in enumerate(helpers)}
                dec = ec_impl.decode(list(want), chunks, chunk_size)
                out[u] = np.frombuffer(dec[want[0]], dtype=np.uint8)
            return out

        return await self._submit(key, np.ascontiguousarray(frags),
                                  dispatch, dispatch, uses_device=False)

    # -- admission -----------------------------------------------------------

    def submit_threadsafe(self, method: str, *args,
                          **kw) -> concurrent.futures.Future:
        """Cross-loop submission seam: build one of the public job
        coroutines (`encode`/`decode`/`crc32c_blocks`/`repair`) and
        hand it to the owning shard's loop via run_coroutine_threadsafe
        — the call_soon_threadsafe handoff, packaged. Callers on other
        shards (or plain threads) get a concurrent Future; awaiting
        shards wrap it with asyncio.wrap_future. The admission queue,
        buckets, and staging stay loop-bound — only the HANDOFF crosses
        threads, which is the whole loop-affinity discipline."""
        if self._loop.is_closed():
            raise RuntimeError("offload service's loop is closed")
        coro = getattr(self, method)(*args, **kw)
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _submit(self, key: tuple, data: np.ndarray,
                      dispatch: Callable, fallback: Callable,
                      uses_device: bool = True,
                      shard_dispatch: Callable | None = None) -> np.ndarray:
        if not self.enabled:
            return self._inline(data, dispatch, fallback, uses_device)
        nbytes = int(sum(f.nbytes for f in data)) \
            if isinstance(data, list) else int(data.nbytes)
        await self._acquire(nbytes)
        self.perf.inc("jobs")
        self.stats["jobs"] += 1
        fut: asyncio.Future = self._loop.create_future()
        job = _Job(data, fut)
        bucket = self._buckets.get(key)
        if bucket is None:
            bucket = self._buckets[key] = _Bucket(key, dispatch, fallback,
                                                  uses_device,
                                                  shard_dispatch)
            bucket.linger_task = self._loop.create_task(
                self._linger_flush(key))
            self._track(bucket.linger_task)
        bucket.jobs.append(job)
        bucket.nbytes += nbytes
        if bucket.nbytes >= self.max_batch_bytes:
            self._flush_bucket(key)
        try:
            return await fut
        finally:
            # admission budget is held until the job's batch completed
            self._release(nbytes)

    def _inline(self, data, dispatch: Callable,
                fallback: Callable, uses_device: bool) -> np.ndarray:
        """Bypass (ec_offload_enabled=false): the pre-service per-op
        synchronous dispatch, breaker semantics included — this is the
        baseline the bench's inline comparison measures. Dispatches on
        the default device (slot 0), like the pre-mesh service."""
        if isinstance(data, list):
            # scatter job on the bypass path: the kernel needs one
            # contiguous batch, so the fragments pay the join here
            t0 = time.perf_counter()
            data = np.concatenate(data, axis=0)
            copytrack.copied("buffer_to_staging", int(data.nbytes),
                             time.perf_counter() - t0)
        self.perf.inc("jobs")
        self.stats["jobs"] += 1
        nbytes = int(data.nbytes)
        if not uses_device:
            t0 = time.perf_counter()
            out = dispatch(data)
            self._note_device("host", 1, nbytes,
                              time.perf_counter() - t0)
            self._note_batch(1, nbytes)
            return out
        slot = self._topology()[0]
        if self._slot_available(slot):
            if slot.degraded:
                # sync path: the claim is released by _slot_success/
                # _slot_failure immediately below, so an anonymous
                # token suffices
                slot.probe_owner = object()
            try:
                t0 = time.perf_counter()
                if faultinject.should_fail_device():
                    raise _InjectedDeviceFailure("injected device failure")
                out = dispatch(data)
                self._slot_success(slot)
                self._note_device(slot.label, 1, nbytes,
                                  time.perf_counter() - t0)
                self._note_batch(1, nbytes)
                return out
            except Exception as e:
                self._slot_failure(slot, e)
        self.perf.inc("fallback_ops")
        self.stats["fallback_ops"] += 1
        t0 = time.perf_counter()
        out = fallback(data)
        self._note_device("host", 1, nbytes,
                          time.perf_counter() - t0, fallback=True)
        return out

    async def _acquire(self, nbytes: int) -> None:
        if 0 < self._throttle.max <= nbytes:
            # oversized job: admit unconditionally (transient overshoot)
            # rather than wait for an exactly-empty queue — smaller jobs
            # have no FIFO ordering against it and would starve it
            # forever under sustained load; normal admissions then block
            # until the big one releases
            self._throttle.take(nbytes)
        else:
            while not self._throttle.get_or_fail(nbytes):
                evt = self._space
                await evt.wait()
        self.perf.set("queue_bytes", self._throttle.current)

    def _release(self, nbytes: int) -> None:
        self._throttle.put(nbytes)
        self.perf.set("queue_bytes", self._throttle.current)
        self._wake_waiters()

    def _wake_waiters(self) -> None:
        evt, self._space = self._space, asyncio.Event()
        evt.set()

    # -- batching ------------------------------------------------------------

    def _track(self, task: asyncio.Task) -> None:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _linger_flush(self, key: tuple) -> None:
        """Deadline flush: after linger_ms the bucket ships however full
        it is (bounded latency for a lone op on an idle cluster)."""
        await asyncio.sleep(self.linger_ms / 1000.0)
        bucket = self._buckets.pop(key, None)
        if bucket is not None and bucket.jobs:
            self._track(self._loop.create_task(self._run_batch(bucket)))

    def _flush_bucket(self, key: tuple) -> None:
        bucket = self._buckets.pop(key, None)
        if bucket is None:
            return
        if bucket.linger_task is not None:
            bucket.linger_task.cancel()
        if bucket.jobs:
            self._track(self._loop.create_task(self._run_batch(bucket)))

    def _on_loop(self) -> bool:
        try:
            return asyncio.get_running_loop() is self._loop
        except RuntimeError:
            return False

    def _from_loop(self, fn):
        """Run `fn` on the service's event loop and return its result —
        admin-socket hooks call from their own thread, and _buckets is
        only coherent on the loop (a dict mutating mid-iteration raises
        RuntimeError under exactly the load the command inspects)."""
        if self._on_loop():
            return fn()
        if self._loop.is_closed():
            return fn()         # loop gone: nothing is mutating anymore

        async def run():
            return fn()
        try:
            return asyncio.run_coroutine_threadsafe(
                run(), self._loop).result(timeout=2.0)
        except concurrent.futures.TimeoutError:
            # loop blocked (possibly by the very caller awaiting this
            # admin response in-process): serve a best-effort direct
            # snapshot, retrying the rare mid-mutation iteration
            for _ in range(5):
                try:
                    return fn()
                except RuntimeError:
                    continue
            return fn()

    def flush(self) -> dict:
        """Force-flush every pending bucket now (admin `ec offload
        flush`). Thread-safe: admin-socket hooks run off-loop, and the
        mutating work only ever executes ON the loop — a busy loop gets
        a call_soon_threadsafe wake instead of an off-thread mutation
        (popping buckets from a foreign thread could strand their jobs'
        futures forever if create_task then fails)."""
        def impl():
            pending = {str(k): len(b.jobs)
                       for k, b in self._buckets.items()}
            self._flush_all()
            return {"flushed_buckets": len(pending),
                    "pending_ops": pending}
        if self._on_loop():
            return impl()
        if self._loop.is_closed():
            return {"flushed_buckets": 0, "pending_ops": {},
                    "error": "event loop closed"}

        async def run():
            return impl()
        try:
            return asyncio.run_coroutine_threadsafe(
                run(), self._loop).result(timeout=2.0)
        except concurrent.futures.TimeoutError:
            self._loop.call_soon_threadsafe(self._flush_all)
            return {"flushed_buckets": 0, "pending_ops": {},
                    "scheduled": True,
                    "error": "loop busy; flush scheduled"}

    def _flush_all(self) -> None:
        for key in list(self._buckets):
            self._flush_bucket(key)

    async def drain(self) -> None:
        """Flush and wait for every in-flight batch (tests/bench)."""
        self._flush_all()
        while self._tasks:
            await asyncio.gather(*list(self._tasks),
                                 return_exceptions=True)

    def _stack(self, slot: _DeviceSlot, jobs: list[_Job]):
        """Jobs -> one contiguous batch. A lone single-array job is
        handed through by reference (zero-copy: the memoryview-through
        path from bufferlist to staging); everything else — coalesced
        jobs AND scatter jobs' fragments — stacks in one pass straight
        into the slot's REUSED staging array (warm pages, no
        intermediate bufferlist join anywhere on the path; the old
        b"".join the callers did before submitting showed up as an
        unmetered extra copy of every csum'd byte). Returns
        (stacked, staging_buf_or_None, stack_seconds)."""
        frags: list[np.ndarray] = []
        for j in jobs:
            if isinstance(j.data, list):
                frags.extend(j.data)
            else:
                frags.append(j.data)
        if len(frags) == 1:
            copytrack.referenced("buffer_to_staging", jobs[0].nbytes)
            return frags[0], None, 0.0
        nbytes = sum(int(f.nbytes) for f in frags)
        rows = sum(f.shape[0] for f in frags)
        t0 = time.perf_counter()
        buf = slot.get_staging(nbytes)
        view = buf[:nbytes].reshape((rows,) + frags[0].shape[1:])
        row = 0
        for f in frags:
            np.copyto(view[row:row + f.shape[0]], f)
            row += f.shape[0]
        dt = time.perf_counter() - t0
        copytrack.copied("buffer_to_staging", nbytes, dt)
        return view, buf, dt

    async def _run_batch(self, bucket: _Bucket) -> None:
        jobs = bucket.jobs
        token = object()         # this batch's probe-claim identity
        slot = self._host_slot if not bucket.uses_device \
            else (self._route(bucket.key, claimant=token)
                  or self._host_slot)
        slot.inflight += 1
        staging = None
        try:
            # the semaphore wait is INSIDE the try: a cancel delivered
            # while queued behind full staging slots must still cancel
            # the job futures, or their submitters hang forever
            async with slot.sem:
                self.perf.inc("inflight_batches")
                try:
                    now = time.perf_counter()
                    for j in jobs:
                        self.perf.hist_add("queue_wait_us",
                                           (now - j.t_submit) * 1e6)
                        if j.span is not None:
                            j.span.set_tag("batch_ops", len(jobs))
                            j.span.finish()
                    stacked, staging, stack_s = self._stack(slot, jobs)
                    nbytes = int(stacked.nbytes)
                    stack_us = round(stack_s * 1e6, 1) if staging \
                        is not None else 0.0
                    with tracer.span("offload_batch") as sp:
                        if sp is not None:
                            # span links (tracing v2): the coalesced
                            # batch serves riders from many PGs and
                            # processes — link every rider's trace so
                            # `trace get <rider>` pulls this span in
                            for j in jobs:
                                if j.span is not None and \
                                        j.span.trace_id != sp.trace_id:
                                    sp.add_link(j.span.context())
                        out, on_device = await self._dispatch(
                            bucket, slot, stacked, len(jobs), sp,
                            token)
                        if sp is not None:
                            sp.set_tag("ops", len(jobs))
                            sp.set_tag("bytes", nbytes)
                            sp.set_tag("device", on_device)
                            sp.set_tag("copy_bytes",
                                       nbytes if staging is not None
                                       else 0)
                            sp.set_tag("copy_us", stack_us)
                    self._note_batch(len(jobs), nbytes)
                    row = 0
                    for j in jobs:
                        if not j.fut.done():
                            j.fut.set_result(out[row:row + j.rows])
                        row += j.rows
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # pre-dispatch failure (stacking): release OUR probe
                    # claim — the breaker callbacks that normally clear
                    # it never ran
                    slot.release_probe(token)
                    for j in jobs:
                        if not j.fut.done():
                            j.fut.set_exception(e)
                finally:
                    if staging is not None:
                        slot.put_staging(staging)
                    self.perf.dec("inflight_batches")
        except asyncio.CancelledError:
            # cancelled before/while dispatching: un-claim OUR probe so
            # a cooled-down device is not stuck out of rotation forever
            slot.release_probe(token)
            for j in jobs:
                if not j.fut.done():
                    j.fut.cancel()
            raise
        finally:
            slot.inflight -= 1

    async def _in_staging_pool(self, fn: Callable,
                               stacked: np.ndarray) -> np.ndarray:
        """Run one batch kernel in the staging pool UNDER the caller's
        contextvar context: run_in_executor does not propagate it, which
        would orphan the plugin's tpu_*_dispatch spans into fresh root
        traces instead of nesting under offload_batch."""
        ctx = contextvars.copy_context()
        return await self._loop.run_in_executor(
            _executor(), lambda: ctx.run(fn, stacked))

    async def _device_call(self, slot: _DeviceSlot, fn: Callable,
                           stacked: np.ndarray, sp=None) -> np.ndarray:
        """One staged dispatch onto `slot`'s device: H2D onto that chip
        (from the reused staging buffer — the steady-state link rate),
        the bucket kernel on the committed device array, D2H of the
        result. The ledger gets the h2d/d2h byte flow the plugin can no
        longer see (it receives a device-resident array). Under
        tracer.set_profile_dispatch each leg is serialized so the batch
        span carries real h2d/kernel/d2h splits (attribution mode only —
        it forfeits the transfer/compute overlap)."""
        if slot.jdev is None:
            # jax-less / anonymous slot: the plugin's own host path does
            # the transfer (and its ledger accounting)
            return await self._in_staging_pool(fn, stacked)
        import jax
        nbytes = int(stacked.nbytes)
        profile = sp is not None and tracer.profile_dispatch()

        def run(batch: np.ndarray) -> np.ndarray:
            if profile:
                t0 = time.perf_counter()
                dev = jax.block_until_ready(jax.device_put(batch,
                                                           slot.jdev))
                t1 = time.perf_counter()
                res = jax.block_until_ready(fn(dev))
                t2 = time.perf_counter()
                out = np.asarray(res)
                t3 = time.perf_counter()
                copytrack.copied("h2d", nbytes, t1 - t0)
                copytrack.copied("d2h", int(out.nbytes), t3 - t2)
                sp.set_tag("h2d_us", round((t1 - t0) * 1e6, 1))
                sp.set_tag("kernel_us", round((t2 - t1) * 1e6, 1))
                sp.set_tag("d2h_us", round((t3 - t2) * 1e6, 1))
                return out
            dev = jax.device_put(batch, slot.jdev)
            out = np.asarray(fn(dev))
            copytrack.copied("h2d", nbytes)
            copytrack.copied("d2h", int(out.nbytes))
            return out

        return await self._in_staging_pool(run, stacked)

    def _mesh_apply(self, cache_key: tuple, M: np.ndarray,
                    batch: np.ndarray) -> np.ndarray:
        """Stripe-shard `batch` across the whole mesh through the
        cached sharded kernel for matrix `M` (runs in the staging
        pool; the kernel cache is pool-shared — one compile serves
        every shard)."""
        fn = self._topo.mesh_fn(cache_key, M)
        nbytes = int(batch.nbytes)
        out = fn(batch)
        copytrack.copied("h2d", nbytes)
        copytrack.copied("d2h", int(out.nbytes))
        return out

    def _mesh_allowed(self) -> bool:
        topo = self._topo
        if topo.mesh is None:
            return False
        with topo.lock:
            topo.note("mesh_degraded", write=False)
            if not topo.mesh_degraded:
                return True
            if (time.monotonic() - topo.mesh_degraded_since
                    >= self.breaker_reset_s) and \
                    not topo.mesh_probe_inflight:
                # half-open: claim the single probe batch (one claim
                # ACROSS shards — the lock makes it atomic); cleared on
                # the probe's success, failure, or cancellation
                topo.note("mesh_degraded", write=True)
                topo.mesh_probe_inflight = True
                return True
            return False

    async def _dispatch(self, bucket: _Bucket, slot: _DeviceSlot,
                        stacked: np.ndarray, n_ops: int,
                        sp=None, token: object = None
                        ) -> tuple[np.ndarray, str]:
        """One staged dispatch with per-device failover and host-codec
        last resort. Returns (result, device label: slot/"mesh"/"host")."""
        if interleave.armed():
            # schedule explorer: let a racing batch reach the breaker/
            # staging state between routing and dispatch
            await interleave.yield_point("offload_dispatch")
        nbytes = int(stacked.nbytes)
        if not bucket.uses_device:
            t0 = time.perf_counter()
            out = await self._in_staging_pool(bucket.dispatch, stacked)
            self._note_device("host", n_ops, nbytes,
                              time.perf_counter() - t0)
            return out, "host"
        injected = slot is not self._host_slot \
            and faultinject.should_fail_device()
        if injected:
            self._slot_failure(slot,
                               _InjectedDeviceFailure("injected device "
                                                      "failure"))
        # oversized batches fan across the whole mesh on the stripe
        # axis instead of serializing on one chip
        if (not injected and bucket.shard_dispatch is not None
                and nbytes >= self.device_shard_bytes
                and self._mesh_allowed()):
            topo = self._topo
            try:
                t0 = time.perf_counter()
                out = await self._in_staging_pool(
                    lambda b: bucket.shard_dispatch(b), stacked)
                busy = time.perf_counter() - t0
                with topo.lock:
                    topo.note("mesh_degraded", write=True)
                    topo.mesh_probe_inflight = False
                    if topo.mesh_degraded:
                        topo.mesh_degraded = False
                        dout("offload", 1, "mesh dispatch recovered")
                self.perf.inc("mesh_batches")
                self.stats["mesh_batches"] += 1
                self._note_mesh(n_ops, nbytes, busy)
                self._note_kernel(bucket.key[0], nbytes, busy)
                # this batch never probed the ROUTED chip: return OUR
                # half-open claim, if _route granted one, or a device
                # whose traffic all mesh-shards would stay out of
                # rotation forever (owner-checked: another batch's
                # in-flight probe claim must not be freed here)
                slot.release_probe(token)
                return out, "mesh"
            except asyncio.CancelledError:
                with topo.lock:
                    topo.mesh_probe_inflight = False
                slot.release_probe(token)
                raise
            except Exception as e:
                with topo.lock:
                    topo.note("mesh_degraded", write=True)
                    topo.mesh_probe_inflight = False
                    topo.mesh_degraded = True
                    topo.mesh_degraded_since = time.monotonic()
                self._last_error = f"{type(e).__name__}: {e}"
                dout("offload", 0,
                     f"mesh dispatch failed ({self._last_error}); "
                     f"falling back to single-device for "
                     f"{self.breaker_reset_s:.0f}s")
                # fall through to the single-device path (the routed
                # slot's probe claim, if any, stands — the loop below
                # probes it)
        tried: set = set()
        failover_slots: list[_DeviceSlot] = []
        try:
            while not injected and slot is not self._host_slot:
                try:
                    t0 = time.perf_counter()
                    out = await self._device_call(slot, bucket.dispatch,
                                                  stacked, sp)
                    self._slot_success(slot)
                    busy_s = time.perf_counter() - t0
                    self._note_device(slot.label, n_ops, nbytes, busy_s)
                    self._note_kernel(bucket.key[0], nbytes, busy_s)
                    return out, slot.label
                except asyncio.CancelledError:
                    # un-claim the half-open probe _route may have
                    # granted us — neither _slot_success nor
                    # _slot_failure will run, and a stuck claim removes
                    # the device from rotation forever
                    slot.release_probe(token)
                    raise
                except Exception as e:
                    self._slot_failure(slot, e)
                    tried.add(slot)
                    nxt = self._route(bucket.key, exclude=tried,
                                      claimant=token)
                    if nxt is None:
                        break
                    # fail the in-flight batch over to the next healthy
                    # chip. Deliberately WITHOUT acquiring its pipeline
                    # semaphore (two opposite-direction failovers under
                    # full pipelines would deadlock on each other's
                    # slots); the staging bound may transiently exceed
                    # depth by the in-flight failovers, but routing DOES
                    # see the extra load via the inflight count below.
                    self.perf.inc("device_failovers")
                    self.stats["device_failovers"] += 1
                    flight.record("device_failover", slot.label,
                                  to=nxt.label,
                                  error=f"{type(e).__name__}: {e}")
                    nxt.inflight += 1
                    failover_slots.append(nxt)
                    slot = nxt
            self.perf.inc("fallback_ops", n_ops)
            self.stats["fallback_ops"] += n_ops
            t0 = time.perf_counter()
            out = await self._in_staging_pool(bucket.fallback, stacked)
            self._note_device("host", n_ops, nbytes,
                              time.perf_counter() - t0, fallback=True)
            return out, "host"
        finally:
            for s in failover_slots:
                s.inflight -= 1

    def _note_device(self, device: str, n_ops: int, nbytes: int,
                     busy_s: float, fallback: bool = False) -> None:
        with self._dev_lock:
            d = self.device_stats.get(device)
            if d is None:
                d = self.device_stats[device] = {
                    "batches": 0, "ops": 0, "bytes": 0, "busy_s": 0.0,
                    "fallback_ops": 0}
            d["batches"] += 1
            d["ops"] += n_ops
            d["bytes"] += nbytes
            d["busy_s"] += busy_s
            if fallback:
                d["fallback_ops"] += n_ops

    def _note_kernel(self, kind, nbytes: int, busy_s: float) -> None:
        """Roofline gauges: achieved GB/s for this kernel kind (EWMA —
        one tiny linger-flushed batch must not zero a healthy trend)
        and, when a device peak is configured, its roofline fraction."""
        if busy_s <= 0 or kind not in ("enc", "dec", "crc", "rep"):
            return
        gbps = nbytes / busy_s / 1e9
        prev = self._kernel_gbps.get(kind)
        ewma = gbps if prev is None else 0.7 * prev + 0.3 * gbps
        self._kernel_gbps[kind] = ewma
        self.perf.set(f"kernel_{kind}_gbps", round(ewma, 4))
        peak = self.device_peak_gbps
        if peak > 0:
            self.perf.set(f"kernel_{kind}_roofline_pct",
                          round(100.0 * ewma / peak, 2))

    def _note_mesh(self, n_ops: int, nbytes: int, busy_s: float) -> None:
        """A mesh batch occupies every device for its wall time; bytes
        and ops are split across the stripe axis (integer shares,
        remainder to the low slots)."""
        slots = self._slots or []
        n = max(1, len(slots))
        for i, slot in enumerate(slots):
            ops = n_ops // n + (1 if i < n_ops % n else 0)
            nb = nbytes // n + (1 if i < nbytes % n else 0)
            self._note_device(slot.label, ops, nb, busy_s)

    def device_snapshot(self) -> dict[str, dict]:
        """Consistent copy of device_stats, safe off the loop thread."""
        with self._dev_lock:
            return {dev: dict(d) for dev, d in self.device_stats.items()}

    def device_metrics(self) -> dict:
        """Per-device counters for the MgrClient report path: the mgr
        stores them per daemon and the exporter renders each as a
        `ceph_device`-labeled family."""
        return {dev: {"offload_device_busy_seconds": round(d["busy_s"], 6),
                      "offload_device_bytes": d["bytes"],
                      "offload_device_batches": d["batches"],
                      "offload_device_ops": d["ops"],
                      "offload_device_fallback_ops": d["fallback_ops"]}
                for dev, d in self.device_snapshot().items()}

    def _note_batch(self, n_ops: int, nbytes: int) -> None:
        self.perf.inc("batches")
        self.perf.inc("coalesced_ops", max(0, n_ops - 1))
        self.perf.hist_add("batch_ops", n_ops)
        self.perf.hist_add("batch_bytes", nbytes)
        self.stats["batches"] += 1
        self.stats["batched_ops"] += n_ops
        self.stats["coalesced_ops"] += max(0, n_ops - 1)

    # -- per-device circuit breaker ------------------------------------------

    @property
    def degraded(self) -> bool:
        """No device left in rotation (every slot tripped). Host-codec
        service continues; the mgr digests this into
        TPU_OFFLOAD_DEGRADED."""
        slots = self._slots
        if not slots:
            return False
        return all(s.degraded for s in slots)

    def _slot_success(self, slot: _DeviceSlot) -> None:
        state = slot.state
        recovered = False
        with state.lock:
            # dispatch outcome is breaker evidence: any claim is consumed
            state.probe_owner = None
            state.consec_failures = 0
            if state.degraded:
                state.degraded = False
                recovered = True
        if recovered:
            dout("offload", 1,
                 f"device {slot.label} recovered; back in rotation"
                 + ("" if self.degraded else
                    " (TPU_OFFLOAD_DEGRADED clears)"))
            flight.record("breaker_recover", slot.label)

    def _slot_failure(self, slot: _DeviceSlot, e: Exception) -> None:
        state = slot.state
        tripped = False
        with state.lock:
            state.probe_owner = None
            state.consec_failures += 1
            state.last_error = f"{type(e).__name__}: {e}"
            self._last_error = state.last_error
            if state.degraded:
                state.degraded_since = time.monotonic()   # probe failed
                return
            if state.consec_failures >= self.breaker_threshold:
                state.degraded = True
                state.degraded_since = time.monotonic()
                tripped = True
        if tripped:
            self.perf.inc("breaker_trips")
            self.stats["breaker_trips"] += 1
            dout("offload", 0,
                 f"device {slot.label} failing ({slot.last_error}); "
                 f"removed from rotation for {self.breaker_reset_s:.0f}s"
                 + (" — no devices left, host codec serves "
                    "(TPU_OFFLOAD_DEGRADED)" if self.degraded else ""))
            flight.record("breaker_trip", slot.label,
                          error=slot.last_error,
                          all_degraded=self.degraded)

    # -- surfaces ------------------------------------------------------------

    def health_metrics(self) -> dict:
        """The MgrClient health blob: the mon/mgr health engine turns
        `degraded` into the TPU_OFFLOAD_DEGRADED check."""
        degraded = self.degraded
        slots = self._slots or []
        # the SERVICE became degraded when the LAST device left
        # rotation, hence max() — min() would bill the whole outage to
        # a chip that may have been solo-degraded for hours
        since = max((s.degraded_since for s in slots if s.degraded),
                    default=0.0)
        return {"degraded": degraded,
                "degraded_for_s": round(time.monotonic() - since, 1)
                if degraded and since else 0.0,
                "devices_out": sum(1 for s in slots if s.degraded),
                "fallback_ops": self.stats["fallback_ops"],
                "breaker_trips": self.stats["breaker_trips"],
                "last_error": self._last_error if degraded else ""}

    def status(self) -> dict:
        """Admin-socket `ec offload status` (loop-coherent off-thread)."""
        return self._from_loop(self._status_impl)

    def _status_impl(self) -> dict:
        s = self.stats
        slots = self._slots or []
        return {
            "enabled": self.enabled,
            "degraded": self.degraded,
            "last_error": self._last_error,
            "settings": {"max_batch_bytes": self.max_batch_bytes,
                         "linger_ms": self.linger_ms,
                         "max_queue_bytes": self.max_queue_bytes,
                         "pipeline_depth": self.pipeline_depth,
                         "breaker_threshold": self.breaker_threshold,
                         "breaker_reset_s": self.breaker_reset_s,
                         "crc_device": self.crc_device,
                         "device_count": self.device_count,
                         "device_shard_bytes": self.device_shard_bytes,
                         "device_spill_threshold":
                             self.device_spill_threshold},
            "mesh": {"devices": len(slots),
                     "shape": dict(self._mesh.shape)
                     if self._mesh is not None else None,
                     "degraded": self._topo.mesh_degraded,
                     "mesh_batches": s["mesh_batches"]},
            "rotation": {sl.label: {"degraded": sl.degraded,
                                    "inflight": sl.inflight,
                                    "last_error": sl.last_error}
                         for sl in slots},
            "queue_bytes": self._throttle.current,
            "pending_buckets": {str(k): {"ops": len(b.jobs),
                                         "bytes": b.nbytes}
                                for k, b in self._buckets.items()},
            "jobs": s["jobs"],
            "batches": s["batches"],
            "coalesced_ops": s["coalesced_ops"],
            "fallback_ops": s["fallback_ops"],
            "breaker_trips": s["breaker_trips"],
            "device_spills": s["device_spills"],
            "device_failovers": s["device_failovers"],
            "mean_batch_ops": round(s["batched_ops"] / s["batches"], 3)
            if s["batches"] else 0.0,
            "devices": {dev: dict(d, busy_s=round(d["busy_s"], 6))
                        for dev, d in self.device_snapshot().items()},
        }


# -- host fallback kernels ---------------------------------------------------

def _host_apply(M: np.ndarray, batch: np.ndarray) -> np.ndarray:
    """(S, k, C) through the (r, k) GF(2^8) matrix on host -> (S, r, C);
    bit-identical to the device kernel (same field, same matrices)."""
    from ceph_tpu.ec import gf256
    S, k, C = batch.shape
    flat = np.ascontiguousarray(
        batch.transpose(1, 0, 2)).reshape(k, S * C)
    out = gf256.mat_vec_apply(np.ascontiguousarray(M, dtype=np.uint8), flat)
    return np.ascontiguousarray(
        out.reshape(M.shape[0], S, C).transpose(1, 0, 2))


def _host_crc(batch: np.ndarray, block_size: int) -> np.ndarray:
    from ceph_tpu.native import ec_native
    return ec_native.crc32c_blocks(
        np.ascontiguousarray(batch).reshape(-1), block_size)


# -- per-loop instance + config plumbing -------------------------------------

def get_service() -> OffloadService:
    """The running loop's service (created on first use). Thread-safe:
    under the sharded reactor every shard loop races this on first
    dispatch."""
    loop = asyncio.get_running_loop()
    with _instances_lock:
        svc = _instances.get(loop)
        if svc is None:
            for stale in [lp for lp in _instances if lp.is_closed()]:
                del _instances[stale]
            svc = _instances[loop] = OffloadService(loop)
    return svc


def service_for(loop) -> OffloadService | None:
    """An existing service by loop (no creation) — the lookup a foreign
    shard or plain thread uses before submit_threadsafe."""
    with _instances_lock:
        return _instances.get(loop)


def get_service_or_none() -> OffloadService | None:
    """get_service, or None outside a running event loop (sync callers
    fall back to inline dispatch)."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return None
    return get_service()


def set_enabled(flag: bool) -> None:
    """Module-wide toggle (bench harness): defaults + live instances."""
    _DEFAULTS["enabled"] = bool(flag)
    with _instances_lock:
        services = list(_instances.values())
    for svc in services:
        svc.enabled = bool(flag)


def OFFLOAD_OPTIONS():
    """The ec_offload_* option schema (declared per daemon Config)."""
    from ceph_tpu.utils.config import Option
    return [
        Option("ec_offload_enabled", "bool", _DEFAULTS["enabled"],
               "route EC/crc dispatches through the batching offload "
               "service (false = per-op inline dispatch)"),
        Option("ec_offload_max_batch_bytes", "size",
               _DEFAULTS["max_batch_bytes"],
               "flush a batch bucket at this many bytes", minimum=4096),
        Option("ec_offload_linger_ms", "float", _DEFAULTS["linger_ms"],
               "max time a job waits for batch-mates before the bucket "
               "ships anyway", minimum=0.0),
        Option("ec_offload_max_queue_bytes", "size",
               _DEFAULTS["max_queue_bytes"],
               "admission-queue byte budget (backpressure past this)",
               minimum=4096),
        Option("ec_offload_pipeline_depth", "int",
               _DEFAULTS["pipeline_depth"],
               "staging slots per device (H2D of batch N+1 overlaps "
               "compute of batch N); startup only", minimum=1),
        Option("ec_offload_breaker_threshold", "int",
               _DEFAULTS["breaker_threshold"],
               "consecutive errors on one device before removing it "
               "from rotation", minimum=1),
        Option("ec_offload_breaker_reset_s", "secs",
               _DEFAULTS["breaker_reset_s"],
               "per-device cooldown before a half-open probe batch"),
        Option("ec_offload_crc_device", "bool", _DEFAULTS["crc_device"],
               "run CrcJobs on the device kernel (host-native when the "
               "transfer link is the bottleneck)"),
        Option("ec_offload_device_count", "int",
               _DEFAULTS["device_count"],
               "dispatch targets to fan batches across (0 = every "
               "visible device); rebuilds the mesh on change",
               minimum=0),
        Option("ec_offload_device_shard_bytes", "size",
               _DEFAULTS["device_shard_bytes"],
               "batches at or past this stripe-shard across the whole "
               "device mesh instead of one chip", minimum=4096),
        Option("ec_offload_device_spill_threshold", "int",
               _DEFAULTS["device_spill_threshold"],
               "inflight-batch lead over the least-busy device at "
               "which an affine bucket spills off its preferred chip",
               minimum=1),
        Option("ec_offload_device_peak_gbps", "float",
               _DEFAULTS["device_peak_gbps"],
               "device memory-bandwidth peak in GB/s for the roofline "
               "gauges (kernel_*_roofline_pct); 0 leaves them at zero "
               "and only the absolute GB/s gauges move", minimum=0.0),
    ]


def register_config(config) -> None:
    """Declare the ec_offload_* options on `config` (idempotent) and
    hot-apply changes to the module defaults and every live service —
    `config set ec_offload_linger_ms 5` over an admin socket retunes
    the batcher live (md_config_obs_t-style)."""
    from ceph_tpu.utils.config import ConfigError
    names = []
    for opt in OFFLOAD_OPTIONS():
        names.append(opt.name)
        try:
            config.declare(opt)
        except ConfigError:
            pass                    # another daemon already declared it

    def _on_change(name: str, value) -> None:
        key = name[len("ec_offload_"):]
        if key in _DEFAULTS:
            _DEFAULTS[key] = value
        # snapshot under the lock: a shard loop's first get_service()
        # can insert mid-iteration (observers fire on arbitrary threads)
        with _instances_lock:
            services = list(_instances.values())
        for svc in services:
            svc.apply_setting(name, value)

    config.add_observer(tuple(names), _on_change)
    # apply only values this Config actually OVERRIDES (conf file /
    # mon store / cli): re-applying plain defaults here would let every
    # later daemon boot in the process silently revert knobs an
    # operator tuned at runtime on another daemon's socket
    diff = config.diff()
    for name in names:
        if name in diff:
            _on_change(name, config.get(name))
