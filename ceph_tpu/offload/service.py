"""Process-wide device offload service: dynamic batching for EC + crc.

The round-5 verdict's core complaint: the raw TPU kernel encodes at
~32 GB/s, yet the in-situ cluster data path crawls at tens of MB/s,
because every PG op dispatches its own tiny synchronous encode — each
one paying the full launch + H2D round trip (~2 ms through the transfer
tunnel) for a few KiB of work, serialized on the event loop. That is
the per-op software overhead that dominates online erasure coding in
real systems (arXiv:1709.05365); the cure is the admission-queue /
continuous-batching discipline of an inference server (arXiv:2108.02692
uses the same staging shape for XOR-network kernels).

This module is that admission queue, one per event loop (i.e. one per
vstart-style cluster — every OSD, and any Checksummer caller, in the
process shares it):

  * submit(): callers hand over an `EncodeJob`/`DecodeJob`/`CrcJob`
    (numpy batch + codec identity) and await a future. Admission is
    gated by a byte-budget `Throttle` — when the queue is full the
    caller waits, so a wedged device backpressures the write path
    instead of buffering unboundedly.
  * size-bucketed dynamic batcher: jobs coalesce per bucket key
    (op kind + coding matrix + chunk geometry — only shape-compatible
    work can share a device dispatch). A bucket flushes when its bytes
    reach `ec_offload_max_batch_bytes` or when the oldest job has
    lingered `ec_offload_linger_ms` (continuous batching's flush rule).
  * double-buffered staging: dispatches run in a small thread pool
    behind a `pipeline_depth`-deep semaphore, so H2D for batch N+1
    overlaps device compute for batch N while the event loop keeps
    accumulating batch N+2.
  * circuit breaker: a device error fails the batch over to the host
    codec (bit-identical output — the GF(2^8) matrix apply), trips a
    `degraded` flag for `ec_offload_breaker_reset_s`, then lets one
    probe batch try the device again (half-open). The flag rides every
    OSD's MgrClient health report; the mgr digests it into a
    TPU_OFFLOAD_DEGRADED cluster health check.

Observability: tracer spans `offload_queue_wait` (admission -> dispatch)
and `offload_batch` (ops/bytes/device tags) nest under the submitting
op's trace; perf counters under the process-wide "offload" logger
(queue depth gauge, batch-size/bytes histograms, coalesced-op and
fallback counters) ride `perf dump`, the mgr report stream, and the
admin-socket `ec offload status` command.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import contextvars
import threading
import time
from typing import Any, Callable

import numpy as np

from ceph_tpu.qa import faultinject
from ceph_tpu.utils import copytrack, tracer
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.perf_counters import (TYPE_GAUGE, TYPE_HISTOGRAM,
                                          PerfCountersCollection)
from ceph_tpu.utils.throttle import Throttle

# -- module-wide defaults (mirrored by the ec_offload_* config options) ------

_DEFAULTS: dict[str, Any] = {
    "enabled": True,
    "max_batch_bytes": 8 << 20,
    "linger_ms": 2.0,
    "max_queue_bytes": 64 << 20,
    "pipeline_depth": 2,
    "breaker_threshold": 1,
    "breaker_reset_s": 30.0,
    "crc_device": False,
}

#: one service per event loop: a loop is one cluster's world (tests and
#: benches run many clusters through sequential asyncio.run calls, and a
#: service holds loop-bound primitives)
_instances: dict[Any, "OffloadService"] = {}

_pool: concurrent.futures.ThreadPoolExecutor | None = None


def _executor() -> concurrent.futures.ThreadPoolExecutor:
    global _pool
    if _pool is None:
        # 2 workers so transfer/compute of consecutive batches overlap
        # (the double-buffer half of the staging design); the inflight
        # semaphore bounds how many batches can occupy them
        _pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="ec-offload")
    return _pool


def _perf():
    coll = PerfCountersCollection.instance()
    pc = coll.get("offload")
    if pc is None:
        pc = coll.create("offload")
        pc.add("jobs", description="ops submitted to the offload queue")
        pc.add("batches", description="device batches dispatched")
        pc.add("coalesced_ops",
               description="ops that shared a device batch with others")
        pc.add("fallback_ops",
               description="ops served by the host codec fallback")
        pc.add("breaker_trips",
               description="circuit-breaker trips (device -> degraded)")
        pc.add("batch_ops", type=TYPE_HISTOGRAM,
               description="ops coalesced per device batch")
        pc.add("batch_bytes", type=TYPE_HISTOGRAM,
               description="bytes per device batch")
        pc.add("queue_wait_us", type=TYPE_HISTOGRAM,
               description="admission-to-dispatch queue wait (µs)")
        pc.add("queue_bytes", type=TYPE_GAUGE,
               description="bytes admitted and not yet completed")
        pc.add("inflight_batches", type=TYPE_GAUGE,
               description="batches occupying staging slots")
    return pc


class _Job:
    """One submitted op: a stripe/block batch plus its completion."""

    __slots__ = ("data", "rows", "nbytes", "fut", "span", "t_submit")

    def __init__(self, data: np.ndarray, fut: asyncio.Future):
        self.data = data
        self.rows = data.shape[0]
        self.nbytes = int(data.nbytes)
        self.fut = fut
        self.span = tracer.start_span("offload_queue_wait")
        self.t_submit = time.perf_counter()


class _Bucket:
    """Pending jobs that can share one device dispatch."""

    __slots__ = ("jobs", "nbytes", "dispatch", "fallback", "linger_task",
                 "uses_device")

    def __init__(self, dispatch: Callable, fallback: Callable,
                 uses_device: bool):
        self.jobs: list[_Job] = []
        self.nbytes = 0
        self.dispatch = dispatch
        self.fallback = fallback
        self.linger_task: asyncio.Task | None = None
        # host-native buckets (e.g. CrcJobs with crc_device off) bypass
        # the circuit breaker entirely: their success says nothing about
        # the device, and must not close a tripped breaker
        self.uses_device = uses_device


class OffloadService:
    """The per-loop admission queue + batcher + breaker (see module doc)."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self.enabled = bool(_DEFAULTS["enabled"])
        self.max_batch_bytes = int(_DEFAULTS["max_batch_bytes"])
        self.linger_ms = float(_DEFAULTS["linger_ms"])
        self.pipeline_depth = max(1, int(_DEFAULTS["pipeline_depth"]))
        self.breaker_threshold = max(1, int(_DEFAULTS["breaker_threshold"]))
        self.breaker_reset_s = float(_DEFAULTS["breaker_reset_s"])
        self.crc_device = bool(_DEFAULTS["crc_device"])
        self._throttle = Throttle("ec_offload_queue",
                                  int(_DEFAULTS["max_queue_bytes"]))
        self._space = asyncio.Event()
        self._inflight = asyncio.Semaphore(self.pipeline_depth)
        self._buckets: dict[tuple, _Bucket] = {}
        self._tasks: set[asyncio.Task] = set()
        self.perf = _perf()
        # per-instance stats (the shared perf logger spans every cluster
        # the process ever booted; these are this loop's numbers)
        self.stats = {"jobs": 0, "batches": 0, "coalesced_ops": 0,
                      "fallback_ops": 0, "breaker_trips": 0,
                      "batched_ops": 0}
        # per-device utilization: busy wall time / bytes / batches per
        # dispatch target. Today every device batch lands on one
        # accelerator; fallback and host-native batches are attributed
        # to "host". The mesh fan-out grades its balance against these.
        self.device_stats: dict[str, dict] = {}
        # guards device_stats against admin-socket-thread readers
        # (`ec offload status` / the MgrClient device_cb) racing the
        # loop's first-seen-device key inserts: unlike self.stats, the
        # key set grows at runtime
        self._dev_lock = threading.Lock()
        self._dev_label: str | None = None
        # circuit breaker
        self.degraded = False
        self._degraded_since = 0.0
        self._consec_failures = 0
        self._probe_inflight = False
        self._last_error = ""

    # -- config --------------------------------------------------------------

    @property
    def max_queue_bytes(self) -> int:
        return self._throttle.max

    def apply_setting(self, name: str, value: Any) -> None:
        """Apply one ec_offload_* option (config-observer hot path)."""
        if name == "ec_offload_enabled":
            self.enabled = bool(value)
        elif name == "ec_offload_max_batch_bytes":
            self.max_batch_bytes = int(value)
        elif name == "ec_offload_linger_ms":
            self.linger_ms = float(value)
        elif name == "ec_offload_max_queue_bytes":
            self._throttle.reset_max(int(value))
            # observers can fire from an admin-socket thread: the waiter
            # event is loop-bound, so hop onto the loop to rotate it
            try:
                on_loop = asyncio.get_running_loop() is self._loop
            except RuntimeError:
                on_loop = False
            if on_loop:
                self._wake_waiters()
            elif not self._loop.is_closed():
                self._loop.call_soon_threadsafe(self._wake_waiters)
        elif name == "ec_offload_breaker_threshold":
            self.breaker_threshold = max(1, int(value))
        elif name == "ec_offload_breaker_reset_s":
            self.breaker_reset_s = float(value)
        elif name == "ec_offload_crc_device":
            self.crc_device = bool(value)

    # -- public job API ------------------------------------------------------

    async def encode(self, ec_impl, stripes: np.ndarray) -> np.ndarray:
        """(S, k, C) data stripes -> (S, m, C) parity via the plugin's
        batched device API, coalesced with concurrent callers."""
        key = ("enc", ec_impl.coding_matrix.tobytes(), stripes.shape[2])

        def dispatch(batch: np.ndarray) -> np.ndarray:
            return np.asarray(ec_impl.encode_stripes(batch))

        def fallback(batch: np.ndarray) -> np.ndarray:
            return _host_apply(ec_impl.coding_matrix, batch)

        return await self._submit(key, stripes, dispatch, fallback)

    async def decode(self, ec_impl, avail_ids: tuple[int, ...],
                     want_ids: tuple[int, ...],
                     chunks: np.ndarray) -> np.ndarray:
        """(S, k, C) available chunks (stacked in avail_ids order) ->
        (S, len(want), C) reconstructed chunks. Jobs coalesce only with
        the same erasure pattern — a different survivor set is a
        different recovery matrix, hence a different bucket."""
        avail_ids, want_ids = tuple(avail_ids), tuple(want_ids)
        key = ("dec", ec_impl.coding_matrix.tobytes(), avail_ids, want_ids,
               chunks.shape[2])

        def dispatch(batch: np.ndarray) -> np.ndarray:
            return np.asarray(ec_impl.decode_stripes(avail_ids, want_ids,
                                                     batch))

        def fallback(batch: np.ndarray) -> np.ndarray:
            from ceph_tpu.ops import rs_codec
            R = rs_codec.recovery_matrix(ec_impl.coding_matrix, avail_ids,
                                         want_ids)
            return _host_apply(R, batch)

        return await self._submit(key, chunks, dispatch, fallback)

    async def crc32c_blocks(self, blocks: np.ndarray,
                            block_size: int) -> np.ndarray:
        """(N, block_size) uint8 -> (N,) uint32 per-block crc32c.
        Host-native by default (the H2D tunnel makes device crc a loss
        for host-resident buffers; flip ec_offload_crc_device on
        hardware where the link is wide) — either way the work leaves
        the event loop and coalesces across callers."""
        key = ("crc", bool(self.crc_device), block_size)
        use_device = self.crc_device

        def dispatch(batch: np.ndarray) -> np.ndarray:
            if use_device:
                from ceph_tpu.ops import crc32c as crc_dev
                return np.asarray(crc_dev.get_device_crc(block_size)(batch))
            return _host_crc(batch, block_size)

        def fallback(batch: np.ndarray) -> np.ndarray:
            return _host_crc(batch, block_size)

        return await self._submit(key, np.ascontiguousarray(blocks),
                                  dispatch, fallback,
                                  uses_device=use_device)

    async def repair(self, ec_impl, helpers: tuple[int, ...],
                     want: tuple[int, ...], frags: np.ndarray,
                     chunk_size: int) -> np.ndarray:
        """Sub-chunk regenerating repair units (the CLAY single-shard
        rebuild): (N, d, repair_per_chunk) helper fragment planes ->
        (N, chunk_size) rebuilt chunks, coalesced per (codec, erasure
        pattern, geometry) bucket like any DecodeJob. Host-staged
        (uses_device=False): the regenerating transform is the plugin's
        own multi-phase kernel and its success says nothing about the
        accelerator — the win here is coalescing + leaving the event
        loop, and the ~qx smaller fetch already happened at the
        gather."""
        helpers, want = tuple(helpers), tuple(want)
        # codec identity by PROFILE, not instance: every PG backend
        # holds its own plugin object, and keying on id() would defeat
        # the cross-PG coalescing this job exists for (same profile =>
        # same deterministic repair math, so any member's impl serves
        # the whole bucket)
        try:
            ident = tuple(sorted(ec_impl.get_profile().items()))
        except Exception:
            ident = id(ec_impl)
        key = ("rep", type(ec_impl).__name__, ident, helpers, want,
               frags.shape[2], chunk_size)

        def dispatch(batch: np.ndarray) -> np.ndarray:
            out = np.empty((batch.shape[0], chunk_size), dtype=np.uint8)
            for u in range(batch.shape[0]):
                chunks = {h: batch[u, j].tobytes()
                          for j, h in enumerate(helpers)}
                dec = ec_impl.decode(list(want), chunks, chunk_size)
                out[u] = np.frombuffer(dec[want[0]], dtype=np.uint8)
            return out

        return await self._submit(key, np.ascontiguousarray(frags),
                                  dispatch, dispatch, uses_device=False)

    # -- admission -----------------------------------------------------------

    async def _submit(self, key: tuple, data: np.ndarray,
                      dispatch: Callable, fallback: Callable,
                      uses_device: bool = True) -> np.ndarray:
        if not self.enabled:
            return self._inline(data, dispatch, fallback, uses_device)
        nbytes = int(data.nbytes)
        await self._acquire(nbytes)
        self.perf.inc("jobs")
        self.stats["jobs"] += 1
        fut: asyncio.Future = self._loop.create_future()
        job = _Job(data, fut)
        bucket = self._buckets.get(key)
        if bucket is None:
            bucket = self._buckets[key] = _Bucket(dispatch, fallback,
                                                  uses_device)
            bucket.linger_task = self._loop.create_task(
                self._linger_flush(key))
            self._track(bucket.linger_task)
        bucket.jobs.append(job)
        bucket.nbytes += nbytes
        if bucket.nbytes >= self.max_batch_bytes:
            self._flush_bucket(key)
        try:
            return await fut
        finally:
            # admission budget is held until the job's batch completed
            self._release(nbytes)

    def _inline(self, data: np.ndarray, dispatch: Callable,
                fallback: Callable, uses_device: bool) -> np.ndarray:
        """Bypass (ec_offload_enabled=false): the pre-service per-op
        synchronous dispatch, breaker semantics included — this is the
        baseline the bench's inline comparison measures."""
        self.perf.inc("jobs")
        self.stats["jobs"] += 1
        nbytes = int(data.nbytes)
        if not uses_device:
            t0 = time.perf_counter()
            out = dispatch(data)
            self._note_device("host", 1, nbytes,
                              time.perf_counter() - t0)
            self._note_batch(1, nbytes)
            return out
        if self._device_allowed():
            try:
                t0 = time.perf_counter()
                if faultinject.should_fail_device():
                    raise RuntimeError("injected device failure")
                out = dispatch(data)
                self._device_success()
                self._note_device(self._device_label(), 1, nbytes,
                                  time.perf_counter() - t0)
                self._note_batch(1, nbytes)
                return out
            except Exception as e:
                self._device_failure(e)
        self.perf.inc("fallback_ops")
        self.stats["fallback_ops"] += 1
        t0 = time.perf_counter()
        out = fallback(data)
        self._note_device("host", 1, nbytes,
                          time.perf_counter() - t0, fallback=True)
        return out

    async def _acquire(self, nbytes: int) -> None:
        if 0 < self._throttle.max <= nbytes:
            # oversized job: admit unconditionally (transient overshoot)
            # rather than wait for an exactly-empty queue — smaller jobs
            # have no FIFO ordering against it and would starve it
            # forever under sustained load; normal admissions then block
            # until the big one releases
            self._throttle.take(nbytes)
        else:
            while not self._throttle.get_or_fail(nbytes):
                evt = self._space
                await evt.wait()
        self.perf.set("queue_bytes", self._throttle.current)

    def _release(self, nbytes: int) -> None:
        self._throttle.put(nbytes)
        self.perf.set("queue_bytes", self._throttle.current)
        self._wake_waiters()

    def _wake_waiters(self) -> None:
        evt, self._space = self._space, asyncio.Event()
        evt.set()

    # -- batching ------------------------------------------------------------

    def _track(self, task: asyncio.Task) -> None:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _linger_flush(self, key: tuple) -> None:
        """Deadline flush: after linger_ms the bucket ships however full
        it is (bounded latency for a lone op on an idle cluster)."""
        await asyncio.sleep(self.linger_ms / 1000.0)
        bucket = self._buckets.pop(key, None)
        if bucket is not None and bucket.jobs:
            self._track(self._loop.create_task(self._run_batch(bucket)))

    def _flush_bucket(self, key: tuple) -> None:
        bucket = self._buckets.pop(key, None)
        if bucket is None:
            return
        if bucket.linger_task is not None:
            bucket.linger_task.cancel()
        if bucket.jobs:
            self._track(self._loop.create_task(self._run_batch(bucket)))

    def _on_loop(self) -> bool:
        try:
            return asyncio.get_running_loop() is self._loop
        except RuntimeError:
            return False

    def _from_loop(self, fn):
        """Run `fn` on the service's event loop and return its result —
        admin-socket hooks call from their own thread, and _buckets is
        only coherent on the loop (a dict mutating mid-iteration raises
        RuntimeError under exactly the load the command inspects)."""
        if self._on_loop():
            return fn()
        if self._loop.is_closed():
            return fn()         # loop gone: nothing is mutating anymore

        async def run():
            return fn()
        try:
            return asyncio.run_coroutine_threadsafe(
                run(), self._loop).result(timeout=2.0)
        except concurrent.futures.TimeoutError:
            # loop blocked (possibly by the very caller awaiting this
            # admin response in-process): serve a best-effort direct
            # snapshot, retrying the rare mid-mutation iteration
            for _ in range(5):
                try:
                    return fn()
                except RuntimeError:
                    continue
            return fn()

    def flush(self) -> dict:
        """Force-flush every pending bucket now (admin `ec offload
        flush`). Thread-safe: admin-socket hooks run off-loop, and the
        mutating work only ever executes ON the loop — a busy loop gets
        a call_soon_threadsafe wake instead of an off-thread mutation
        (popping buckets from a foreign thread could strand their jobs'
        futures forever if create_task then fails)."""
        def impl():
            pending = {str(k): len(b.jobs)
                       for k, b in self._buckets.items()}
            self._flush_all()
            return {"flushed_buckets": len(pending),
                    "pending_ops": pending}
        if self._on_loop():
            return impl()
        if self._loop.is_closed():
            return {"flushed_buckets": 0, "pending_ops": {},
                    "error": "event loop closed"}

        async def run():
            return impl()
        try:
            return asyncio.run_coroutine_threadsafe(
                run(), self._loop).result(timeout=2.0)
        except concurrent.futures.TimeoutError:
            self._loop.call_soon_threadsafe(self._flush_all)
            return {"flushed_buckets": 0, "pending_ops": {},
                    "scheduled": True,
                    "error": "loop busy; flush scheduled"}

    def _flush_all(self) -> None:
        for key in list(self._buckets):
            self._flush_bucket(key)

    async def drain(self) -> None:
        """Flush and wait for every in-flight batch (tests/bench)."""
        self._flush_all()
        while self._tasks:
            await asyncio.gather(*list(self._tasks),
                                 return_exceptions=True)

    async def _run_batch(self, bucket: _Bucket) -> None:
        jobs = bucket.jobs
        try:
            # the semaphore wait is INSIDE the try: a cancel delivered
            # while queued behind full staging slots must still cancel
            # the job futures, or their submitters hang forever
            async with self._inflight:
                self.perf.inc("inflight_batches")
                try:
                    now = time.perf_counter()
                    for j in jobs:
                        self.perf.hist_add("queue_wait_us",
                                           (now - j.t_submit) * 1e6)
                        if j.span is not None:
                            j.span.set_tag("batch_ops", len(jobs))
                            j.span.finish()
                    # a lone job's array is handed to the device as-is
                    # (referenced); coalesced jobs pay one stacking copy
                    # — the bufferlist->staging leg of the copy ledger
                    t_stack = time.perf_counter()
                    stacked = jobs[0].data if len(jobs) == 1 else \
                        np.concatenate([j.data for j in jobs], axis=0)
                    stack_s = time.perf_counter() - t_stack
                    nbytes = int(stacked.nbytes)
                    if len(jobs) == 1:
                        copytrack.referenced("buffer_to_staging", nbytes)
                        stack_us = 0.0
                    else:
                        copytrack.copied("buffer_to_staging", nbytes,
                                         stack_s)
                        stack_us = round(stack_s * 1e6, 1)
                    with tracer.span("offload_batch") as sp:
                        out, on_device = await self._dispatch(
                            bucket, stacked, len(jobs))
                        if sp is not None:
                            sp.set_tag("ops", len(jobs))
                            sp.set_tag("bytes", nbytes)
                            sp.set_tag("device", on_device)
                            sp.set_tag("copy_bytes",
                                       nbytes if len(jobs) > 1 else 0)
                            sp.set_tag("copy_us", stack_us)
                    self._note_batch(len(jobs), nbytes)
                    row = 0
                    for j in jobs:
                        if not j.fut.done():
                            j.fut.set_result(out[row:row + j.rows])
                        row += j.rows
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    for j in jobs:
                        if not j.fut.done():
                            j.fut.set_exception(e)
                finally:
                    self.perf.dec("inflight_batches")
        except asyncio.CancelledError:
            for j in jobs:
                if not j.fut.done():
                    j.fut.cancel()
            raise

    async def _in_staging_pool(self, fn: Callable,
                               stacked: np.ndarray) -> np.ndarray:
        """Run one batch kernel in the staging pool UNDER the caller's
        contextvar context: run_in_executor does not propagate it, which
        would orphan the plugin's tpu_*_dispatch spans into fresh root
        traces instead of nesting under offload_batch."""
        ctx = contextvars.copy_context()
        return await self._loop.run_in_executor(
            _executor(), lambda: ctx.run(fn, stacked))

    async def _dispatch(self, bucket: _Bucket, stacked: np.ndarray,
                        n_ops: int) -> tuple[np.ndarray, bool]:
        """One staged device dispatch with host-codec failover."""
        nbytes = int(stacked.nbytes)
        if not bucket.uses_device:
            t0 = time.perf_counter()
            out = await self._in_staging_pool(bucket.dispatch, stacked)
            self._note_device("host", n_ops, nbytes,
                              time.perf_counter() - t0)
            return out, False
        if self._device_allowed():
            try:
                t0 = time.perf_counter()
                if faultinject.should_fail_device():
                    raise RuntimeError("injected device failure")
                out = await self._in_staging_pool(bucket.dispatch, stacked)
                self._device_success()
                self._note_device(self._device_label(), n_ops, nbytes,
                                  time.perf_counter() - t0)
                return out, True
            except Exception as e:
                self._device_failure(e)
        self.perf.inc("fallback_ops", n_ops)
        self.stats["fallback_ops"] += n_ops
        t0 = time.perf_counter()
        out = await self._in_staging_pool(bucket.fallback, stacked)
        self._note_device("host", n_ops, nbytes,
                          time.perf_counter() - t0, fallback=True)
        return out, False

    def _device_label(self) -> str:
        """Identity of the accelerator device batches land on (the
        `ceph_device` metric label). Resolved once; host fallback and
        host-native batches use the fixed "host" label instead."""
        if self._dev_label is None:
            try:
                import jax
                d = jax.devices()[0]
                self._dev_label = f"{d.platform}:{d.id}"
            except Exception:
                self._dev_label = "device:0"
        return self._dev_label

    def _note_device(self, device: str, n_ops: int, nbytes: int,
                     busy_s: float, fallback: bool = False) -> None:
        with self._dev_lock:
            d = self.device_stats.get(device)
            if d is None:
                d = self.device_stats[device] = {
                    "batches": 0, "ops": 0, "bytes": 0, "busy_s": 0.0,
                    "fallback_ops": 0}
            d["batches"] += 1
            d["ops"] += n_ops
            d["bytes"] += nbytes
            d["busy_s"] += busy_s
            if fallback:
                d["fallback_ops"] += n_ops

    def device_snapshot(self) -> dict[str, dict]:
        """Consistent copy of device_stats, safe off the loop thread."""
        with self._dev_lock:
            return {dev: dict(d) for dev, d in self.device_stats.items()}

    def device_metrics(self) -> dict:
        """Per-device counters for the MgrClient report path: the mgr
        stores them per daemon and the exporter renders each as a
        `ceph_device`-labeled family."""
        return {dev: {"offload_device_busy_seconds": round(d["busy_s"], 6),
                      "offload_device_bytes": d["bytes"],
                      "offload_device_batches": d["batches"],
                      "offload_device_ops": d["ops"],
                      "offload_device_fallback_ops": d["fallback_ops"]}
                for dev, d in self.device_snapshot().items()}

    def _note_batch(self, n_ops: int, nbytes: int) -> None:
        self.perf.inc("batches")
        self.perf.inc("coalesced_ops", max(0, n_ops - 1))
        self.perf.hist_add("batch_ops", n_ops)
        self.perf.hist_add("batch_bytes", nbytes)
        self.stats["batches"] += 1
        self.stats["batched_ops"] += n_ops
        self.stats["coalesced_ops"] += max(0, n_ops - 1)

    # -- circuit breaker -----------------------------------------------------

    def _device_allowed(self) -> bool:
        if not self.degraded:
            return True
        if (time.monotonic() - self._degraded_since >= self.breaker_reset_s
                and not self._probe_inflight):
            self._probe_inflight = True      # half-open: one probe batch
            return True
        return False

    def _device_success(self) -> None:
        self._probe_inflight = False
        self._consec_failures = 0
        if self.degraded:
            self.degraded = False
            dout("offload", 1, "device codec recovered; leaving degraded "
                               "mode (TPU_OFFLOAD_DEGRADED clears)")

    def _device_failure(self, e: Exception) -> None:
        self._probe_inflight = False
        self._consec_failures += 1
        self._last_error = f"{type(e).__name__}: {e}"
        if self.degraded:
            self._degraded_since = time.monotonic()    # probe failed
            return
        if self._consec_failures >= self.breaker_threshold:
            self.degraded = True
            self._degraded_since = time.monotonic()
            self.perf.inc("breaker_trips")
            self.stats["breaker_trips"] += 1
            dout("offload", 0, f"device codec failing ({self._last_error}); "
                               f"falling back to host codec for "
                               f"{self.breaker_reset_s:.0f}s "
                               f"(TPU_OFFLOAD_DEGRADED)")

    # -- surfaces ------------------------------------------------------------

    def health_metrics(self) -> dict:
        """The MgrClient health blob: the mon/mgr health engine turns
        `degraded` into the TPU_OFFLOAD_DEGRADED check."""
        return {"degraded": self.degraded,
                "degraded_for_s": round(
                    time.monotonic() - self._degraded_since, 1)
                if self.degraded else 0.0,
                "fallback_ops": self.stats["fallback_ops"],
                "breaker_trips": self.stats["breaker_trips"],
                "last_error": self._last_error if self.degraded else ""}

    def status(self) -> dict:
        """Admin-socket `ec offload status` (loop-coherent off-thread)."""
        return self._from_loop(self._status_impl)

    def _status_impl(self) -> dict:
        s = self.stats
        return {
            "enabled": self.enabled,
            "degraded": self.degraded,
            "last_error": self._last_error,
            "settings": {"max_batch_bytes": self.max_batch_bytes,
                         "linger_ms": self.linger_ms,
                         "max_queue_bytes": self.max_queue_bytes,
                         "pipeline_depth": self.pipeline_depth,
                         "breaker_threshold": self.breaker_threshold,
                         "breaker_reset_s": self.breaker_reset_s,
                         "crc_device": self.crc_device},
            "queue_bytes": self._throttle.current,
            "pending_buckets": {str(k): {"ops": len(b.jobs),
                                         "bytes": b.nbytes}
                                for k, b in self._buckets.items()},
            "jobs": s["jobs"],
            "batches": s["batches"],
            "coalesced_ops": s["coalesced_ops"],
            "fallback_ops": s["fallback_ops"],
            "breaker_trips": s["breaker_trips"],
            "mean_batch_ops": round(s["batched_ops"] / s["batches"], 3)
            if s["batches"] else 0.0,
            "devices": {dev: dict(d, busy_s=round(d["busy_s"], 6))
                        for dev, d in self.device_snapshot().items()},
        }


# -- host fallback kernels ---------------------------------------------------

def _host_apply(M: np.ndarray, batch: np.ndarray) -> np.ndarray:
    """(S, k, C) through the (r, k) GF(2^8) matrix on host -> (S, r, C);
    bit-identical to the device kernel (same field, same matrices)."""
    from ceph_tpu.ec import gf256
    S, k, C = batch.shape
    flat = np.ascontiguousarray(
        batch.transpose(1, 0, 2)).reshape(k, S * C)
    out = gf256.mat_vec_apply(np.ascontiguousarray(M, dtype=np.uint8), flat)
    return np.ascontiguousarray(
        out.reshape(M.shape[0], S, C).transpose(1, 0, 2))


def _host_crc(batch: np.ndarray, block_size: int) -> np.ndarray:
    from ceph_tpu.native import ec_native
    return ec_native.crc32c_blocks(
        np.ascontiguousarray(batch).reshape(-1), block_size)


# -- per-loop instance + config plumbing -------------------------------------

def get_service() -> OffloadService:
    """The running loop's service (created on first use)."""
    loop = asyncio.get_running_loop()
    svc = _instances.get(loop)
    if svc is None:
        for stale in [lp for lp in _instances if lp.is_closed()]:
            del _instances[stale]
        svc = _instances[loop] = OffloadService(loop)
    return svc


def get_service_or_none() -> OffloadService | None:
    """get_service, or None outside a running event loop (sync callers
    fall back to inline dispatch)."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return None
    return get_service()


def set_enabled(flag: bool) -> None:
    """Module-wide toggle (bench harness): defaults + live instances."""
    _DEFAULTS["enabled"] = bool(flag)
    for svc in _instances.values():
        svc.enabled = bool(flag)


def OFFLOAD_OPTIONS():
    """The ec_offload_* option schema (declared per daemon Config)."""
    from ceph_tpu.utils.config import Option
    return [
        Option("ec_offload_enabled", "bool", _DEFAULTS["enabled"],
               "route EC/crc dispatches through the batching offload "
               "service (false = per-op inline dispatch)"),
        Option("ec_offload_max_batch_bytes", "size",
               _DEFAULTS["max_batch_bytes"],
               "flush a batch bucket at this many bytes", minimum=4096),
        Option("ec_offload_linger_ms", "float", _DEFAULTS["linger_ms"],
               "max time a job waits for batch-mates before the bucket "
               "ships anyway", minimum=0.0),
        Option("ec_offload_max_queue_bytes", "size",
               _DEFAULTS["max_queue_bytes"],
               "admission-queue byte budget (backpressure past this)",
               minimum=4096),
        Option("ec_offload_pipeline_depth", "int",
               _DEFAULTS["pipeline_depth"],
               "staging slots (H2D of batch N+1 overlaps compute of "
               "batch N); startup only", minimum=1),
        Option("ec_offload_breaker_threshold", "int",
               _DEFAULTS["breaker_threshold"],
               "consecutive device errors before tripping to host "
               "fallback", minimum=1),
        Option("ec_offload_breaker_reset_s", "secs",
               _DEFAULTS["breaker_reset_s"],
               "degraded cooldown before a device probe batch"),
        Option("ec_offload_crc_device", "bool", _DEFAULTS["crc_device"],
               "run CrcJobs on the device kernel (host-native when the "
               "transfer link is the bottleneck)"),
    ]


def register_config(config) -> None:
    """Declare the ec_offload_* options on `config` (idempotent) and
    hot-apply changes to the module defaults and every live service —
    `config set ec_offload_linger_ms 5` over an admin socket retunes
    the batcher live (md_config_obs_t-style)."""
    from ceph_tpu.utils.config import ConfigError
    names = []
    for opt in OFFLOAD_OPTIONS():
        names.append(opt.name)
        try:
            config.declare(opt)
        except ConfigError:
            pass                    # another daemon already declared it

    def _on_change(name: str, value) -> None:
        key = name[len("ec_offload_"):]
        if key in _DEFAULTS:
            _DEFAULTS[key] = value
        for svc in _instances.values():
            svc.apply_setting(name, value)

    config.add_observer(tuple(names), _on_change)
    # apply only values this Config actually OVERRIDES (conf file /
    # mon store / cli): re-applying plain defaults here would let every
    # later daemon boot in the process silently revert knobs an
    # operator tuned at runtime on another daemon's socket
    diff = config.diff()
    for name in names:
        if name in diff:
            _on_change(name, config.get(name))
