"""Process-wide TPU offload service — dynamic batching for the in-situ
EC data path (see service.py for the full design notes)."""
from ceph_tpu.offload.service import (OFFLOAD_OPTIONS, OffloadService,
                                      get_service, get_service_or_none,
                                      register_config, service_for,
                                      set_enabled)

__all__ = ["OFFLOAD_OPTIONS", "OffloadService", "get_service",
           "get_service_or_none", "register_config", "service_for",
           "set_enabled"]
