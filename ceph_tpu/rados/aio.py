"""Async completion API over the Objecter — the librados AIO /
neorados role.

Re-creation of the reference's async client surfaces:
  * `AioCompletion` (src/librados/AioCompletionImpl.h: is_complete /
    wait_for_complete / get_return_value / callbacks) wrapping an
    in-flight op;
  * dispatch returns IMMEDIATELY with a completion; results and errors
    surface when awaited (neorados' asio-future style collapsed onto
    asyncio);
  * an in-flight throttle caps CONCURRENTLY EXECUTING ops the way
    the Objecter's op budget does (objecter_inflight_ops / Throttle in
    src/osdc/Objecter.h); submission itself never blocks — a producer
    issuing unbounded fire-and-forget ops should interleave
    `aio_flush()` to bound its queue;
  * `aio_flush` (rados_aio_flush) waits for everything outstanding on
    the ioctx.
"""
from __future__ import annotations

import asyncio
from typing import Any, Callable

from ceph_tpu.utils import tracer


class AioCompletion:
    """One in-flight async op (AioCompletionImpl)."""

    def __init__(self):
        self._fut: asyncio.Future = asyncio.get_running_loop(
        ).create_future()
        self._callbacks: list[Callable[["AioCompletion"], None]] = []

    # -- producer side -------------------------------------------------------

    def _finish(self, result: Any = None,
                error: BaseException | None = None) -> None:
        if self._fut.done():
            return
        if error is not None:
            self._fut.set_exception(error)
            # mark retrieved: a fire-and-forget op that fails must not
            # spam "Future exception was never retrieved" at GC —
            # wait_for_complete still re-raises from the future
            self._fut.exception()
        else:
            self._fut.set_result(result)
        for cb in self._callbacks:
            try:
                cb(self)
            except Exception:
                pass

    # -- consumer side -------------------------------------------------------

    def is_complete(self) -> bool:
        return self._fut.done()

    async def wait_for_complete(self) -> Any:
        """Await the result (raises the op's error, like
        get_return_value returning rc<0)."""
        return await asyncio.shield(self._fut)

    def get_return_value(self) -> Any:
        """Result of a COMPLETED op (ValueError while in flight)."""
        if not self._fut.done():
            raise ValueError("operation still in flight")
        return self._fut.result()

    def add_callback(self, fn: Callable[["AioCompletion"], None]) -> None:
        """rados_aio_set_complete_callback: fires at completion (or
        immediately if already complete)."""
        if self._fut.done():
            fn(self)
        else:
            self._callbacks.append(fn)


class AioDispatcher:
    """Per-client submission engine: throttle + task tracking.

    Attached lazily to a RadosClient; IoCtx.aio_* routes through it."""

    MAX_INFLIGHT = 64          # objecter_inflight_ops-lite

    def __init__(self, max_inflight: int | None = None):
        self._throttle = asyncio.Semaphore(
            max_inflight or self.MAX_INFLIGHT)
        self._inflight: set[asyncio.Task] = set()

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    def submit(self, coro) -> AioCompletion:
        comp = AioCompletion()

        async def run():
            acquired = False
            try:
                # the task inherits the submitter's trace context, so an
                # aio op traced from application code stays one trace;
                # this span additionally shows throttle-queue wait
                # (elided on unsampled traces — rados_op covers it)
                with tracer.span_sampled_only("aio_op", "client"):
                    await self._throttle.acquire()
                    acquired = True
                    comp._finish(await coro)
            except asyncio.CancelledError as e:
                # record the op as failed, then PROPAGATE: swallowing
                # here made flush()/teardown cancellation a silent no-op
                # (the task kept running to loop close)
                comp._finish(error=e)
                raise
            except Exception as e:
                comp._finish(error=e)
            finally:
                if acquired:
                    self._throttle.release()
        t = asyncio.get_running_loop().create_task(run())
        self._inflight.add(t)
        t.add_done_callback(self._inflight.discard)
        return comp

    async def flush(self) -> None:
        """Wait for every outstanding op (rados_aio_flush). Errors stay
        in their completions — flush itself never raises."""
        while self._inflight:
            await asyncio.gather(*list(self._inflight),
                                 return_exceptions=True)
