"""librados-subset client: RadosClient + IoCtx over an Objecter-lite.

Re-creation of the reference client stack's essentials:
  * Objecter placement + retry (src/osdc/Objecter.cc:2783 _calc_target
    computes pg + primary from the osdmap; ops are resent on map epoch
    change rather than failed — :2286 _op_submit);
  * librados surface (src/librados/librados_c.cc:1308 rados_write ->
    IoCtxImpl::write -> operate): connect, pool I/O contexts, synchronous
    object ops, pool/profile admin via mon commands.

Idiomatic divergences: JSON command plane instead of the CLI encoding;
one lossy connection per OSD re-established on fault; a -11 reply or a
sub-op timeout triggers a map refresh + recompute instead of the
reference's epoch broadcast machinery.
"""
from __future__ import annotations

import asyncio
import time

from ceph_tpu.crush.crush import CRUSH_NONE
from ceph_tpu.crush.osdmap import Incremental, OSDMap
from ceph_tpu.msg.messages import (Message, MOSDOp, MOSDOpReply,
                                   MOSDOpThrottle, MWatchNotify,
                                   MWatchNotifyAck)
from ceph_tpu.msg.messenger import Connection, Dispatcher, Messenger, Policy
from ceph_tpu.mon.mon_client import MonClient
from ceph_tpu.utils import tracer
from ceph_tpu.utils.dout import dout

import json


class RadosError(Exception):
    def __init__(self, rc: int, message: str):
        super().__init__(f"rc={rc}: {message}")
        self.rc = rc


class ObjectNotFound(RadosError):
    pass


class RadosClient(Dispatcher):
    """rados_connect + Objecter-lite (placement, resend on epoch change)."""

    OP_TIMEOUT = 15.0
    ATTEMPT_TIMEOUT = 5.0
    # capped exponential backoff between resends (Objecter backoff
    # semantics): the first retry is immediate — it usually lands on a
    # freshly-elected primary after the map refresh — later ones slow
    # down so a storm of failed ops cannot hammer a recovering cluster
    BACKOFF_BASE = 0.05
    BACKOFF_MAX = 2.0

    def __init__(self, mon_addrs: list[tuple[str, int]],
                 auth_key: bytes | None = None,
                 name: str | None = None,
                 tenant: str | None = None):
        # client instance nonce: makes (nonce, seq) reqids globally
        # unique so OSDs can dedup retried non-idempotent ops
        # (osd_reqid_t semantics)
        import secrets
        self._nonce = secrets.randbits(48)
        # client identity (EntityName client.<id>): negotiated once per
        # msgr2 session at the HELLO handshake and stamped on every
        # MOSDOp, so the OSD's per-client accountant can attribute ops,
        # bytes, and tail latency to THIS client. Anonymous callers get
        # a nonce-derived id — still stable for the client's lifetime.
        if name:
            self.name = name if name.startswith("client.") \
                else f"client.{name}"
        else:
            self.name = f"client.{self._nonce:012x}"
        self.tenant = tenant
        self.messenger = Messenger(self.name, auth_key=auth_key,
                                   tenant=tenant)
        self.messenger.add_dispatcher(self)
        self.monc = MonClient(self.messenger, mon_addrs)
        self.monc.on_osdmap = self._on_osdmap
        self.osdmap = OSDMap()
        self._map_changed = asyncio.Event()
        self._tid = 0
        self._reqseq = 0
        # ops bounced by QoS shed admission control (MOSDOpThrottle
        # replies absorbed by the backoff-and-resend path)
        self.throttled_ops = 0
        self._waiters: dict[int, asyncio.Future] = {}
        self._osd_conns: dict[int, Connection] = {}
        # linger watches (Objecter linger ops): cookie -> registration;
        # re-sent on map change / connection reset so a watch survives
        # primary failover
        self._watches: dict[int, dict] = {}
        self._next_cookie = 1
        self._relinger_task: asyncio.Task | None = None
        self._relinger_pending = False
        # strong refs: the loop keeps only weak refs to tasks, and a
        # collected delivery task would silently swallow a notify
        self._notify_tasks: set[asyncio.Task] = set()

    # -- lifecycle -----------------------------------------------------------

    async def connect(self) -> None:
        await self.messenger.bind("127.0.0.1", 0)
        await self.monc.start()
        self.monc.subscribe("osdmap", 1)
        await self.wait_for_map()

    async def shutdown(self) -> None:
        await self.monc.close()
        await self.messenger.shutdown()

    # -- map handling --------------------------------------------------------

    def _on_osdmap(self, payload: dict) -> None:
        from ceph_tpu.crush.osdmap import apply_map_payload
        apply_map_payload(self.osdmap, payload)
        self.monc.sub_got("osdmap", self.osdmap.epoch)
        self._map_changed.set()
        self._schedule_relinger()

    async def wait_for_map(self, min_epoch: int = 1,
                           timeout: float = 15.0) -> None:
        deadline = time.monotonic() + timeout
        while self.osdmap.epoch < min_epoch:
            self._map_changed.clear()
            await self.monc.request_osdmap(self.osdmap.epoch)
            try:
                await asyncio.wait_for(
                    self._map_changed.wait(),
                    max(0.1, min(2.0, deadline - time.monotonic())))
            except asyncio.TimeoutError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no osdmap epoch >= {min_epoch}") from None

    # -- admin plane ---------------------------------------------------------

    async def command(self, cmd: dict, timeout: float = 30.0) -> dict:
        return await self.monc.command(cmd, timeout=timeout)

    async def pool_create(self, name: str, **kwargs) -> dict:
        out = await self.command({"prefix": "osd pool create", "pool": name,
                                  **{k: v for k, v in kwargs.items()}})
        # wait until our map shows the pool so I/O can target it
        deadline = time.monotonic() + 15.0
        while name not in self.osdmap.pool_names:
            if time.monotonic() > deadline:
                raise TimeoutError(f"pool {name!r} never appeared in map")
            await self.wait_for_map(self.osdmap.epoch + 1)
        return out

    def ioctx(self, pool_name: str) -> "IoCtx":
        return IoCtx(self, pool_name)

    # -- objecter ------------------------------------------------------------

    async def _osd_conn(self, osd: int) -> Connection:
        conn = self._osd_conns.get(osd)
        if conn is not None and not conn._closed and conn.connected:
            return conn
        a = self.osdmap.get_addr(osd)
        conn = await self.messenger.connect((a[0], int(a[1])),
                                            Policy.lossy_client())
        self._osd_conns[osd] = conn
        return conn

    async def submit(self, pool_name: str, oid: str, ops: list[dict],
                     data: bytes = b"", timeout: float | None = None,
                     pgid=None,
                     attempt_timeout: float | None = None
                     ) -> tuple[dict, bytes]:
        """Objecter::op_submit-lite: compute the target, send, resend on
        epoch change / wrong-primary / transport fault. `pgid` pins the
        target PG (PG-scoped ops like `list`). When tracing is on, this
        opens the ROOT span of the op's trace (where the head-sampling
        decision is drawn); every messenger hop and OSD-side stage
        nests under it."""
        if not tracer.active():
            return await self._submit_inner(pool_name, oid, ops, data,
                                            timeout, pgid, attempt_timeout)
        with tracer.span("rados_op", "client") as sp:
            if sp is not None:      # hot-toggle race: may disable mid-call
                sp.set_tag("pool", pool_name)
                sp.set_tag("oid", oid)
                sp.set_tag("ops", "+".join(o.get("op", "?") for o in ops))
                sp.set_tag("bytes", len(data))
                sp.set_tag("client", self.name)
            return await self._submit_inner(pool_name, oid, ops, data,
                                            timeout, pgid, attempt_timeout)

    async def _submit_inner(self, pool_name: str, oid: str,
                            ops: list[dict], data: bytes = b"",
                            timeout: float | None = None, pgid=None,
                            attempt_timeout: float | None = None
                            ) -> tuple[dict, bytes]:
        deadline = time.monotonic() + (timeout or self.OP_TIMEOUT)
        last = "no attempt"
        # one reqid per LOGICAL op, stable across retries: the PG's
        # dup-op index keys on it, so a retry whose first attempt
        # committed is answered from the log instead of re-executing
        self._reqseq += 1
        reqid = [self._nonce, self._reqseq]
        attempt = 0
        while time.monotonic() < deadline:
            if attempt:
                await self._op_backoff(attempt, deadline)
            attempt += 1
            if pool_name not in self.osdmap.pool_names:
                raise RadosError(-2, f"pool {pool_name!r} does not exist")
            pg = pgid if pgid is not None \
                else self.osdmap.object_to_pg(pool_name, oid)
            primary = self.osdmap.primary(pg)
            if primary == CRUSH_NONE:
                last = f"pg {pg} has no primary"
                await self._refresh_map(deadline)
                continue
            try:
                conn = await self._osd_conn(primary)
            except Exception as e:
                last = f"osd.{primary} unreachable: {e}"
                await self._refresh_map(deadline)
                continue
            self._tid += 1
            tid = self._tid
            fut = asyncio.get_running_loop().create_future()
            self._waiters[tid] = fut
            # the op is stamped with the session's negotiated identity:
            # the OSD accountant keys on the handshake entity and uses
            # this stamp only as the cross-check / requeue-path carrier
            payload = {"tid": tid, "pgid": [pg.pool, pg.ps], "oid": oid,
                       "ops": ops, "reqid": reqid,
                       "epoch": self.osdmap.epoch,
                       "client": self.name}
            if self.tenant:
                payload["tenant"] = self.tenant
            conn.send_message(MOSDOp(payload, data))
            try:
                reply = await asyncio.wait_for(
                    fut, min(attempt_timeout or self.ATTEMPT_TIMEOUT,
                             max(0.1, deadline - time.monotonic())))
            except asyncio.TimeoutError:
                last = f"op timeout against osd.{primary}"
                self._osd_conns.pop(primary, None)
                await self._refresh_map(deadline)
                continue
            finally:
                self._waiters.pop(tid, None)
            p, outdata = reply
            rc = p.get("rc", 0)
            if "retry_after_ms" in p:
                # QoS shed (MOSDOpThrottle): the map is fine — the
                # tenant is over its share. Honor the OSD's pacing
                # hint (scaled up on consecutive bounces, bounded by
                # the op deadline) and resend the same tid; no map
                # refresh, no connection teardown.
                self.throttled_ops += 1
                last = "throttled (qos shed)"
                delay = (float(p.get("retry_after_ms") or 50) / 1e3
                         * min(attempt, 8))
                delay = min(delay, max(0.0,
                                       deadline - time.monotonic()))
                if delay > 0:
                    await asyncio.sleep(delay)
                continue
            if rc == -11:            # wrong primary / stale map: recompute
                last = p.get("error", "wrong target")
                await self._refresh_map(deadline)
                continue
            if rc == -110:           # primary lost a replica mid-op: the op
                last = "sub-op timeout"   # is retried on the new interval
                await self._refresh_map(deadline)
                continue
            if rc == -2:
                raise ObjectNotFound(rc, p.get("error", oid))
            if rc < 0:
                raise RadosError(rc, p.get("error", "op failed"))
            return p, outdata
        raise TimeoutError(f"op on {oid!r} timed out ({last})")

    async def _op_backoff(self, attempt: int, deadline: float) -> None:
        """Sleep the capped exponential backoff before resend `attempt`
        (per-op: every logical op starts back at the base). Bounded by
        the op's own deadline so backoff can never extend it."""
        if attempt < 2:
            return          # first retry is immediate (stale-map case)
        delay = min(self.BACKOFF_MAX,
                    self.BACKOFF_BASE * (2 ** (attempt - 2)))
        delay = min(delay, max(0.0, deadline - time.monotonic()))
        if delay > 0:
            await asyncio.sleep(delay)

    async def _refresh_map(self, deadline: float) -> None:
        self._map_changed.clear()
        try:
            await self.monc.request_osdmap(self.osdmap.epoch)
            await asyncio.wait_for(
                self._map_changed.wait(),
                max(0.1, min(1.0, deadline - time.monotonic())))
        except (asyncio.TimeoutError, ConnectionError):
            pass

    # -- watch/notify linger plumbing ----------------------------------------

    def register_watch(self, pool: str, oid: str, callback) -> int:
        # the OSD keys watchers by cookie alone (the reference keys by
        # (entity, cookie)): embed the client nonce so two clients'
        # cookies can never collide; a wide shift so the sequence can
        # never carry into the nonce bits
        cookie = self._nonce * 2 ** 32 + self._next_cookie
        self._next_cookie += 1
        self._watches[cookie] = {"pool": pool, "oid": oid,
                                 "callback": callback}
        return cookie

    def unregister_watch(self, cookie: int) -> None:
        self._watches.pop(cookie, None)

    def _schedule_relinger(self) -> None:
        if not self._watches:
            return
        # a reset arriving while a relinger pass is mid-flight must
        # trigger ANOTHER pass: the running one may already be past the
        # watch the new reset just killed
        self._relinger_pending = True
        if self._relinger_task is not None and \
                not self._relinger_task.done():
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        self._relinger_task = loop.create_task(self._relinger())

    async def _relinger(self) -> None:
        """Re-send every registered watch (idempotent on the OSD): runs
        after a map change or transport reset, so a watch follows the
        PG's primary (Objecter::_linger_submit semantics)."""
        while self._relinger_pending:
            self._relinger_pending = False
            await asyncio.sleep(0.05)
            for cookie, w in list(self._watches.items()):
                try:
                    await self.submit(w["pool"], w["oid"],
                                      [{"op": "watch", "oid": w["oid"],
                                        "cookie": cookie}])
                except Exception as e:
                    dout("rados", 3, f"relinger watch {cookie} on "
                                     f"{w['oid']!r}: {type(e).__name__} {e}")

    async def _deliver_notify(self, conn: Connection,
                              msg: Message) -> None:
        p = msg.payload
        w = self._watches.get(int(p.get("cookie", 0)))
        ack = b""
        if w is not None:
            try:
                res = w["callback"](p["notify_id"], msg.data)
                if asyncio.iscoroutine(res):
                    res = await res
                if isinstance(res, bytes):
                    ack = res
            except Exception as e:
                dout("rados", 2, f"watch callback failed: "
                                 f"{type(e).__name__} {e}")
        # ack on the SAME connection the notify came in on: it reaches
        # the waiting primary without re-entering the op queue
        conn.send_message(MWatchNotifyAck(
            {"pgid": p["pgid"], "notify_id": p["notify_id"],
             "cookie": p["cookie"]}, ack))

    # -- dispatch ------------------------------------------------------------

    async def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, (MOSDOpReply, MOSDOpThrottle)):
            # a throttle is delivered through the same waiter: the
            # submit loop recognizes the retry_after_ms marker and
            # backs off WITHOUT a map refresh (QoS shed, not topology)
            fut = self._waiters.get(msg.payload.get("tid", 0))
            if fut is not None and not fut.done():
                fut.set_result((msg.payload, msg.data))
            return True
        if isinstance(msg, MWatchNotify):
            t = asyncio.get_running_loop().create_task(
                self._deliver_notify(conn, msg))
            self._notify_tasks.add(t)
            t.add_done_callback(self._notify_tasks.discard)
            return True
        return False

    def ms_handle_reset(self, conn: Connection) -> None:
        for osd, c in list(self._osd_conns.items()):
            if c is conn:
                del self._osd_conns[osd]
        # the primary holding our watches died with that conn
        self._schedule_relinger()


class IoCtx:
    """Synchronous-ish per-pool I/O context (librados IoCtx)."""

    #: op kinds that mutate state and therefore carry a SnapContext
    #: ("call" may stage writes server-side, so it carries one too)
    MOD_KINDS = frozenset({"write_full", "write", "append", "truncate",
                           "zero", "create", "delete", "setxattr",
                           "rmxattr", "omap_set", "omap_rm", "rollback",
                           "call"})

    def __init__(self, client: RadosClient, pool_name: str):
        self.client = client
        self.pool_name = pool_name
        # self-managed SnapContext (seq + snap ids, newest first); when
        # unset, writes use the pool-snap context from the osdmap
        self._snapc: dict | None = None

    # -- snapshots (librados snap API subset) --------------------------------

    def set_snap_context(self, seq: int, snaps: list[int]) -> None:
        """rados_ioctx_selfmanaged_snap_set_write_ctx: snaps newest
        first. (0, []) reverts to the pool-snap context — the reference
        forbids mixing pool and self-managed snaps in one pool; keep
        them in separate pools."""
        if seq == 0 and not snaps:
            self._snapc = None
            return
        self._snapc = {"seq": seq, "snaps": sorted(snaps, reverse=True)}

    def _snap_context(self) -> dict | None:
        if self._snapc is not None:
            return self._snapc
        pool = self.client.osdmap.get_pool(self.pool_name)
        if pool is None or not pool.pool_snaps:
            return None
        snaps = sorted((int(s) for s in pool.pool_snaps), reverse=True)
        return {"seq": pool.snap_seq, "snaps": snaps}

    async def selfmanaged_snap_create(self) -> int:
        out = await self.client.command(
            {"prefix": "osd pool selfmanaged snap create",
             "pool": self.pool_name})
        return out["snapid"]

    async def selfmanaged_snap_rm(self, snapid: int) -> None:
        out = await self.client.command(
            {"prefix": "osd pool selfmanaged snap rm",
             "pool": self.pool_name, "snapid": snapid})
        # wait for the COMMITTED epoch from the reply (a concurrent
        # unrelated proposal could satisfy "my epoch + 1" early)
        await self.client.wait_for_map(out["epoch"])

    async def snap_create(self, name: str) -> int:
        out = await self.client.command(
            {"prefix": "osd pool mksnap", "pool": self.pool_name,
             "snap": name})
        # writes must see the new pool record or they won't clone: wait
        # for the committed epoch the mon reported
        await self.client.wait_for_map(out["epoch"])
        return out["snapid"]

    async def snap_rm(self, name: str) -> None:
        out = await self.client.command(
            {"prefix": "osd pool rmsnap", "pool": self.pool_name,
             "snap": name})
        await self.client.wait_for_map(out["epoch"])

    def snap_list(self) -> dict[str, int]:
        pool = self.client.osdmap.get_pool(self.pool_name)
        return {v: int(k) for k, v in (pool.pool_snaps or {}).items()}

    def snap_lookup(self, name: str) -> int:
        sid = self.snap_list().get(name)
        if sid is None:
            raise RadosError(-2, f"snap {name!r} not found")
        return sid

    async def rollback(self, oid: str, snapid: int) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "rollback", "oid": oid, "snapid": snapid}])
        return p

    async def snap_rollback(self, oid: str, snap_name: str) -> dict:
        return await self.rollback(oid, self.snap_lookup(snap_name))

    async def list_snaps(self, oid: str) -> dict:
        p, _ = await self.client.submit(
            self.pool_name, oid, [{"op": "list_snaps", "oid": oid}])
        return p["results"][0]["out"]

    async def _submit(self, oid: str, ops: list[dict],
                      data: bytes = b"") -> tuple[dict, bytes]:
        """Mutation submit: stamps each modifying op with the current
        SnapContext (IoCtxImpl::operate attaching the io ctx snapc)."""
        snapc = self._snap_context()
        if snapc is not None:
            for op in ops:
                if op["op"] in self.MOD_KINDS:
                    op.setdefault("snapc", snapc)
        return await self.client.submit(self.pool_name, oid, ops, data)

    async def write_full(self, oid: str, data: bytes) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "write_full", "oid": oid}], data)
        return p

    async def write(self, oid: str, data: bytes, offset: int = 0) -> dict:
        """Ranged write (rados_write): extends the object as needed; on
        EC pools this drives the RMW partial-stripe pipeline."""
        p, _ = await self._submit(
            oid, [{"op": "write", "oid": oid, "off": offset}], data)
        return p

    async def append(self, oid: str, data: bytes) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "append", "oid": oid}], data)
        return p

    async def create(self, oid: str, exclusive: bool = True) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "create", "oid": oid, "exclusive": exclusive}])
        return p

    async def truncate(self, oid: str, size: int) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "truncate", "oid": oid, "size": size}])
        return p

    async def zero(self, oid: str, offset: int, length: int) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "zero", "oid": oid, "off": offset, "len": length}])
        return p

    async def read(self, oid: str, offset: int = 0, length: int = 0,
                   snapid: int | None = None) -> bytes:
        op = {"op": "read", "oid": oid, "off": offset, "len": length}
        if snapid is not None:
            op["snapid"] = snapid
        _, data = await self.client.submit(self.pool_name, oid, [op])
        return data

    async def remove(self, oid: str) -> dict:
        p, _ = await self._submit(oid, [{"op": "delete", "oid": oid}])
        return p

    async def stat(self, oid: str, snapid: int | None = None) -> dict:
        op = {"op": "stat", "oid": oid}
        if snapid is not None:
            op["snapid"] = snapid
        p, _ = await self.client.submit(self.pool_name, oid, [op])
        return p["results"][0]["out"]

    # -- xattrs / omap (replicated pools; EC pools return EOPNOTSUPP) ---------

    async def setxattr(self, oid: str, name: str, value: bytes) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "setxattr", "oid": oid, "name": name}], value)
        return p

    async def getxattr(self, oid: str, name: str) -> bytes:
        _, data = await self.client.submit(
            self.pool_name, oid,
            [{"op": "getxattr", "oid": oid, "name": name}])
        return data

    async def getxattrs(self, oid: str) -> dict[str, bytes]:
        p, _ = await self.client.submit(
            self.pool_name, oid, [{"op": "getxattrs", "oid": oid}])
        return {k: v.encode("latin1")
                for k, v in p["results"][0]["out"]["xattrs"].items()}

    async def rmxattr(self, oid: str, name: str) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "rmxattr", "oid": oid, "name": name}])
        return p

    async def omap_set(self, oid: str, kv: dict[str, bytes]) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "omap_set", "oid": oid,
                   "kv": {k: v.decode("latin1") for k, v in kv.items()}}])
        return p

    async def omap_get(self, oid: str) -> dict[str, bytes]:
        p, _ = await self.client.submit(
            self.pool_name, oid, [{"op": "omap_get", "oid": oid}])
        return {k: v.encode("latin1")
                for k, v in p["results"][0]["out"]["omap"].items()}

    async def omap_rm(self, oid: str, keys: list[str]) -> dict:
        p, _ = await self._submit(
            oid, [{"op": "omap_rm", "oid": oid, "keys": keys}])
        return p

    # -- aio (librados AioCompletion / neorados role) ------------------------

    @property
    def _aio(self):
        from ceph_tpu.rados.aio import AioDispatcher
        d = getattr(self.client, "_aio_dispatcher", None)
        if d is None:
            d = self.client._aio_dispatcher = AioDispatcher()
        return d

    def aio_write_full(self, oid: str, data: bytes):
        return self._aio.submit(self.write_full(oid, data))

    def aio_write(self, oid: str, data: bytes, offset: int = 0):
        return self._aio.submit(self.write(oid, data, offset))

    def aio_append(self, oid: str, data: bytes):
        return self._aio.submit(self.append(oid, data))

    def aio_read(self, oid: str, offset: int = 0, length: int = 0):
        return self._aio.submit(self.read(oid, offset, length))

    def aio_remove(self, oid: str):
        return self._aio.submit(self.remove(oid))

    def aio_stat(self, oid: str):
        return self._aio.submit(self.stat(oid))

    def aio_operate(self, oid: str, ops: list[dict], data: bytes = b""):
        return self._aio.submit(self._submit(oid, ops, data))

    async def aio_flush(self) -> None:
        await self._aio.flush()

    # -- watch/notify (rados_watch3 / rados_notify2 subset) ------------------

    async def watch(self, oid: str, callback) -> int:
        """Register a watch; `callback(notify_id, data)` runs on every
        notify (may be sync or async; bytes it returns ride the ack).
        Returns the watch cookie. The client lingers the watch across
        primary failover and reconnects."""
        cookie = self.client.register_watch(self.pool_name, oid, callback)
        try:
            await self.client.submit(
                self.pool_name, oid,
                [{"op": "watch", "oid": oid, "cookie": cookie}])
        except Exception:
            self.client.unregister_watch(cookie)
            raise
        return cookie

    async def unwatch(self, cookie: int) -> None:
        w = self.client._watches.get(cookie)
        self.client.unregister_watch(cookie)
        if w is not None:
            await self.client.submit(
                self.pool_name, w["oid"],
                [{"op": "unwatch", "oid": w["oid"], "cookie": cookie}])

    async def notify(self, oid: str, payload: bytes = b"",
                     timeout: float = 3.0) -> dict:
        """Fan a notification out to every watcher of `oid`; returns
        {"acks": [[cookie, data], ...], "timeouts": [cookie, ...]}.
        The attempt window extends past the server-side gather so a slow
        watcher can't make the Objecter resend (and double-notify)."""
        p, _ = await self.client.submit(
            self.pool_name, oid,
            [{"op": "notify", "oid": oid, "timeout": timeout}], payload,
            timeout=timeout + 10.0, attempt_timeout=timeout + 5.0)
        out = p["results"][0]["out"]
        return {"notify_id": out["notify_id"],
                "acks": [[c, d.encode("latin1")] for c, d in out["acks"]],
                "timeouts": list(out["timeouts"])}

    async def list_watchers(self, oid: str) -> list[dict]:
        p, _ = await self.client.submit(
            self.pool_name, oid, [{"op": "list_watchers", "oid": oid}])
        return p["results"][0]["out"]["watchers"]

    async def call(self, oid: str, cls: str, method: str,
                   indata: bytes = b"") -> bytes:
        """Execute an object-class method server-side
        (rados_exec / CEPH_OSD_OP_CALL)."""
        _, out = await self._submit(
            oid, [{"op": "call", "oid": oid, "cls": cls,
                   "method": method}], indata)
        return out

    async def list_objects(self) -> list[str]:
        """Union of object listings across this pool's PG primaries."""
        from ceph_tpu.crush.osdmap import PG as PGId
        seen: set[str] = set()
        pool = self.client.osdmap.get_pool(self.pool_name)
        for ps in range(pool.pg_num):
            pg = PGId(pool.id, ps)
            if self.client.osdmap.primary(pg) == CRUSH_NONE:
                continue
            try:
                p, _ = await self.client.submit(
                    self.pool_name, f"pg{ps}", [{"op": "list", "oid": ""}],
                    pgid=pg)
            except (RadosError, TimeoutError):
                continue
            seen.update(p["results"][0]["out"].get("objects", []))
        return sorted(seen)
