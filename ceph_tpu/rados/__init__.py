"""Client library: Objecter-style placement + resend (under construction).

Will hold the librados-subset client (reference src/osdc/Objecter.cc,
src/librados/): object->PG->OSD targeting from the current OSDMap epoch
and resend-on-map-change. Empty until that lands; nothing is re-exported.
"""
