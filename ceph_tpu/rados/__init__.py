"""Client library: librados-subset + Objecter-lite.

Reference: src/osdc/Objecter.cc (placement + resend-on-map-change),
src/librados/librados_c.cc (public API shape).
"""
from ceph_tpu.rados.client import (IoCtx, ObjectNotFound, RadosClient,
                                   RadosError)
from ceph_tpu.rados.aio import AioCompletion, AioDispatcher

__all__ = ["IoCtx", "ObjectNotFound", "RadosClient", "RadosError",
           "AioCompletion", "AioDispatcher"]
