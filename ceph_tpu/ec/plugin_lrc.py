"""`lrc` plugin — locally repairable layered code.

Re-creation of the reference's LRC plugin
(src/erasure-code/lrc/ErasureCodeLrc.{h,cc}): the code is a stack of
layers, each a (chunk-pattern, sub-profile) pair where the pattern marks
each global chunk position as data 'D', coding 'c', or absent '_' for that
layer; every layer recursively instantiates another registered plugin
(jerasure by default) over its own positions (ErasureCodeLrc.cc:140
layers_parse, :736 encode applying layers in sequence). Repair prefers the
cheapest local layer: `_minimum_to_decode` (:565) walks layers from the
most local and only falls back to wider layers when a local group cannot
recover.

Profiles: either explicit `layers` (JSON list of [pattern, profile]) +
`mapping`, or the generated k/m/l form (parse_kml, :290): (k+m)/l local
groups, one global layer plus one local parity per group.
"""
from __future__ import annotations

import json
from typing import Iterable, Mapping

import numpy as np

from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError
from ceph_tpu.ec.registry import (ERASURE_CODE_VERSION, ErasureCodePlugin,
                                  ErasureCodePluginRegistry)

__erasure_code_version__ = ERASURE_CODE_VERSION


class Layer:
    def __init__(self, pattern: str, profile: dict):
        self.pattern = pattern
        self.data = [i for i, c in enumerate(pattern) if c == "D"]
        self.coding = [i for i, c in enumerate(pattern) if c == "c"]
        self.chunks = self.data + self.coding
        self.chunks_set = set(self.chunks)
        profile = dict(profile)
        profile.setdefault("k", str(len(self.data)))
        profile.setdefault("m", str(len(self.coding)))
        profile.setdefault("plugin", "jerasure")
        profile.setdefault("technique", "reed_sol_van")
        self.profile = profile
        self.code = ErasureCodePluginRegistry.instance().factory(
            profile["plugin"], profile)


def _generate_kml(k: int, m: int, l: int) -> tuple[str, list]:
    """mapping + layers for the k/m/l shorthand (ErasureCodeLrc::parse_kml)."""
    if l <= 0 or (k + m) % l:
        raise ErasureCodeError(f"k+m={k + m} must be a multiple of l={l}")
    groups = (k + m) // l
    if k % groups or m % groups:
        raise ErasureCodeError(
            f"k={k} and m={m} must be multiples of (k+m)/l={groups}")
    kg, mg = k // groups, m // groups
    mapping = ("D" * kg + "_" * mg + "_") * groups
    global_pattern = ("D" * kg + "c" * mg + "_") * groups
    layers = [[global_pattern, ""]]
    for i in range(groups):
        pattern = "".join("D" * l + "c" if i == j else "_" * (l + 1)
                          for j in range(groups))
        layers.append([pattern, ""])
    return mapping, layers


def _parse_layer_profile(spec) -> dict:
    if isinstance(spec, dict):
        return {str(a): str(b) for a, b in spec.items()}
    if isinstance(spec, str):
        if not spec.strip():
            return {}
        try:
            obj = json.loads(spec)
        except json.JSONDecodeError:
            # reference accepts space-separated k=v pairs via json_spirit
            # leniency; support the plain form too
            obj = dict(item.split("=", 1) for item in spec.split())
        if not isinstance(obj, dict):
            raise ErasureCodeError(f"layer profile {spec!r} is not a map")
        return {str(a): str(b) for a, b in obj.items()}
    raise ErasureCodeError(f"layer profile {spec!r} must be str or map")


class ErasureCodeLrc(ErasureCode):
    def __init__(self):
        super().__init__()
        self.layers: list[Layer] = []
        self._chunk_count = 0

    def init(self, profile: Mapping[str, str]) -> None:
        profile = dict(profile)
        has_kml = any(profile.get(x) not in (None, "")
                      for x in ("k", "m", "l"))
        if has_kml:
            if any(profile.get(x) in (None, "") for x in ("k", "m", "l")):
                raise ErasureCodeError("all of k, m, l must be set or none")
            for key in ("mapping", "layers"):
                if profile.get(key):
                    raise ErasureCodeError(
                        f"{key} cannot be set when k/m/l are set")
            k = self.to_int("k", profile, 4, minimum=1)
            m = self.to_int("m", profile, 2, minimum=1)
            l = self.to_int("l", profile, 3, minimum=1)
            mapping, layer_desc = _generate_kml(k, m, l)
            profile["mapping"] = mapping
        else:
            mapping = profile.get("mapping", "")
            if not mapping:
                raise ErasureCodeError("the 'mapping' profile is missing")
            raw = profile.get("layers", "")
            if not raw:
                raise ErasureCodeError("the 'layers' profile is missing")
            try:
                layer_desc = json.loads(raw) if isinstance(raw, str) else raw
            except json.JSONDecodeError as e:
                raise ErasureCodeError(f"layers is not valid JSON: {e}") from e
            if not isinstance(layer_desc, list):
                raise ErasureCodeError("layers must be a JSON array")

        super().init(profile)
        self._chunk_count = len(mapping)
        self.k = mapping.count("D")
        self.m = self._chunk_count - self.k

        self.layers = []
        for entry in layer_desc:
            if isinstance(entry, str):
                entry = [entry, ""]
            if not isinstance(entry, (list, tuple)) or not entry:
                raise ErasureCodeError(
                    f"each layer must be [pattern, profile], got {entry!r}")
            pattern = entry[0]
            if not isinstance(pattern, str):
                raise ErasureCodeError(f"layer pattern {pattern!r} not a string")
            if len(pattern) != self._chunk_count:
                raise ErasureCodeError(
                    f"layer pattern {pattern!r} length {len(pattern)} != "
                    f"mapping length {self._chunk_count}")
            sub = _parse_layer_profile(entry[1] if len(entry) > 1 else "")
            self.layers.append(Layer(pattern, sub))
        if not self.layers:
            raise ErasureCodeError("at least one layer is required")

        covered = set()
        for layer in self.layers:
            covered |= layer.chunks_set
        if covered != set(range(self._chunk_count)):
            raise ErasureCodeError(
                f"layers cover {sorted(covered)} != all positions "
                f"0..{self._chunk_count - 1}")

        echo = {"mapping": mapping}
        if has_kml:
            echo.update({"k": str(self.k), "m": profile["m"],
                         "l": profile["l"]})
        self._profile.update(echo)

    # -- geometry -----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self._chunk_count

    def get_chunk_size(self, stripe_width: int) -> int:
        align = max(layer.code.get_alignment() for layer in self.layers)
        padded = self.k * align * (-(-stripe_width // (self.k * align)))
        return padded // self.k

    # -- locality-aware minimum --------------------------------------------

    def _minimum_to_decode(self, want_to_read: set[int],
                           available: set[int]) -> set[int]:
        """Cheapest-layer-first read planning (ErasureCodeLrc.cc:565)."""
        all_ids = set(range(self._chunk_count))
        erasures_total = all_ids - available
        erasures_want = want_to_read & erasures_total
        if not erasures_want:
            return set(want_to_read)

        # case 2: recover wanted erasures with the most local layer possible
        minimum: set[int] = set()
        not_recovered = set(erasures_total)
        want_left = set(erasures_want)
        for layer in reversed(self.layers):
            layer_want = want_to_read & layer.chunks_set
            if not layer_want:
                continue
            if not layer_want & want_left:
                minimum |= layer_want
                continue
            layer_erasures = layer.chunks_set & not_recovered
            if len(layer_erasures) > len(layer.coding):
                continue  # too many holes for this layer
            minimum |= layer.chunks_set - not_recovered
            not_recovered -= layer_erasures
            want_left -= layer_erasures
        if not want_left:
            minimum |= want_to_read
            minimum -= erasures_total
            return minimum

        # case 3: cascade — some layer may repair chunks other layers need
        not_recovered = set(erasures_total)
        progress = True
        while progress and not_recovered:
            progress = False
            for layer in reversed(self.layers):
                layer_erasures = layer.chunks_set & not_recovered
                if layer_erasures and \
                        len(layer_erasures) <= len(layer.coding):
                    not_recovered -= layer_erasures
                    progress = True
        if not not_recovered:
            return set(available)
        raise ErasureCodeError(
            f"not enough chunks in {sorted(available)} to read "
            f"{sorted(want_to_read)}")

    # -- kernels ------------------------------------------------------------

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        """Apply every layer in declaration order (global first, then
        locals) — ErasureCodeLrc::encode_chunks."""
        for layer in self.layers:
            sub_chunks = {j: chunks[c] for j, c in enumerate(layer.chunks)}
            layer.code.encode_chunks(sub_chunks)

    def decode_chunks(self, want_to_read: Iterable[int],
                      chunks: dict[int, np.ndarray],
                      available: set[int]) -> None:
        """Walk layers from most local, decoding whatever each can; later
        layers reuse chunks recovered by earlier ones."""
        want = set(want_to_read)
        erasures = set(range(self._chunk_count)) - set(available)
        progress = True
        while progress and want & erasures:
            progress = False
            for layer in reversed(self.layers):
                layer_erasures = layer.chunks_set & erasures
                if not layer_erasures:
                    continue
                if len(layer_erasures) > len(layer.coding):
                    continue
                sub_chunks = {}
                sub_avail = set()
                for j, c in enumerate(layer.chunks):
                    sub_chunks[j] = chunks[c]
                    if c not in erasures:
                        sub_avail.add(j)
                sub_want = {j for j, c in enumerate(layer.chunks)
                            if c in layer_erasures}
                layer.code.decode_chunks(sub_want, sub_chunks, sub_avail)
                erasures -= layer.chunks_set
                progress = True
                if not want & erasures:
                    break
        if want & erasures:
            raise ErasureCodeError(
                f"unable to read chunks {sorted(want & erasures)}")


class ErasureCodePluginLrc(ErasureCodePlugin):
    def factory(self, profile: Mapping[str, str], directory: str | None = None):
        instance = ErasureCodeLrc()
        instance.init(profile)
        return instance


def __erasure_code_init__(name: str, directory: str | None = None):
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginLrc())
