"""`tpu` plugin — the flagship erasure code, designed for the accelerator.

This is the plugin the north-star benchmark targets (BASELINE.json): the
reference's `ErasureCodeInterface::encode_chunks` contract, but engineered
around TPU realities measured on hardware:

  * the bitplane-matmul kernel sustains hundreds of GiB/s device-resident,
  * a single host<->device round trip costs ~2 ms through the transfer
    tunnel, i.e. one unbatched 1 MiB-stripe dispatch would be ~0.01 GiB/s.

So the plugin exposes, beyond the scalar interface:
  - encode_stripes/decode_stripes: (batch, k, S) one-dispatch batch APIs —
    the ECUtil::encode stripe loop (reference src/osd/ECUtil.cc:134) maps
    here, amortizing transfer and launch across concurrent RMW pipelines;
  - pipelined host-buffer encode with split batches so H2D of batch i+1
    overlaps compute of batch i (double buffering);
  - device-resident mode for callers that keep chunks in HBM (the OSD
    bridge and the benchmark steady state).

Techniques: reed_sol_van (default), cauchy_good. Matrices follow the
published jerasure constructions (Plank-Ding 2005 extended-Vandermonde
systematization; Plank-Xu 2006 cauchy_good) over the same field (0x11D),
validated in-repo against an independent from-scratch re-derivation
(tests/test_gf256_independent.py: peasant-multiply arithmetic, Fermat
inversion, full 256x256 table cross-check). A live jerasure build is not
available here, so interop with real jerasure-encoded chunks is
construction-level compatible, not verified against jerasure binaries.
"""
from __future__ import annotations

import time
from typing import Iterable, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ec import gf256
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.plugin_jerasure import ErasureCodeJerasure
from ceph_tpu.ec.registry import (ERASURE_CODE_VERSION, ErasureCodePlugin,
                                  ErasureCodePluginRegistry)
from ceph_tpu.ops import rs_codec
from ceph_tpu.utils import copytrack, tracer

__erasure_code_version__ = ERASURE_CODE_VERSION

DEFAULT_K = 8
DEFAULT_M = 3


def _device_of(arr) -> str:
    """`platform:id` of a committed single-device array ("sharded" for
    mesh-placed inputs) — the span label the per-device utilization
    dashboards join against."""
    try:
        ds = arr.devices()
        if len(ds) != 1:
            return "sharded"
        d = next(iter(ds))
        return f"{d.platform}:{d.id}"
    except Exception:
        return "unknown"


def _profiled_roundtrip(kernel, host_batch, timings: list) -> np.ndarray:
    """One serialized H2D -> kernel -> D2H round trip, accumulating the
    three stage durations into `timings` ([h2d_s, kernel_s, d2h_s]).
    Attribution-mode only (tracer.set_profile_dispatch): the explicit
    block_until_ready per stage forfeits the transfer/compute overlap
    to make the splits real."""
    t0 = time.perf_counter()
    dev = jax.block_until_ready(jnp.asarray(host_batch))
    t1 = time.perf_counter()
    res = jax.block_until_ready(kernel(dev))
    t2 = time.perf_counter()
    out = np.asarray(res)
    t3 = time.perf_counter()
    timings[0] += t1 - t0
    timings[1] += t2 - t1
    timings[2] += t3 - t2
    return out


def _record_roundtrip(timings: list, in_bytes: int, out_bytes: int,
                      sp) -> None:
    """Feed accumulated round-trip timings to the copy ledger and the
    dispatch span (the attribution waterfall's h2d/kernel/d2h buckets)."""
    h2d_s, kernel_s, d2h_s = timings
    copytrack.copied("h2d", in_bytes, h2d_s)
    copytrack.copied("d2h", out_bytes, d2h_s)
    sp.set_tag("h2d_us", round(h2d_s * 1e6, 1))
    sp.set_tag("kernel_us", round(kernel_s * 1e6, 1))
    sp.set_tag("d2h_us", round(d2h_s * 1e6, 1))


class ErasureCodeTpu(ErasureCodeJerasure):
    technique = "reed_sol_van"
    #: batched APIs dispatch to the accelerator: the offload service
    #: routes/queues only plugins that set this — the jerasure family
    #: has the same encode_stripes signature but runs on host, where
    #: the admission queue's linger buys nothing
    device_batched = True

    def init(self, profile: Mapping[str, str]) -> None:
        profile = dict(profile)
        profile.setdefault("k", str(DEFAULT_K))
        profile.setdefault("m", str(DEFAULT_M))
        super().init(profile)
        # pipeline depth for host-buffer batches (number of sub-batches whose
        # transfers overlap compute); 1 disables double buffering
        self.pipeline_depth = self.to_int("pipeline-depth", profile, 4, minimum=1)

    def _build_matrix(self) -> np.ndarray:
        if self._profile.get("technique", "reed_sol_van") == "cauchy_good":
            return gf256.cauchy_good_matrix(self.k, self.m)
        return gf256.reed_sol_van_matrix(self.k, self.m)

    def _check_technique(self) -> None:
        tech = self._profile.get("technique", "reed_sol_van")
        if tech not in ("reed_sol_van", "cauchy_good"):
            raise ErasureCodeError(f"tpu technique {tech!r} unsupported")

    # -- batched data path ---------------------------------------------------

    def encode_stripes(self, data: np.ndarray | jax.Array) -> np.ndarray | jax.Array:
        """(batch, k, S) -> (batch, m, S) parity. numpy in => pipelined
        host transfer + numpy out; device array in => device array out.
        Each call is one traced device dispatch: the span separates
        device-resident time from host-buffer (H2D + compute + D2H)
        time, per stripe batch."""
        device_resident = isinstance(data, jax.Array)
        with tracer.span("tpu_encode_dispatch") as sp:
            if sp is not None:
                sp.set_tag("mode", "device" if device_resident
                           else "host-pipelined")
                sp.set_tag("batch", int(data.shape[0]))
                sp.set_tag("bytes", int(data.size))
                sp.set_tag("k", self.k)
                sp.set_tag("m", self.m)
                if device_resident:
                    # which mesh slot this batch landed on (the offload
                    # service's device-affine routing made the choice)
                    sp.set_tag("device", _device_of(data))
            if device_resident:
                return self._encoder.apply_batch_device(data)
            return self._encode_host_pipelined(
                np.ascontiguousarray(data, dtype=np.uint8), sp=sp)

    def _encode_host_pipelined(self, data: np.ndarray,
                               sp=None) -> np.ndarray:
        b = data.shape[0]
        depth = min(self.pipeline_depth, b)
        splits = np.array_split(np.arange(b), depth)
        if sp is not None and tracer.profile_dispatch():
            # attribution mode (tracer.set_profile_dispatch): serialize
            # each pipeline stage so the span carries REAL h2d/kernel/
            # d2h splits — costs the transfer/compute overlap, so it
            # never rides plain tracer_enabled
            return self._encode_host_profiled(data, splits, sp)
        # enqueue all transfers+dispatches first (async), then collect —
        # XLA/PJRT overlaps H2D of later sub-batches with earlier compute
        outs = []
        for idx in splits:
            if len(idx) == 0:
                continue
            dev = jnp.asarray(data[idx[0]: idx[-1] + 1])
            outs.append(self._encoder.apply_batch_device(dev))
        out = np.concatenate([np.asarray(o) for o in outs], axis=0)
        copytrack.copied("h2d", int(data.nbytes))
        copytrack.copied("d2h", int(out.nbytes))
        return out

    def _encode_host_profiled(self, data: np.ndarray, splits,
                              sp) -> np.ndarray:
        outs = []
        timings = [0.0, 0.0, 0.0]
        for idx in splits:
            if len(idx) == 0:
                continue
            outs.append(_profiled_roundtrip(
                self._encoder.apply_batch_device,
                data[idx[0]: idx[-1] + 1], timings))
        out = np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]
        _record_roundtrip(timings, int(data.nbytes), int(out.nbytes), sp)
        return out

    def decode_stripes(self, avail_ids: tuple[int, ...], want_ids: tuple[int, ...],
                       chunks: np.ndarray | jax.Array) -> np.ndarray | jax.Array:
        """Batched reconstruction: `chunks` is (batch, k, S) holding the
        available chunks stacked in `avail_ids` order; returns the
        reconstructed `want_ids` chunks as (batch, len(want), S)."""
        R = rs_codec.recovery_matrix(self.coding_matrix, avail_ids, want_ids)
        codec = rs_codec.MatrixCodec.get(R)
        device_resident = isinstance(chunks, jax.Array)
        with tracer.span("tpu_decode_dispatch") as sp:
            if sp is not None:
                sp.set_tag("mode", "device" if device_resident else "host")
                sp.set_tag("batch", int(chunks.shape[0]))
                sp.set_tag("bytes", int(chunks.size))
                sp.set_tag("want", list(want_ids))
                if device_resident:
                    sp.set_tag("device", _device_of(chunks))
            if device_resident:
                return codec.apply_batch_device(chunks)
            chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
            if sp is not None and tracer.profile_dispatch():
                timings = [0.0, 0.0, 0.0]
                out = _profiled_roundtrip(codec.apply_batch_device,
                                          chunks, timings)
                _record_roundtrip(timings, int(chunks.nbytes),
                                  int(out.nbytes), sp)
                return out
            dev = jnp.asarray(chunks)
            out = np.asarray(codec.apply_batch_device(dev))
            copytrack.copied("h2d", int(chunks.nbytes))
            copytrack.copied("d2h", int(out.nbytes))
            return out


class ErasureCodePluginTpu(ErasureCodePlugin):
    def factory(self, profile: Mapping[str, str], directory: str | None = None):
        instance = ErasureCodeTpu()
        instance.init(profile)
        return instance


def __erasure_code_init__(name: str, directory: str | None = None):
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginTpu())
