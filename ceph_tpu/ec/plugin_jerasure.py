"""`jerasure` plugin: matrix Reed-Solomon techniques on the TPU codec.

Re-creation of the reference's default plugin
(src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}): techniques are
dispatched by the profile's `technique` key
(ErasureCodePluginJerasure.cc:34-71); each class's prepare() builds its
coding matrix once at init (ErasureCodeJerasure.cc:203). Instead of
jerasure's GF tables + SIMD loops, all techniques lower to the shared
bitplane-matmul codec (ceph_tpu.ops.rs_codec), so the same code runs the
w=8 field math on CPU or TPU (construction-compatible with jerasure;
independently cross-validated in tests/test_gf256_independent.py).

Supported techniques: reed_sol_van, reed_sol_r6_op, cauchy_orig,
cauchy_good (GF(2^8) matrix codes on the bitplane-matmul codec), and the
minimal-density bitmatrix RAID-6 family — liberation, blaum_roth,
liber8tion — lowered onto the GF(2) packet-XOR machinery in
ceph_tpu.ec.bitmatrix (constructions verified MDS at prepare()).
"""
from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np

from ceph_tpu.ec import gf256
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError
from ceph_tpu.ec.registry import (ERASURE_CODE_VERSION, ErasureCodePlugin,
                                  ErasureCodePluginRegistry)
from ceph_tpu.ops import rs_codec

__erasure_code_version__ = ERASURE_CODE_VERSION

DEFAULT_K = 2
DEFAULT_M = 1
DEFAULT_W = 8


class ErasureCodeJerasure(ErasureCode):
    """Base for matrix techniques; subclasses provide _build_matrix()."""

    technique = "reed_sol_van"

    def __init__(self):
        super().__init__()
        self.w = DEFAULT_W
        self.coding_matrix: np.ndarray | None = None

    DEFAULT_TECHNIQUE_W = DEFAULT_W

    def init(self, profile: Mapping[str, str]) -> None:
        super().init(profile)
        self.k = self.to_int("k", profile, DEFAULT_K, minimum=1)
        self.m = self.to_int("m", profile, DEFAULT_M, minimum=1)
        self.w = self.to_int("w", profile, self.DEFAULT_TECHNIQUE_W)
        self._check_w()
        if self.k + self.m > 256:
            raise ErasureCodeError("k+m must be <= 256 in GF(2^8)")
        self._check_technique()
        self.prepare()
        # normalize defaulted keys back into the profile like the reference
        self._profile.update({"k": str(self.k), "m": str(self.m), "w": str(self.w)})

    def _check_w(self) -> None:
        if self.w != 8:
            # The TPU data path is GF(2^8)-native; other word sizes existed in
            # jerasure for CPU table-size tradeoffs that do not apply here.
            raise ErasureCodeError(f"w={self.w} unsupported; only w=8")

    def _check_technique(self) -> None:
        pass

    def prepare(self) -> None:
        self.coding_matrix = np.asarray(self._build_matrix(), dtype=np.uint8)
        self._encoder = rs_codec.MatrixCodec.get(self.coding_matrix)

    def _build_matrix(self) -> np.ndarray:
        raise NotImplementedError

    # -- kernels ------------------------------------------------------------

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        parity = self._encoder.apply(data)
        for i in range(self.m):
            chunks[self.k + i][:] = parity[i]

    def decode_chunks(self, want_to_read: Iterable[int],
                      chunks: dict[int, np.ndarray],
                      available: set[int]) -> None:
        # `available` is required: the kernel contract supplies `chunks` with
        # zero-filled holes for missing ids, so deriving it as set(chunks)
        # would make every chunk look present and silently skip
        # reconstruction (ADVICE r1).
        want = sorted(set(want_to_read) - available)
        if not want:
            return
        avail = tuple(sorted(available))[: self.k]
        if len(avail) < self.k:
            raise ErasureCodeError(
                f"cannot decode {want}: only {len(avail)} chunks available")
        R = rs_codec.recovery_matrix(self.coding_matrix, avail, tuple(want))
        src = np.stack([chunks[i] for i in avail])
        rec = rs_codec.MatrixCodec.get(R).apply(src)
        for row, i in enumerate(want):
            chunks[i][:] = rec[row]

    # -- batched stripe API (the ec_util one-dispatch driver) ---------------

    def _apply_flat(self, M: np.ndarray, src) -> np.ndarray:
        """(S, rows_in, C) through M (rows_out, rows_in) -> (S, rows_out, C).

        Host arrays: the stripe axis folds into the byte lanes so the
        whole batch is ONE matrix application — via the native
        split-table SIMD codec when available (the OSD write path feeds
        host bytes; per-stripe dispatch was ~100x slower there), else
        one MatrixCodec dispatch (the reference amortizes the same way
        at its ECUtil::encode batching site, src/osd/ECUtil.cc:134).
        Device arrays stay on device (device in => device out, the
        plugin_tpu contract) — silently pulling a jax batch to host
        would hide a ~5 MB/s tunnel transfer inside a "device" bench.
        Host output is stripe-major as a VIEW over shard-major storage:
        the ec_util consumers re-transpose to shard-major, so their
        ascontiguousarray lands back on this buffer for free."""
        import jax
        if isinstance(src, jax.Array):
            return rs_codec.MatrixCodec.get(M).apply_batch_device(src)
        from ceph_tpu.native import ec_native
        src = np.ascontiguousarray(src, dtype=np.uint8)
        S, kin, C = src.shape
        rows = M.shape[0]
        flat = np.ascontiguousarray(src.transpose(1, 0, 2)).reshape(
            kin, S * C)
        if ec_native.available():
            out = np.empty((rows, S * C), dtype=np.uint8)
            ec_native.encode(M, flat, out)
        else:
            out = rs_codec.MatrixCodec.get(M).apply(flat)
        return out.reshape(rows, S, C).transpose(1, 0, 2)

    def encode_stripes(self, data):
        """(S, k, C) data stripes -> (S, m, C) parity, one dispatch."""
        return self._apply_flat(self.coding_matrix, data)

    def decode_stripes(self, avail_ids: tuple[int, ...],
                       want_ids: tuple[int, ...], chunks) -> np.ndarray:
        """Batched reconstruction of `want_ids` from the first-k available
        chunks stacked in `avail_ids` order: (S, k, C) -> (S, want, C)."""
        R = rs_codec.recovery_matrix(self.coding_matrix, tuple(avail_ids),
                                     tuple(want_ids))
        return self._apply_flat(R, chunks)


class ErasureCodeJerasureReedSolomonVandermonde(ErasureCodeJerasure):
    technique = "reed_sol_van"

    def _build_matrix(self) -> np.ndarray:
        return gf256.reed_sol_van_matrix(self.k, self.m)


class ErasureCodeJerasureReedSolomonRAID6(ErasureCodeJerasure):
    technique = "reed_sol_r6_op"

    def _check_technique(self) -> None:
        if self.m != 2:
            raise ErasureCodeError("reed_sol_r6_op requires m=2")

    def _build_matrix(self) -> np.ndarray:
        return gf256.reed_sol_r6_matrix(self.k)


class ErasureCodeJerasureCauchyOrig(ErasureCodeJerasure):
    technique = "cauchy_orig"

    def _build_matrix(self) -> np.ndarray:
        return gf256.cauchy_orig_matrix(self.k, self.m)


class ErasureCodeJerasureCauchyGood(ErasureCodeJerasure):
    technique = "cauchy_good"

    def _build_matrix(self) -> np.ndarray:
        return gf256.cauchy_good_matrix(self.k, self.m)


class ErasureCodeJerasureBitMatrix(ErasureCodeJerasure):
    """Base for the minimal-density GF(2) bitmatrix RAID-6 family
    (liberation/blaum_roth/liber8tion): m=2, word size w, chunk = w
    contiguous packets. Lowers onto ceph_tpu.ec.bitmatrix rather than
    the GF(2^8) codec (these codes are not GF(2^8) matrices)."""

    # the GF(2^8) batched stripe API does not apply to GF(2) bit codes;
    # ec_util's callable() gate sends these through the per-stripe loop
    encode_stripes = None
    decode_stripes = None

    def _check_w(self) -> None:
        pass            # per-technique constraints in _check_technique

    def _check_technique(self) -> None:
        if self.m != 2:
            raise ErasureCodeError(f"{self.technique} requires m=2")
        if self.k > self.w:
            raise ErasureCodeError(
                f"{self.technique}: k={self.k} > w={self.w}")

    def prepare(self) -> None:
        from ceph_tpu.ec import bitmatrix
        self.code = bitmatrix.RAID6BitCode(
            "blaum_roth" if self.technique == "blaum_roth"
            else "liberation", self.k, self.w)

    def get_alignment(self) -> int:
        # chunks must split into w equal packets; keep packets themselves
        # 64-byte aligned for the XOR path
        return self.w * 64

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        self.code.encode(chunks)

    def decode_chunks(self, want_to_read: Iterable[int],
                      chunks: dict[int, np.ndarray],
                      available: set[int]) -> None:
        want = sorted(set(want_to_read) - available)
        if not want:
            return
        self.code.decode(want, chunks, available)


class ErasureCodeJerasureLiberation(ErasureCodeJerasureBitMatrix):
    technique = "liberation"
    DEFAULT_TECHNIQUE_W = 7

    def _check_technique(self) -> None:
        super()._check_technique()
        from ceph_tpu.ec.bitmatrix import _is_prime
        if not _is_prime(self.w):
            raise ErasureCodeError(f"liberation: w={self.w} must be prime")


class ErasureCodeJerasureBlaumRoth(ErasureCodeJerasureBitMatrix):
    technique = "blaum_roth"
    DEFAULT_TECHNIQUE_W = 6

    def _check_technique(self) -> None:
        super()._check_technique()
        from ceph_tpu.ec.bitmatrix import _is_prime
        if not _is_prime(self.w + 1):
            raise ErasureCodeError(
                f"blaum_roth: w+1={self.w + 1} must be prime")


class ErasureCodeJerasureLiber8tion(ErasureCodeJerasureBitMatrix):
    technique = "liber8tion"
    DEFAULT_TECHNIQUE_W = 8

    def _check_technique(self) -> None:
        if self.w != 8:
            raise ErasureCodeError("liber8tion requires w=8")
        super()._check_technique()


_TECHNIQUES = {
    cls.technique: cls
    for cls in (
        ErasureCodeJerasureReedSolomonVandermonde,
        ErasureCodeJerasureReedSolomonRAID6,
        ErasureCodeJerasureCauchyOrig,
        ErasureCodeJerasureCauchyGood,
        ErasureCodeJerasureLiberation,
        ErasureCodeJerasureBlaumRoth,
        ErasureCodeJerasureLiber8tion,
    )
}

_DEFERRED: set[str] = set()


class ErasureCodePluginJerasure(ErasureCodePlugin):
    def factory(self, profile: Mapping[str, str],
                directory: str | None = None):
        technique = profile.get("technique", "reed_sol_van")
        cls = _TECHNIQUES.get(technique)
        if cls is None:
            if technique in _DEFERRED:
                raise ErasureCodeError(
                    f"technique {technique!r} not yet implemented")
            raise ErasureCodeError(f"unknown jerasure technique {technique!r}")
        instance = cls()
        instance.init(profile)
        return instance


def __erasure_code_init__(name: str, directory: str | None = None):
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginJerasure())
