"""GF(2^8) host-side arithmetic for Reed-Solomon erasure codes.

This is the control-plane math: building generator/coding matrices, inverting
decode submatrices, and converting GF(2^8) matrices to GF(2) bitmatrices that
the TPU data path (bitplane matmul / XOR networks, see ceph_tpu.ops) executes.

All arithmetic uses the primitive polynomial x^8 + x^4 + x^3 + x^2 + 1
(0x11D), the field used by both jerasure/gf-complete (w=8) and Intel ISA-L
(reference: src/erasure-code/jerasure/ErasureCodeJerasure.cc,
src/erasure-code/isa/ErasureCodeIsa.cc:388-390). The tables and matrix
constructions are cross-validated against an independent from-scratch
implementation (peasant multiply + Fermat inversion) in
tests/test_gf256_independent.py; interop with chunks from real jerasure
binaries is construction-level (the submodules aren't available here to
bit-verify against).

Matrix constructions follow the published algorithms (Plank, "A Tutorial on
Reed-Solomon Coding for Fault-Tolerance in RAID-like Systems" + the 2003
correction note; Plank & Xu, "Optimizing Cauchy Reed-Solomon Codes"), which is
what the reference wraps — nothing here is translated from the reference tree.
"""
from __future__ import annotations

import functools

import numpy as np

PRIM_POLY = 0x11D  # x^8+x^4+x^3+x^2+1, generator alpha=2
W = 8
FIELD = 1 << W  # 256


def _build_tables():
    exp = np.zeros(2 * FIELD, dtype=np.uint16)
    log = np.zeros(FIELD, dtype=np.uint16)
    x = 1
    for i in range(FIELD - 1):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & FIELD:
            x ^= PRIM_POLY
    # duplicate so exp[log a + log b] never wraps
    exp[FIELD - 1 : 2 * (FIELD - 1)] = exp[: FIELD - 1]
    log[0] = 0  # undefined; callers must special-case 0
    return exp, log


GF_EXP, GF_LOG = _build_tables()

# Full 256x256 multiplication table: 64 KiB, used for vectorized host encode
# (the numpy ground-truth codec that the JAX kernels are validated against).
_a = np.arange(FIELD, dtype=np.uint16)
GF_MUL_TABLE = np.where(
    (_a[:, None] == 0) | (_a[None, :] == 0),
    0,
    GF_EXP[(GF_LOG[_a[:, None]].astype(np.int32) + GF_LOG[_a[None, :]].astype(np.int32)) % (FIELD - 1)],
).astype(np.uint8)
del _a

GF_INV_TABLE = np.zeros(FIELD, dtype=np.uint8)
GF_INV_TABLE[1:] = GF_EXP[(FIELD - 1) - GF_LOG[np.arange(1, FIELD)].astype(np.int32)]


def gf_mul(a: int, b: int) -> int:
    return int(GF_MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) - int(GF_LOG[b])) % (FIELD - 1)])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(GF_INV_TABLE[a])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % (FIELD - 1)])


# ---------------------------------------------------------------------------
# Matrix ops over GF(2^8) (numpy uint8 matrices)
# ---------------------------------------------------------------------------

def mat_mul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """C = A @ B over GF(2^8). Shapes (n,k) @ (k,m) -> (n,m)."""
    A = np.asarray(A, dtype=np.uint8)
    B = np.asarray(B, dtype=np.uint8)
    # products[i,j,l] = A[i,l]*B[l,j]; XOR-reduce over l
    prod = GF_MUL_TABLE[A[:, :, None], B[None, :, :]]  # (n,k,m)
    return np.bitwise_xor.reduce(prod, axis=1)


def mat_vec_apply(M: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Apply coding matrix M (m,k) to data bytes (k, N) -> (m, N) over GF(2^8).

    This is the numpy ground-truth encoder used to validate the JAX/Pallas
    kernels (equivalent of jerasure_matrix_encode with w=8).
    """
    M = np.asarray(M, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    out = np.zeros((M.shape[0], data.shape[1]), dtype=np.uint8)
    for i in range(M.shape[0]):
        acc = out[i]
        for j in range(M.shape[1]):
            c = M[i, j]
            if c == 0:
                continue
            acc ^= GF_MUL_TABLE[c, data[j]]
        out[i] = acc
    return out


def mat_invert(M: np.ndarray) -> np.ndarray:
    """Invert a square matrix over GF(2^8) by Gauss-Jordan elimination."""
    M = np.asarray(M, dtype=np.uint8).copy()
    n = M.shape[0]
    if M.shape != (n, n):
        raise ValueError("matrix must be square")
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        # pivot search
        pivot = -1
        for row in range(col, n):
            if M[row, col] != 0:
                pivot = row
                break
        if pivot < 0:
            raise np.linalg.LinAlgError("singular matrix over GF(2^8)")
        if pivot != col:
            M[[col, pivot]] = M[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        # scale pivot row to 1
        pv = int(M[col, col])
        if pv != 1:
            pinv = gf_inv(pv)
            M[col] = GF_MUL_TABLE[pinv, M[col]]
            inv[col] = GF_MUL_TABLE[pinv, inv[col]]
        # eliminate
        for row in range(n):
            if row == col or M[row, col] == 0:
                continue
            f = int(M[row, col])
            M[row] ^= GF_MUL_TABLE[f, M[col]]
            inv[row] ^= GF_MUL_TABLE[f, inv[col]]
    return inv


# ---------------------------------------------------------------------------
# Coding-matrix constructions
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def reed_sol_van_matrix(k: int, m: int) -> np.ndarray:
    """Systematic Vandermonde RS coding matrix (m, k), jerasure reed_sol_van.

    Construction-compatible with jerasure's reed_sol_vandermonde_coding_matrix (the
    published Plank algorithm wrapped by reference
    src/erasure-code/jerasure/ErasureCodeJerasure.cc:162): build the
    *extended* Vandermonde matrix — first row e_0, last row e_{k-1}, middle
    row i = [1, i, i^2, ...] — then systematize the top k rows to the
    identity with row swaps + elementary column operations, and finally
    normalize the coding block: scale each column of the coding rows so the
    first coding row is all ones, then scale every later coding row so its
    first element is 1 (both scalings preserve the MDS property). The
    all-ones first coding row is the documented jerasure property that makes
    m=1 parity plain XOR for any k (and is what the reference ISA plugin's
    region_xor single-erasure fast path relies on for its own Vandermonde,
    src/erasure-code/isa/ErasureCodeIsa.cc:206). Golden values pinned in
    tests/test_gf256.py.
    """
    if k + m > FIELD:
        raise ValueError("k+m must be <= 256 for GF(2^8)")
    rows = k + m
    vdm = np.zeros((rows, k), dtype=np.uint8)
    vdm[0, 0] = 1
    vdm[rows - 1, k - 1] = 1
    q = 1
    for i in range(1, rows - 1):
        vdm[i, 0] = 1
        for j in range(1, k):
            vdm[i, j] = gf_mul(int(vdm[i, j - 1]), q)
        q += 1
    # systematize: make row i equal e_i for i in 1..k-1 (row 0 already is e_0)
    for i in range(1, k):
        # find a row at/below i with a nonzero entry in column i, swap it up
        j = i
        while j < rows and vdm[j, i] == 0:
            j += 1
        if j >= rows:
            raise np.linalg.LinAlgError("vandermonde systematization failed")
        if j != i:
            vdm[[i, j]] = vdm[[j, i]]
        piv = int(vdm[i, i])
        if piv != 1:
            vdm[:, i] = GF_MUL_TABLE[gf_inv(piv), vdm[:, i]]
        for c in range(k):
            if c != i and vdm[i, c] != 0:
                vdm[:, c] ^= GF_MUL_TABLE[int(vdm[i, c]), vdm[:, i]]
    coding = vdm[k:].copy()
    # normalize: first coding row -> all ones (divide each coding column by
    # its first-row element), later rows -> leading element 1
    for j in range(k):
        d = int(coding[0, j])
        if d not in (0, 1):
            coding[:, j] = GF_MUL_TABLE[gf_inv(d), coding[:, j]]
    for i in range(1, m):
        d = int(coding[i, 0])
        if d not in (0, 1):
            coding[i] = GF_MUL_TABLE[gf_inv(d), coding[i]]
    coding.setflags(write=False)
    return coding


@functools.lru_cache(maxsize=None)
def reed_sol_r6_matrix(k: int) -> np.ndarray:
    """RAID-6 optimized matrix (m=2): row0 = all ones (P), row1[j] = 2^j (Q)."""
    coding = np.zeros((2, k), dtype=np.uint8)
    coding[0, :] = 1
    for j in range(k):
        coding[1, j] = gf_pow(2, j)
    coding.setflags(write=False)
    return coding


@functools.lru_cache(maxsize=None)
def cauchy_orig_matrix(k: int, m: int) -> np.ndarray:
    """Original Cauchy matrix: a[i][j] = 1/(i XOR (m+j)), i<m, j<k."""
    if k + m > FIELD:
        raise ValueError("k+m must be <= 256")
    coding = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            coding[i, j] = gf_inv(i ^ (m + j))
    coding.setflags(write=False)
    return coding


@functools.lru_cache(maxsize=256)
def _bitmatrix_ones(x: int) -> int:
    """Number of ones in the 8x8 GF(2) bitmatrix of multiply-by-x."""
    return int(elem_bitmatrix(x).sum())


@functools.lru_cache(maxsize=None)
def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """Cauchy matrix optimized to minimize bitmatrix ones (Plank & Xu 2006).

    Start from cauchy_orig; divide each column j by its row-0 element so row 0
    becomes all ones; then for each subsequent row pick the element divisor
    that minimizes the total popcount of the row's bitmatrices. Divisor
    candidates are scanned in column order with strict-improvement comparison
    so ties resolve deterministically, matching jerasure's
    cauchy_good_general_coding_matrix scan order.
    """
    A = np.array(cauchy_orig_matrix(k, m), dtype=np.uint8)
    for j in range(k):
        d = int(A[0, j])
        if d not in (0, 1):
            A[:, j] = GF_MUL_TABLE[gf_inv(d), A[:, j]]
    for i in range(1, m):
        best_div, best_cost = 1, sum(_bitmatrix_ones(int(x)) for x in A[i])
        seen = {0, 1}
        for div in map(int, A[i]):
            if div in seen:
                continue
            seen.add(div)
            cand = GF_MUL_TABLE[gf_inv(div), A[i]]
            cost = sum(_bitmatrix_ones(int(x)) for x in cand)
            if cost < best_cost:
                best_div, best_cost = div, cost
        if best_div != 1:
            A[i] = GF_MUL_TABLE[gf_inv(best_div), A[i]]
    A.setflags(write=False)
    return A


@functools.lru_cache(maxsize=None)
def isa_rs_vandermonde_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L gf_gen_rs_matrix-style coding rows: a[i][j] = (2^i)^j = 2^(i*j).

    Guaranteed MDS only for the ranges ISA-L supports (k+m <= 255 with m <= ...);
    the reference isa plugin switches to Cauchy for larger geometries
    (src/erasure-code/isa/ErasureCodeIsa.cc:388-390 behavior).
    """
    coding = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            coding[i, j] = gf_pow(2, i * j)
    coding.setflags(write=False)
    return coding


@functools.lru_cache(maxsize=None)
def isa_cauchy1_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L gf_gen_cauchy1_matrix coding rows: a[i][j] = 1/((k+i) XOR j) —
    Cauchy with X = {k..k+m-1}, Y = {0..k-1} (i XOR j != 0 since i >= k > j)."""
    coding = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            coding[i, j] = gf_inv((k + i) ^ j)
    coding.setflags(write=False)
    return coding


# ---------------------------------------------------------------------------
# GF(2) bitmatrix conversion (for bitplane-matmul / XOR-network data path)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _elem_bitmatrix_cached(x: int) -> bytes:
    B = np.zeros((W, W), dtype=np.uint8)
    for c in range(W):
        y = gf_mul(x, 1 << c)
        for r in range(W):
            B[r, c] = (y >> r) & 1
    return B.tobytes()


def elem_bitmatrix(x: int) -> np.ndarray:
    """8x8 GF(2) matrix B with (x*v) bit r = XOR_c B[r,c] * v_c."""
    return np.frombuffer(_elem_bitmatrix_cached(int(x)), dtype=np.uint8).reshape(W, W)


def matrix_to_bitmatrix(M: np.ndarray) -> np.ndarray:
    """Expand an (m,k) GF(2^8) matrix to an (m*8, k*8) GF(2) bitmatrix.

    Output bit-row i*8+r of the product equals XOR over (j,c) of
    B[i*8+r, j*8+c] * (input chunk j, bit c) — the contract consumed by
    ceph_tpu.ops bitplane kernels (jerasure_matrix_to_bitmatrix semantics).
    """
    M = np.asarray(M, dtype=np.uint8)
    m, k = M.shape
    B = np.zeros((m * W, k * W), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            B[i * W : (i + 1) * W, j * W : (j + 1) * W] = elem_bitmatrix(int(M[i, j]))
    return B


def bitmatrix_invert(B: np.ndarray) -> np.ndarray:
    """Invert a square GF(2) bitmatrix (Gauss-Jordan, XOR pivoting)."""
    B = np.asarray(B, dtype=np.uint8).copy()
    n = B.shape[0]
    if B.shape != (n, n):
        raise ValueError("bitmatrix must be square")
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        pivot = -1
        for row in range(col, n):
            if B[row, col]:
                pivot = row
                break
        if pivot < 0:
            raise np.linalg.LinAlgError("singular GF(2) matrix")
        if pivot != col:
            B[[col, pivot]] = B[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        for row in range(n):
            if row != col and B[row, col]:
                B[row] ^= B[col]
                inv[row] ^= inv[col]
    return inv
