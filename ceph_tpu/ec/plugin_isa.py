"""`isa` plugin: ISA-L-matrix-compatible Reed-Solomon on the TPU codec.

Re-creation of the reference's Intel ISA-L plugin
(src/erasure-code/isa/ErasureCodeIsa.{h,cc}): techniques `reed_sol_van`
(gf_gen_rs_matrix Vandermonde, :388) and `cauchy` (gf_gen_cauchy1_matrix,
:390). The reference caches decode tables in an LRU shared across instances
(ErasureCodeIsaTableCache.h:35) — here that role is played by the global
MatrixCodec / recovery-matrix LRUs in ceph_tpu.ops.rs_codec. The m=1
region_xor fast path (:127,201) becomes a plain XOR on device (a 1-row
all-ones bitmatrix), which XLA lowers to the same thing.
"""
from __future__ import annotations

from typing import Mapping

import numpy as np

from ceph_tpu.ec import gf256
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.plugin_jerasure import ErasureCodeJerasure
from ceph_tpu.ec.registry import (ERASURE_CODE_VERSION, ErasureCodePlugin,
                                  ErasureCodePluginRegistry)

__erasure_code_version__ = ERASURE_CODE_VERSION

DEFAULT_K = 7
DEFAULT_M = 3


class ErasureCodeIsa(ErasureCodeJerasure):
    """Shares the matrix-code machinery; differs in matrix construction."""

    technique = "reed_sol_van"

    def init(self, profile: Mapping[str, str]) -> None:
        # ISA defaults differ from jerasure's (ErasureCodeIsa.h)
        profile = dict(profile)
        profile.setdefault("k", str(DEFAULT_K))
        profile.setdefault("m", str(DEFAULT_M))
        super().init(profile)

    def get_alignment(self) -> int:
        # reference ISA-L pads to 64B (EC_ISA_ADDRESS_ALIGNMENT); TPU lanes
        # want 128, which is a multiple, so both contracts hold.
        return 128


class ErasureCodeIsaVandermonde(ErasureCodeIsa):
    technique = "reed_sol_van"

    def _check_technique(self) -> None:
        # The reference rejects (err=-EINVAL) geometries where the raw ISA-L
        # Vandermonde is not verified MDS: k<=32, m<=4, and k<=21 when m=4
        # (src/erasure-code/isa/ErasureCodeIsa.cc:331-362). Same limits here.
        if self.k > 32:
            raise ErasureCodeError(
                f"Vandermonde: k={self.k} should be less/equal than 32")
        if self.m > 4:
            raise ErasureCodeError(
                f"Vandermonde: m={self.m} should be less than 5 to guarantee "
                "an MDS codec; use technique=cauchy")
        if self.m == 4 and self.k > 21:
            raise ErasureCodeError(
                f"Vandermonde: k={self.k} should be less than 22 to guarantee "
                "an MDS codec with m=4")

    def _build_matrix(self) -> np.ndarray:
        return gf256.isa_rs_vandermonde_matrix(self.k, self.m)


class ErasureCodeIsaCauchy(ErasureCodeIsa):
    technique = "cauchy"

    def _build_matrix(self) -> np.ndarray:
        return gf256.isa_cauchy1_matrix(self.k, self.m)


_TECHNIQUES = {
    "reed_sol_van": ErasureCodeIsaVandermonde,
    "cauchy": ErasureCodeIsaCauchy,
}


class ErasureCodePluginIsa(ErasureCodePlugin):
    def factory(self, profile: Mapping[str, str], directory: str | None = None):
        technique = profile.get("technique", "reed_sol_van")
        cls = _TECHNIQUES.get(technique)
        if cls is None:
            raise ErasureCodeError(f"unknown isa technique {technique!r}")
        instance = cls()
        instance.init(profile)
        return instance


def __erasure_code_init__(name: str, directory: str | None = None):
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginIsa())
