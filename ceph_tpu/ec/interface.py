"""Erasure-code plugin interface + base class.

Re-creation of the reference's plugin contract in idiomatic Python
(reference: src/erasure-code/ErasureCodeInterface.h:170-476 and
src/erasure-code/ErasureCode.{h,cc}); the C++ ABI mirror lives under
native/. A code is *systematic*: k data chunks + m coding chunks; any k of
the k+m suffice to reconstruct. Profiles are string->string maps
(ErasureCodeInterface.h:155). Buffers cross the interface as `bytes`;
device arrays stay internal to plugins.

Sub-chunk support (ErasureCodeInterface.h:297 minimum_to_decode): each chunk
is logically divided into `get_sub_chunk_count()` sub-chunks; regenerating
codes (clay) request only some sub-chunk ranges from helpers during repair.
"""
from __future__ import annotations

import math
from typing import Iterable, Mapping, Sequence

import numpy as np

from ceph_tpu.utils import sanitizer

# Reference pads chunks to SIMD_ALIGN=32 (ErasureCode.cc:42). TPU lane tiles
# want the byte axis in multiples of 128; padding is imposed through
# get_chunk_size, the sanctioned place per ErasureCodeIsa.cc:66-78.
TPU_ALIGN = 128

ErasureCodeProfile = dict  # str -> str


class ErasureCodeError(Exception):
    """Raised for profile/argument errors (stand-in for -EINVAL etc.)."""


class ErasureCodeInterface:
    """Abstract systematic erasure-code API (ErasureCodeInterface.h:170)."""

    def init(self, profile: Mapping[str, str]) -> None:
        raise NotImplementedError

    def get_profile(self) -> ErasureCodeProfile:
        raise NotImplementedError

    def get_chunk_count(self) -> int:
        """k + m (ErasureCodeInterface.h:227)."""
        raise NotImplementedError

    def get_data_chunk_count(self) -> int:
        raise NotImplementedError

    def get_coding_chunk_count(self) -> int:
        raise NotImplementedError

    def get_sub_chunk_count(self) -> int:
        """Sub-chunks per chunk; 1 for scalar codes, q^t for clay."""
        return 1

    def get_chunk_size(self, stripe_width: int) -> int:
        """Chunk size for an object of `stripe_width` bytes, including
        alignment padding (ErasureCodeInterface.h:278)."""
        raise NotImplementedError

    def minimum_to_decode(self, want_to_read: Iterable[int],
                          available: Iterable[int]) -> dict[int, list[tuple[int, int]]]:
        """Minimum chunks (with per-chunk sub-chunk (offset,count) ranges)
        needed to decode `want_to_read` given `available`
        (ErasureCodeInterface.h:297)."""
        raise NotImplementedError

    def minimum_to_decode_with_cost(self, want_to_read: Iterable[int],
                                    available: Mapping[int, int]) -> list[int]:
        """Like minimum_to_decode but `available` maps chunk -> retrieval cost
        (ErasureCodeInterface.h:326)."""
        raise NotImplementedError

    def encode(self, want_to_encode: Iterable[int], data: bytes) -> dict[int, bytes]:
        """Pad+split `data` into k chunks, compute m parity chunks, return the
        requested subset (ErasureCodeInterface.h:365)."""
        raise NotImplementedError

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        """Kernel entry: chunks 0..k-1 hold data; fill chunks k..k+m-1
        in place (ErasureCodeInterface.h:370)."""
        raise NotImplementedError

    def decode(self, want_to_read: Iterable[int], chunks: Mapping[int, bytes],
               chunk_size: int) -> dict[int, bytes]:
        """Reconstruct `want_to_read` from available `chunks`
        (ErasureCodeInterface.h:407)."""
        raise NotImplementedError

    def decode_chunks(self, want_to_read: Iterable[int],
                      chunks: dict[int, np.ndarray],
                      available: set[int]) -> None:
        """Kernel entry: reconstruct the `want_to_read` arrays in place.
        `chunks` holds every chunk id (zero-filled holes for missing ones);
        `available` is the set of ids holding real data."""
        raise NotImplementedError

    def get_chunk_mapping(self) -> list[int]:
        """Chunk index remapping, empty list = identity
        (ErasureCodeInterface.h:448)."""
        raise NotImplementedError

    def decode_concat(self, chunks: Mapping[int, bytes],
                      chunk_size: int) -> bytes:
        """Decode data chunks and concatenate in rank order
        (ErasureCodeInterface.h:464)."""
        raise NotImplementedError


class ErasureCode(ErasureCodeInterface):
    """Default behavior shared by plugins (src/erasure-code/ErasureCode.cc).

    Subclasses set self.k / self.m in init() and implement encode_chunks /
    decode_chunks (and optionally override minimum_to_decode & friends).
    """

    #: profile keys consumed by the framework, excluded from "unknown key" checks
    _COMMON_KEYS = {
        "plugin", "technique", "k", "m", "w", "packetsize", "mapping",
        "crush-root", "crush-failure-domain", "crush-device-class",
        "crush-num-failure-domains", "crush-osds-per-failure-domain",
        "ruleset-root", "ruleset-failure-domain", "directory",
    }

    def __init__(self):
        self.k = 0
        self.m = 0
        self._profile: ErasureCodeProfile = {}
        self.chunk_mapping: list[int] = []

    # -- profile plumbing ---------------------------------------------------

    def init(self, profile: Mapping[str, str]) -> None:
        self._profile = dict(profile)
        mapping = self._profile.get("mapping")
        if mapping:
            self._parse_mapping(mapping)

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    def _parse_mapping(self, mapping: str) -> None:
        """Profile `mapping=DD_D...`: position i of the generated chunk vector
        is stored at shard i only where pattern has 'D' (ErasureCode.cc:280)."""
        positions = [i for i, c in enumerate(mapping) if c == "D"]
        self.chunk_mapping = positions

    def to_int(self, name: str, profile: Mapping[str, str], default: int,
               minimum: int | None = None, maximum: int | None = None) -> int:
        raw = profile.get(name)
        if raw is None or raw == "":
            return default
        try:
            val = int(raw)
        except ValueError as e:
            raise ErasureCodeError(f"{name}={raw!r} is not an integer") from e
        if minimum is not None and val < minimum:
            raise ErasureCodeError(f"{name}={val} is below minimum {minimum}")
        if maximum is not None and val > maximum:
            raise ErasureCodeError(f"{name}={val} is above maximum {maximum}")
        return val

    def to_bool(self, name: str, profile: Mapping[str, str], default: bool) -> bool:
        raw = profile.get(name)
        if raw is None or raw == "":
            return default
        return str(raw).lower() in ("true", "1", "yes", "on")

    # -- geometry -----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_coding_chunk_count(self) -> int:
        return self.m

    def get_alignment(self) -> int:
        """Per-chunk byte alignment this plugin requires."""
        return TPU_ALIGN

    def get_chunk_size(self, stripe_width: int) -> int:
        align = self.get_alignment()
        padded = self.k * align * math.ceil(stripe_width / (self.k * align))
        return padded // self.k

    def get_chunk_mapping(self) -> list[int]:
        return list(self.chunk_mapping)

    # -- minimum_to_decode --------------------------------------------------

    def _minimum_to_decode(self, want_to_read: set[int],
                           available: set[int]) -> set[int]:
        """Default policy (ErasureCode.cc:122): if everything wanted is
        available return it; else any k available chunks (lowest ids first)."""
        if want_to_read <= available:
            return set(want_to_read)
        if len(available) < self.k:
            raise ErasureCodeError(
                f"cannot decode: {len(available)} chunks available, need {self.k}")
        return set(sorted(available)[: self.k])

    def minimum_to_decode(self, want_to_read: Iterable[int],
                          available: Iterable[int]) -> dict[int, list[tuple[int, int]]]:
        chosen = self._minimum_to_decode(set(want_to_read), set(available))
        sub = self.get_sub_chunk_count()
        return {c: [(0, sub)] for c in sorted(chosen)}

    def minimum_to_decode_with_cost(self, want_to_read: Iterable[int],
                                    available: Mapping[int, int]) -> list[int]:
        want = set(want_to_read)
        avail = set(available)
        if want <= avail:
            return sorted(want)
        if len(avail) < self.k:
            raise ErasureCodeError("not enough chunks to decode")
        # cheapest k chunks
        return sorted(sorted(avail, key=lambda c: (available[c], c))[: self.k])

    # -- encode/decode ------------------------------------------------------

    def encode_prepare(self, data: bytes) -> dict[int, np.ndarray]:
        """Split + zero-pad input into k aligned chunks (ErasureCode.cc:170).

        Data rank i lands at position chunk_mapping[i] when a mapping is
        set (lrc's sparse layouts); all other positions are zero-initialized
        coding chunks.
        """
        data = sanitizer.unwrap(data)   # numpy boundary: checked unwrap
        chunk_size = self.get_chunk_size(len(data))
        mapping = self.get_chunk_mapping()
        chunks: dict[int, np.ndarray] = {
            i: np.zeros(chunk_size, dtype=np.uint8)
            for i in range(self.get_chunk_count())}
        for i in range(self.k):
            pos = mapping[i] if mapping else i
            lo = i * chunk_size
            hi = min(len(data), lo + chunk_size)
            if hi > lo:
                chunks[pos][: hi - lo] = np.frombuffer(data[lo:hi],
                                                       dtype=np.uint8)
        return chunks

    def encode(self, want_to_encode: Iterable[int], data: bytes) -> dict[int, bytes]:
        chunks = self.encode_prepare(data)
        self.encode_chunks(chunks)
        want = set(want_to_encode)
        return {i: chunks[i].tobytes() for i in sorted(want)}

    def _decode(self, want_to_read: set[int],
                chunks: Mapping[int, bytes], chunk_size: int) -> dict[int, np.ndarray]:
        """Fill holes then decode_chunks (ErasureCode.cc:225)."""
        arrays: dict[int, np.ndarray] = {}
        for i, buf in chunks.items():
            # zero-copy read-only view; only the holes below get (writable)
            # fresh buffers — avoids a full-stripe memcpy on the degraded-read
            # hot path (the reference avoids the same via bufferlist views)
            arr = np.frombuffer(buf, dtype=np.uint8)
            if len(arr) != chunk_size:
                raise ErasureCodeError(
                    f"chunk {i} has size {len(arr)}, expected {chunk_size}")
            arrays[i] = arr
        if want_to_read <= set(arrays):
            return {i: arrays[i] for i in want_to_read}
        for i in range(self.get_chunk_count()):
            if i not in arrays:
                arrays[i] = np.zeros(chunk_size, dtype=np.uint8)
        self.decode_chunks(want_to_read, dict(arrays),
                           available=set(chunks))
        return {i: arrays[i] for i in want_to_read}

    def decode(self, want_to_read: Iterable[int], chunks: Mapping[int, bytes],
               chunk_size: int) -> dict[int, bytes]:
        out = self._decode(set(want_to_read), chunks, chunk_size)
        return {i: a.tobytes() for i, a in out.items()}

    def decode_chunks(self, want_to_read: Iterable[int],
                      chunks: dict[int, np.ndarray],
                      available: set[int]) -> None:
        """Kernel entry: reconstruct the `want_to_read` arrays in `chunks` in
        place. `chunks` holds every chunk id with zero-filled holes for the
        missing ones; `available` is the set of ids holding real data."""
        raise NotImplementedError

    def decode_concat(self, chunks: Mapping[int, bytes], chunk_size: int) -> bytes:
        want = list(range(self.k))
        mapping = self.get_chunk_mapping()
        if mapping:
            want = [mapping[i] for i in range(self.k)]
        decoded = self.decode(want, chunks, chunk_size)
        return b"".join(decoded[i] for i in want)
