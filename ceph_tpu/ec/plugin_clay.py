"""`clay` plugin — Coupled-LAYer MSR regenerating code.

Re-creation of the reference's clay plugin behavior
(src/erasure-code/clay/ErasureCodeClay.{h,cc}; algorithm from Vajha et al.,
"Clay Codes: Moulding MDS Codes to Yield Vector Codes", FAST '18): chunks
form a q x t grid (q = d-k+1, q*t = k+m+nu), each chunk split into
sub_chunk_no = q^t sub-chunks, one per "plane" z (a base-q vector). Repair
of a single chunk reads only sub_chunk_no/q sub-chunks from each of d
helpers — the bandwidth-optimal MSR property — surfaced through
`minimum_to_decode`'s per-chunk (sub-chunk offset, count) runs
(ErasureCodeClay.cc:98-130; note the reference snapshot disables its
`is_repair` gate with an XXX — here the sub-chunk repair path is live).

Design differences from the reference (original implementation, not
byte-compatible with reference clay chunks):
  * the pairwise coupling is an explicit 2x2 transform over GF(2^8),
    [U_a; U_b] = [[1, g],[g, 1]] [C_a; C_b] with g=2 (invertible since
    1 + g^2 != 0), applied as vectorized numpy table lookups — the
    reference routes every pair through a k=2,m=2 scalar-RS decode;
  * the per-plane MDS decodes are batched by decoding order: all planes of
    one intersection score go to the device codec as a single matrix apply
    (ceph_tpu.ops.rs_codec), instead of one scalar decode per plane.

The inner MDS code is any registered scalar plugin (jerasure/isa/tpu) with
k' = k+nu, m' = m, exposing its coding matrix.
"""
from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np

from ceph_tpu.ec import gf256
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError
from ceph_tpu.ec.registry import (ERASURE_CODE_VERSION, ErasureCodePlugin,
                                  ErasureCodePluginRegistry)
from ceph_tpu.ops import rs_codec
from ceph_tpu.utils import sanitizer

__erasure_code_version__ = ERASURE_CODE_VERSION

GAMMA = 2  # coupling coefficient g; 1 XOR g*g = 5 != 0 so the PFT inverts


def _mul(c: int, arr: np.ndarray) -> np.ndarray:
    return gf256.GF_MUL_TABLE[c, arr]


_INV_DET = gf256.gf_inv(1 ^ gf256.gf_mul(GAMMA, GAMMA))
_INV_GAMMA = gf256.gf_inv(GAMMA)


class _Pair:
    """Solve the pairwise coupling transform given any two known symbols.

    Canonical order: `a` is the pair element whose own x-digit exceeds its
    companion's. U_a = C_a + g*C_b ; U_b = g*C_a + C_b.
    """

    @staticmethod
    def cc_from_uu(Ua, Ub):
        Ca = _mul(_INV_DET, Ua ^ _mul(GAMMA, Ub))
        Cb = _mul(_INV_DET, _mul(GAMMA, Ua) ^ Ub)
        return Ca, Cb

    @staticmethod
    def uu_from_cc(Ca, Cb):
        return Ca ^ _mul(GAMMA, Cb), _mul(GAMMA, Ca) ^ Cb

    @staticmethod
    def ua_from_ca_ub(Ca, Ub):
        Cb = Ub ^ _mul(GAMMA, Ca)
        return Ca ^ _mul(GAMMA, Cb)

    @staticmethod
    def ub_from_cb_ua(Cb, Ua):
        Ca = Ua ^ _mul(GAMMA, Cb)
        return _mul(GAMMA, Ca) ^ Cb

    @staticmethod
    def ca_from_ua_cb(Ua, Cb):
        return Ua ^ _mul(GAMMA, Cb)

    @staticmethod
    def cb_from_ub_ca(Ub, Ca):
        return Ub ^ _mul(GAMMA, Ca)

    @staticmethod
    def cb_from_ua_ca(Ua, Ca):
        return _mul(_INV_GAMMA, Ua ^ Ca)

    @staticmethod
    def ca_from_ub_cb(Ub, Cb):
        return _mul(_INV_GAMMA, Ub ^ Cb)


class ErasureCodeClay(ErasureCode):
    def __init__(self):
        super().__init__()
        self.d = 0
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 0
        self.mds = None  # inner scalar MDS over the q*t grid

    # -- init ---------------------------------------------------------------

    def init(self, profile: Mapping[str, str]) -> None:
        super().init(profile)
        self.k = self.to_int("k", profile, 4, minimum=1)
        self.m = self.to_int("m", profile, 2, minimum=1)
        self.d = self.to_int("d", profile, self.k + self.m - 1)
        if not self.k <= self.d <= self.k + self.m - 1:
            raise ErasureCodeError(
                f"d={self.d} must be within [{self.k},{self.k + self.m - 1}]")
        scalar_mds = profile.get("scalar_mds", "jerasure") or "jerasure"
        if scalar_mds not in ("jerasure", "isa", "tpu"):
            raise ErasureCodeError(
                f"scalar_mds {scalar_mds!r} unsupported; use jerasure/isa/tpu")
        technique = profile.get("technique", "reed_sol_van") or "reed_sol_van"

        self.q = self.d - self.k + 1
        self.nu = (-(self.k + self.m)) % self.q
        if self.k + self.m + self.nu > 254:
            raise ErasureCodeError("k+m+nu must be <= 254")
        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = self.q ** self.t

        mds_profile = {"plugin": scalar_mds, "technique": technique,
                       "k": str(self.k + self.nu), "m": str(self.m),
                       "w": "8"}
        self.mds = ErasureCodePluginRegistry.instance().factory(
            scalar_mds, mds_profile)
        if getattr(self.mds, "coding_matrix", None) is None:
            raise ErasureCodeError(
                f"inner plugin {scalar_mds} exposes no coding matrix")
        self._profile.update({"k": str(self.k), "m": str(self.m),
                              "d": str(self.d), "scalar_mds": scalar_mds,
                              "technique": technique, "w": "8"})

    # -- geometry -----------------------------------------------------------

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_chunk_size(self, stripe_width: int) -> int:
        # every sub-chunk must hold the scalar code's alignment
        # (reference ErasureCodeClay.cc get_chunk_size)
        alignment = self.sub_chunk_no * self.k * self.mds.get_alignment()
        padded = alignment * -(-stripe_width // alignment)
        return padded // self.k

    def _grid_id(self, chunk_id: int) -> int:
        """Real chunk id -> grid node id (virtual nodes occupy k..k+nu-1)."""
        return chunk_id if chunk_id < self.k else chunk_id + self.nu

    def _chunk_id(self, node: int) -> int | None:
        """Grid node id -> real chunk id (None for virtual nodes)."""
        if node < self.k:
            return node
        if node < self.k + self.nu:
            return None
        return node - self.nu

    def _z_vec(self, z: int) -> list[int]:
        """Base-q digits of plane z, most significant first (digit[y])."""
        digits = [0] * self.t
        for i in range(self.t - 1, -1, -1):
            digits[i] = z % self.q
            z //= self.q
        return digits

    def _z_sw(self, z: int, y: int, new_digit: int) -> int:
        old = self._z_vec(z)[y]
        return z + (new_digit - old) * self.q ** (self.t - 1 - y)

    # -- repair planning ----------------------------------------------------

    def is_repair(self, want_to_read: set[int], available: set[int]) -> bool:
        """True when the bandwidth-optimal single-chunk repair path applies:
        one lost chunk, its whole grid column group surviving, >= d helpers
        (original ErasureCodeClay::is_repair semantics)."""
        if want_to_read <= available:
            return False
        if len(want_to_read) != 1:
            return False
        if len(available) < self.d:
            return False
        lost = self._grid_id(next(iter(want_to_read)))
        y0 = lost // self.q
        for x in range(self.q):
            node = y0 * self.q + x
            cid = self._chunk_id(node)
            if cid is None or cid in want_to_read:
                continue
            if cid not in available:
                return False
        return True

    def get_repair_subchunks(self, lost_node: int) -> list[tuple[int, int]]:
        """(sub-chunk index, count) runs of planes with digit[y0] == x0
        (ErasureCodeClay::get_repair_subchunks semantics)."""
        y0, x0 = divmod(lost_node, self.q)
        run = self.q ** (self.t - 1 - y0)
        stride = run * self.q
        return [(x0 * run + s * stride, run) for s in range(self.q ** y0)]

    def minimum_to_decode(self, want_to_read: Iterable[int],
                          available: Iterable[int]) -> dict[int, list[tuple[int, int]]]:
        want = set(want_to_read)
        avail = set(available)
        if not self.is_repair(want, avail):
            return super().minimum_to_decode(want, avail)
        lost = self._grid_id(next(iter(want)))
        runs = self.get_repair_subchunks(lost)
        minimum: dict[int, list[tuple[int, int]]] = {}
        y0 = lost // self.q
        for x in range(self.q):
            cid = self._chunk_id(y0 * self.q + x)
            if cid is not None and cid not in want:
                minimum[cid] = list(runs)
        for cid in sorted(avail):
            if len(minimum) >= self.d:
                break
            minimum.setdefault(cid, list(runs))
        if len(minimum) != self.d:
            raise ErasureCodeError(
                f"repair needs {self.d} helpers, found {len(minimum)}")
        return minimum

    # -- kernels ------------------------------------------------------------

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        C = self._grid_views(chunks)
        erased = {self._grid_id(i) for i in range(self.k, self.k + self.m)}
        self._decode_layered(erased, C)

    def decode_chunks(self, want_to_read: Iterable[int],
                      chunks: dict[int, np.ndarray],
                      available: set[int]) -> None:
        C = self._grid_views(chunks)
        erased = {self._grid_id(i) for i in range(self.k + self.m)
                  if i not in available}
        if not erased:
            return
        self._decode_layered(erased, C)

    def decode(self, want_to_read: Iterable[int],
               chunks: Mapping[int, bytes], chunk_size: int) -> dict[int, bytes]:
        want = set(want_to_read)
        avail = set(chunks)
        lens = {len(b) for b in chunks.values()}
        if self.is_repair(want, avail) and lens and max(lens) < chunk_size:
            return self._repair(want, chunks, chunk_size)
        return super().decode(want, chunks, chunk_size)

    # -- internals ----------------------------------------------------------

    def _grid_views(self, chunks: dict[int, np.ndarray]) -> dict[int, np.ndarray]:
        """Map chunk arrays into grid-node (sub_chunk_no, sc) views; virtual
        shortening nodes get zero buffers."""
        size = chunks[0].size
        if size % self.sub_chunk_no:
            raise ErasureCodeError(
                f"chunk size {size} not divisible by {self.sub_chunk_no} "
                "sub-chunks")
        sc = size // self.sub_chunk_no
        C: dict[int, np.ndarray] = {}
        for i in range(self.k + self.m):
            C[self._grid_id(i)] = chunks[i].reshape(self.sub_chunk_no, sc)
        for node in range(self.k, self.k + self.nu):
            C[node] = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        return C

    def _decode_uncoupled_batch(self, erased: set[int], zs: list[int],
                                U: dict[int, np.ndarray]) -> None:
        """MDS-decode the U symbols of `erased` nodes for all planes in
        `zs` with ONE device matrix apply (the reference decodes plane by
        plane, ErasureCodeClay::decode_uncoupled)."""
        if not zs:
            return
        avail = tuple(i for i in range(self.q * self.t) if i not in erased)
        want = tuple(sorted(erased))
        R = rs_codec.recovery_matrix(self.mds.coding_matrix, avail, want)
        sc = U[0].shape[1]
        src = np.stack([U[i][zs].reshape(-1) for i in avail])  # (k', nz*sc)
        out = rs_codec.MatrixCodec.get(R).apply(src)
        for row, node in enumerate(want):
            U[node][zs] = out[row].reshape(len(zs), sc)

    def _plane_scores(self, erased: set[int]) -> list[int]:
        scores = []
        for z in range(self.sub_chunk_no):
            zv = self._z_vec(z)
            scores.append(sum(1 for i in erased if i % self.q == zv[i // self.q]))
        return scores

    def _decode_layered(self, erased: set[int],
                        C: dict[int, np.ndarray]) -> None:
        """Full-chunk decode: recover the C symbols of `erased` grid nodes
        in place (ErasureCodeClay::decode_layered structure)."""
        erased = set(erased)
        # pad with virtual/parity nodes so the MDS step sees exactly m holes
        for node in range(self.k + self.nu, self.q * self.t):
            if len(erased) >= self.m:
                break
            erased.add(node)
        for node in range(self.k, self.k + self.nu):
            if len(erased) >= self.m:
                break
            erased.add(node)
        if len(erased) != self.m:
            raise ErasureCodeError(
                f"cannot decode {len(erased)} > m={self.m} erasures")
        # nodes added only to round the MDS hole count up to m may be
        # read-only caller views; recompute into private scratch copies
        for node in erased:
            if not C[node].flags.writeable:
                C[node] = C[node].copy()

        q, t = self.q, self.t
        sub, sc = C[0].shape
        U = {node: np.zeros((sub, sc), dtype=np.uint8)
             for node in range(q * t)}
        scores = self._plane_scores(erased)

        for score in range(max(scores) + 1):
            zs = [z for z in range(sub) if scores[z] == score]
            # phase 1a: uncouple every non-erased node's known symbols
            for z in zs:
                zv = self._z_vec(z)
                for node in range(q * t):
                    if node in erased:
                        continue
                    y, x = divmod(node, q)
                    if zv[y] == x:
                        U[node][z] = C[node][z]
                        continue
                    node_sw = y * q + zv[y]
                    z_sw = self._z_sw(z, y, x)
                    if zv[y] < x:
                        # canonical side: this node is `a`; fills both U's
                        Ua, Ub = _Pair.uu_from_cc(C[node][z], C[node_sw][z_sw])
                        U[node][z] = Ua
                        U[node_sw][z_sw] = Ub
                    elif node_sw in erased:
                        # companion erased: its C at z_sw was recovered at
                        # score-1; this node is `b` of the pair
                        Ua, Ub = _Pair.uu_from_cc(C[node_sw][z_sw], C[node][z])
                        U[node_sw][z_sw] = Ua
                        U[node][z] = Ub
            # phase 1b: one batched MDS decode for all planes of this score
            self._decode_uncoupled_batch(erased, zs, U)
            # phase 2: re-couple to recover erased C symbols
            for z in zs:
                zv = self._z_vec(z)
                for node in sorted(erased):
                    y, x = divmod(node, q)
                    node_sw = y * q + zv[y]
                    z_sw = self._z_sw(z, y, x)
                    if zv[y] == x:
                        C[node][z] = U[node][z]
                    elif node_sw not in erased:
                        # companion C known; recover this C from (U, C_sw)
                        if zv[y] < x:  # this node is `a`
                            C[node][z] = _Pair.ca_from_ua_cb(
                                U[node][z], C[node_sw][z_sw])
                        else:          # this node is `b`
                            C[node][z] = _Pair.cb_from_ub_ca(
                                U[node][z], C[node_sw][z_sw])
                    elif zv[y] < x:
                        # both erased: rebuild the whole pair from both U's
                        Ca, Cb = _Pair.cc_from_uu(U[node][z],
                                                  U[node_sw][z_sw])
                        C[node][z] = Ca
                        C[node_sw][z_sw] = Cb

    # -- sub-chunk repair ---------------------------------------------------

    def _repair(self, want: set[int], chunks: Mapping[int, bytes],
                chunk_size: int) -> dict[int, bytes]:
        """Single-chunk repair reading only repair sub-chunks from d helpers
        (ErasureCodeClay::repair / repair_one_lost_chunk structure)."""
        if chunk_size % self.sub_chunk_no:
            raise ErasureCodeError("chunk_size not sub-chunk aligned")
        sc = chunk_size // self.sub_chunk_no
        repair_subchunks = self.sub_chunk_no // self.q
        repair_blocksize = repair_subchunks * sc
        lost_cid = next(iter(want))
        lost = self._grid_id(lost_cid)
        q, t = self.q, self.t

        runs = self.get_repair_subchunks(lost)
        repair_zs = [z for off, cnt in runs for z in range(off, off + cnt)]
        plane_to_ind = {z: i for i, z in enumerate(repair_zs)}

        # helper C data, reshaped (repair_subchunks, sc); virtual nodes zero
        helper: dict[int, np.ndarray] = {}
        aloof: set[int] = set()
        for i in range(self.k + self.m):
            node = self._grid_id(i)
            if i in chunks:
                buf = np.frombuffer(sanitizer.unwrap(chunks[i]),
                                    dtype=np.uint8)
                if buf.size != repair_blocksize:
                    raise ErasureCodeError(
                        f"helper {i} has {buf.size} bytes, expected "
                        f"{repair_blocksize}")
                helper[node] = buf.reshape(repair_subchunks, sc)
            elif i != lost_cid:
                aloof.add(node)
        for node in range(self.k, self.k + self.nu):
            helper[node] = np.zeros((repair_subchunks, sc), dtype=np.uint8)
        if len(helper) + len(aloof) + 1 != q * t:
            raise ErasureCodeError("helper/aloof accounting mismatch")

        # MDS-erased set: the lost node's whole column group + aloof nodes
        y0 = lost // q
        group = {y0 * q + x for x in range(q)}
        erased = group | aloof
        if len(erased) > self.m:
            raise ErasureCodeError(
                f"repair needs {len(erased)} MDS erasures > m={self.m} "
                "(too few helpers)")
        # surplus helpers (caller sent more than d): demote to aloof so the
        # MDS step sees exactly m erasures
        for node in sorted((set(helper) - group), reverse=True):
            if len(erased) >= self.m:
                break
            if self._chunk_id(node) is None:
                continue  # keep virtual shortening helpers
            del helper[node]
            aloof.add(node)
            erased.add(node)
        if len(erased) != self.m:
            raise ErasureCodeError(
                f"{len(erased)} MDS erasures != m={self.m}")

        recovered = np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
        U = {node: np.zeros((self.sub_chunk_no, sc), dtype=np.uint8)
             for node in range(q * t)}

        # order repair planes by intersection score over the erased set
        scores = {}
        for z in repair_zs:
            zv = self._z_vec(z)
            scores[z] = sum(1 for i in erased if i % q == zv[i // q])

        for score in range(1, max(scores.values()) + 1):
            zs = sorted(z for z in repair_zs if scores[z] == score)
            for z in zs:
                zv = self._z_vec(z)
                for node in range(q * t):
                    if node in erased:
                        continue
                    y, x = divmod(node, q)
                    if zv[y] == x:
                        U[node][z] = helper[node][plane_to_ind[z]]
                        continue
                    node_sw = y * q + zv[y]
                    z_sw = self._z_sw(z, y, x)
                    c_here = helper[node][plane_to_ind[z]]
                    if node_sw in aloof:
                        # companion plane z_sw was handled at score-1; its
                        # U is known, companion C is not (aloof)
                        if zv[y] < x:
                            U[node][z] = _Pair.ua_from_ca_ub(
                                c_here, U[node_sw][z_sw])
                        else:
                            U[node][z] = _Pair.ub_from_cb_ua(
                                c_here, U[node_sw][z_sw])
                    else:
                        c_sw = helper[node_sw][plane_to_ind[z_sw]]
                        if zv[y] < x:
                            U[node][z] = _Pair.uu_from_cc(c_here, c_sw)[0]
                        else:
                            U[node][z] = _Pair.uu_from_cc(c_sw, c_here)[1]
            self._decode_uncoupled_batch(erased, zs, U)
            for z in zs:
                zv = self._z_vec(z)
                for node in sorted(erased - aloof):
                    y, x = divmod(node, q)
                    if zv[y] == x:
                        if node != lost:
                            raise ErasureCodeError("unexpected dot node")
                        recovered[z] = U[node][z]
                    else:
                        # group helper: its C is known; recover the LOST
                        # node's C at companion plane z_sw
                        node_sw = y * q + zv[y]
                        z_sw = self._z_sw(z, y, x)
                        if node_sw != lost:
                            raise ErasureCodeError("companion is not lost node")
                        c_here = helper[node][plane_to_ind[z]]
                        if zv[y] < x:
                            # node is `a` (knowns U_a, C_a), lost is `b`
                            recovered[z_sw] = _Pair.cb_from_ua_ca(
                                U[node][z], c_here)
                        else:
                            # node is `b` (knowns U_b, C_b), lost is `a`
                            recovered[z_sw] = _Pair.ca_from_ub_cb(
                                U[node][z], c_here)
        return {lost_cid: recovered.tobytes()}


class ErasureCodePluginClay(ErasureCodePlugin):
    def factory(self, profile: Mapping[str, str], directory: str | None = None):
        instance = ErasureCodeClay()
        instance.init(profile)
        return instance


def __erasure_code_init__(name: str, directory: str | None = None):
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginClay())
