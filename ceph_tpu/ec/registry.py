"""Erasure-code plugin registry.

Python analog of the reference's dlopen registry
(src/erasure-code/ErasureCodePlugin.{h,cc}): plugins are modules exposing
`__erasure_code_version__` (ABI gate, ErasureCodePlugin.cc:138) and
`__erasure_code_init__(name, directory)` which must register an
ErasureCodePlugin (:145-171). Built-in plugins resolve to
`ceph_tpu.ec.plugin_<name>`; external directories are searched for
`ec_<name>.py` the way the reference searches `libec_<name>.so`.
"""
from __future__ import annotations

import importlib
import importlib.util
import threading
from pathlib import Path
from typing import Mapping

from ceph_tpu.ec.interface import ErasureCodeError, ErasureCodeInterface

#: version every plugin must declare; mismatch refuses the load
ERASURE_CODE_VERSION = "ceph-tpu-ec-1"


class ErasureCodePlugin:
    """Base plugin: a named factory for code instances."""

    def factory(self, profile: Mapping[str, str],
                directory: str | None = None) -> ErasureCodeInterface:
        raise NotImplementedError


class ErasureCodePluginRegistry:
    _instance: "ErasureCodePluginRegistry | None" = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self._lock = threading.RLock()
        self.disable_dlclose = True  # parity with benchmark behavior; no-op here

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise ErasureCodeError(f"plugin {name} already registered")
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        with self._lock:
            return self._plugins.get(name)

    def remove(self, name: str) -> None:
        with self._lock:
            self._plugins.pop(name, None)

    # -- loading ------------------------------------------------------------

    def load(self, name: str, directory: str | None = None) -> ErasureCodePlugin:
        with self._lock:
            plugin = self._plugins.get(name)
            if plugin is not None:
                return plugin
            module = self._import_module(name, directory)
            version = getattr(module, "__erasure_code_version__", None)
            if version is None:
                raise ErasureCodeError(
                    f"plugin {name}: missing __erasure_code_version__")
            if version != ERASURE_CODE_VERSION:
                raise ErasureCodeError(
                    f"plugin {name}: version {version!r} does not match "
                    f"{ERASURE_CODE_VERSION!r}")
            init = getattr(module, "__erasure_code_init__", None)
            if init is None:
                raise ErasureCodeError(
                    f"plugin {name}: missing __erasure_code_init__ entry point")
            rc = init(name, directory)
            if rc not in (None, 0):
                raise ErasureCodeError(f"plugin {name}: init failed rc={rc}")
            plugin = self._plugins.get(name)
            if plugin is None:
                raise ErasureCodeError(
                    f"plugin {name}: init did not register the plugin")
            return plugin

    @staticmethod
    def _import_module(name: str, directory: str | None):
        if directory:
            path = Path(directory) / f"ec_{name}.py"
            if not path.exists():
                raise ErasureCodeError(f"plugin file not found: {path}")
            spec = importlib.util.spec_from_file_location(f"ec_ext_{name}", path)
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)  # type: ignore[union-attr]
            return module
        try:
            return importlib.import_module(f"ceph_tpu.ec.plugin_{name}")
        except ImportError as e:
            raise ErasureCodeError(f"no builtin plugin {name!r}: {e}") from e

    def factory(self, name: str, profile: Mapping[str, str],
                directory: str | None = None) -> ErasureCodeInterface:
        """Build and init a code instance (ErasureCodePlugin.cc:86); verifies
        the instance adopted the profile it was given (:108)."""
        plugin = self.load(name, directory)
        instance = plugin.factory(profile, directory)
        got = instance.get_profile()
        for key, val in profile.items():
            if key == "directory":
                continue
            if str(got.get(key)) != str(val):
                raise ErasureCodeError(
                    f"profile mismatch after init: {key}={got.get(key)!r} "
                    f"!= requested {val!r}")
        return instance

    def preload(self, names: list[str], directory: str | None = None) -> None:
        """Load plugins at daemon start so a broken one fails fast
        (global_init_preload_erasure_code, src/global/global_init.cc:593)."""
        for name in names:
            self.load(name, directory)


def factory(name: str, profile: Mapping[str, str],
            directory: str | None = None) -> ErasureCodeInterface:
    """Module-level convenience mirroring registry().factory()."""
    return ErasureCodePluginRegistry.instance().factory(name, profile, directory)
