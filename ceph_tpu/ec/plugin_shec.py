"""`shec` plugin — Shingled Erasure Code.

Re-creation of the reference's SHEC plugin
(src/erasure-code/shec/ErasureCodeShec.{h,cc}): a non-MDS code trading
storage for recovery bandwidth. The m x k coding matrix starts as a
Vandermonde RS matrix and is then "shingled": each parity row keeps only a
sliding window of data columns (shec_reedsolomon_coding_matrix,
ErasureCodeShec.cc:465), so single-chunk recovery touches only the window.
technique=multiple splits (m, c) into two shingle bands chosen to minimize
the reference's recovery-efficiency metric (:424); technique=single uses
one band. Decoding searches parity subsets for a minimal invertible system
(shec_make_decoding_matrix, :535) because arbitrary erasure patterns are
not always recoverable; `minimum_to_decode` (:113) reports exactly the
window chunks that search selects.
"""
from __future__ import annotations

import itertools
from typing import Iterable, Mapping

import numpy as np

from ceph_tpu.ec import gf256
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError
from ceph_tpu.ec.registry import (ERASURE_CODE_VERSION, ErasureCodePlugin,
                                  ErasureCodePluginRegistry)
from ceph_tpu.ops import rs_codec

__erasure_code_version__ = ERASURE_CODE_VERSION

DEFAULT_K = 4
DEFAULT_M = 3
DEFAULT_C = 2


def _band_zero_ranges(k: int, mb: int, cb: int, row: int) -> list[int]:
    """Columns zeroed for `row` of a (mb, cb) shingle band: the cyclic range
    [start, end) with start=((row+cb)*k)//mb % k, end=(row*k)//mb % k —
    i.e. each row KEEPS a window of ((row+cb)*k)//mb - (row*k)//mb columns."""
    end = (row * k) // mb % k
    start = ((row + cb) * k) // mb % k
    cols = []
    cc = start
    while cc != end:
        cols.append(cc)
        cc = (cc + 1) % k
    return cols


def _recovery_efficiency(k: int, m1: int, m2: int, c1: int, c2: int) -> float:
    """The reference's r_e1 metric (shec_calc_recovery_efficiency1)."""
    window = [10 ** 8] * k
    total = 0.0
    for mb, cb, in ((m1, c1), (m2, c2)):
        for row in range(mb):
            width = ((row + cb) * k) // mb - (row * k) // mb
            start = (row * k) // mb % k
            end = ((row + cb) * k) // mb % k
            cc = start
            first = True
            while first or cc != end:
                first = False
                window[cc] = min(window[cc], width)
                cc = (cc + 1) % k
            total += width
    return (total + sum(window)) / (k + m1 + m2)


def shec_matrix(k: int, m: int, c: int, technique: str) -> np.ndarray:
    """(m, k) shingled coding matrix."""
    if technique == "single":
        splits = [(0, 0, m, c)]
    else:
        best, best_re = None, float("inf")
        for c1 in range(c // 2 + 1):
            for m1 in range(m + 1):
                c2, m2 = c - c1, m - m1
                if m1 < c1 or m2 < c2:
                    continue
                if (m1 == 0) != (c1 == 0) or (m2 == 0) != (c2 == 0):
                    continue
                re = _recovery_efficiency(k, m1, m2, c1, c2)
                if re < best_re - 1e-12:
                    best_re, best = re, (m1, c1, m2, c2)
        if best is None:
            raise ErasureCodeError(f"no valid shingle split for m={m} c={c}")
        m1, c1, m2, c2 = best
        splits = [(0, m1, m1, c1), (m1, m1 + m2, m2, c2)]
        splits = [(off, _, mb, cb) for off, _, mb, cb in splits if mb]

    M = np.array(gf256.reed_sol_van_matrix(k, m), dtype=np.uint8).copy()
    for off, _, mb, cb in splits:
        for row in range(mb):
            for col in _band_zero_ranges(k, mb, cb, row):
                M[off + row, col] = 0
    M.setflags(write=False)
    return M


class ErasureCodeShec(ErasureCode):
    technique = "multiple"

    def __init__(self):
        super().__init__()
        self.c = 0
        self.matrix: np.ndarray | None = None

    def init(self, profile: Mapping[str, str]) -> None:
        super().init(profile)
        has_any = any(profile.get(x) not in (None, "") for x in "kmc")
        has_all = all(profile.get(x) not in (None, "") for x in "kmc")
        if has_any and not has_all:
            raise ErasureCodeError("all of k, m, c must be chosen together")
        self.k = self.to_int("k", profile, DEFAULT_K, minimum=1)
        self.m = self.to_int("m", profile, DEFAULT_M, minimum=1)
        self.c = self.to_int("c", profile, DEFAULT_C, minimum=1)
        w = self.to_int("w", profile, 8)
        if w != 8:
            raise ErasureCodeError(f"w={w} unsupported; only w=8")
        if self.c > self.m:
            raise ErasureCodeError(f"c={self.c} must be <= m={self.m}")
        if self.k > 12:
            raise ErasureCodeError(f"k={self.k} must be <= 12")
        if self.k + self.m > 20:
            raise ErasureCodeError(f"k+m={self.k + self.m} must be <= 20")
        if self.m > self.k:
            raise ErasureCodeError(f"m={self.m} must be <= k={self.k}")
        technique = profile.get("technique", "multiple") or "multiple"
        if technique not in ("single", "multiple"):
            raise ErasureCodeError(f"unknown shec technique {technique!r}")
        self.technique = technique
        self.matrix = shec_matrix(self.k, self.m, self.c, technique)
        self._profile.update({"k": str(self.k), "m": str(self.m),
                              "c": str(self.c), "w": "8",
                              "technique": technique})

    # -- decode planning ----------------------------------------------------

    def _parity_support(self, p: int) -> set[int]:
        return {j for j in range(self.k) if self.matrix[p, j]}

    def _solve_plan(self, want: set[int], avail: set[int]):
        """Search parity subsets for a minimal solvable system
        (shec_make_decoding_matrix semantics). Returns
        (parities, unknown_data, A_inv, data_reads) or raises."""
        k, m = self.k, self.m
        erased = set(range(k + m)) - avail
        # data needed: wanted erased data + windows of wanted erased parity
        needed = {i for i in want if i < k and i in erased}
        for i in want:
            if i >= k and i in erased:
                needed |= self._parity_support(i - k) & erased
        best = None
        avail_parities = [p for p in range(m) if k + p in avail]
        for count in range(len(avail_parities) + 1):
            for P in itertools.combinations(avail_parities, count):
                unknowns = set(needed)
                for p in P:
                    unknowns |= self._parity_support(p) & erased
                if len(unknowns) != count:
                    continue
                cols = sorted(unknowns)  # all data ids: supports are < k
                A = self.matrix[np.ix_(list(P), cols)] if count else \
                    np.zeros((0, 0), dtype=np.uint8)
                if count:
                    try:
                        A_inv = gf256.mat_invert(A)
                    except np.linalg.LinAlgError:
                        continue
                else:
                    A_inv = A
                reads = set()
                for p in P:
                    reads |= self._parity_support(p) & avail
                best = (list(P), cols, A_inv, reads)
                break
            if best is not None:
                break
        if best is None:
            raise ErasureCodeError(
                f"cannot decode {sorted(want)} from {sorted(avail)}")
        return best

    def _minimum_to_decode(self, want_to_read: set[int],
                           available: set[int]) -> set[int]:
        want = set(want_to_read)
        avail = set(available)
        if want <= avail:
            return want
        P, cols, _, reads = self._solve_plan(want, avail)
        minimum = {self.k + p for p in P} | reads | (want & avail)
        # rebuilding a lost parity also reads the available part of its
        # data window (the erased part is in `cols`, recovered via P)
        for i in want:
            if i >= self.k and i not in avail:
                minimum |= self._parity_support(i - self.k) & avail
        return minimum

    # -- kernels ------------------------------------------------------------

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        parity = rs_codec.MatrixCodec.get(self.matrix).apply(data)
        for i in range(self.m):
            chunks[self.k + i][:] = parity[i]

    def decode_chunks(self, want_to_read: Iterable[int],
                      chunks: dict[int, np.ndarray],
                      available: set[int]) -> None:
        want = set(want_to_read) - set(available)
        if not want:
            return
        P, cols, A_inv, _ = self._solve_plan(want, set(available))
        k = self.k
        if cols:
            # rhs_p = parity_p XOR (contribution of available data)
            size = chunks[0].size
            rhs = np.zeros((len(P), size), dtype=np.uint8)
            for row, p in enumerate(P):
                acc = chunks[k + p].copy()
                for j in self._parity_support(p):
                    if j not in cols:
                        acc ^= gf256.GF_MUL_TABLE[self.matrix[p, j],
                                                  chunks[j]]
                rhs[row] = acc
            solved = rs_codec.MatrixCodec.get(A_inv).apply(rhs)
            for row, j in enumerate(cols):
                chunks[j][:] = solved[row]
        # recompute wanted erased parities from (now complete) data windows
        for i in want:
            if i >= k:
                p = i - k
                acc = np.zeros(chunks[0].size, dtype=np.uint8)
                for j in self._parity_support(p):
                    acc ^= gf256.GF_MUL_TABLE[self.matrix[p, j], chunks[j]]
                chunks[i][:] = acc


class ErasureCodeShecPlugin(ErasureCodePlugin):
    def factory(self, profile: Mapping[str, str], directory: str | None = None):
        instance = ErasureCodeShec()
        instance.init(profile)
        return instance


def __erasure_code_init__(name: str, directory: str | None = None):
    ErasureCodePluginRegistry.instance().add(name, ErasureCodeShecPlugin())
