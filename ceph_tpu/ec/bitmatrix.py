"""GF(2) bitmatrix RAID-6 codes: blaum_roth, liberation, liber8tion.

Re-creation of jerasure's minimal-density bitmatrix technique family
(reference src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}:353
bitmatrix + schedule dispatch; the vendored jerasure C implements the
constructions from the published papers):

  * blaum_roth: the Blaum-Roth construction over the ring
    GF(2)[x]/M_p(x) with p = w+1 prime, M_p(x) = 1 + x + ... + x^w;
    data disk i's Q-block is multiplication by x^i (the companion
    matrix power) — provably MDS for k <= w;
  * liberation / liber8tion: minimal-density codes of Plank's
    liberation family — Q-blocks are a cyclic rotation R^i plus extra
    bit(s). The defining property (lowest density + MDS) is enforced
    CONSTRUCTIVELY here: extra-bit positions are found by a
    deterministic search that verifies every 2-erasure pattern decodes,
    rather than transcribing jerasure's tables. The resulting matrices
    are therefore liberation-FAMILY codes (same density, same w
    constraints, same performance shape) whose exact bit placement may
    differ from jerasure's; the non-regression corpus pins OUR
    placement so on-disk stability is still guarded.

Data layout: a chunk of S bytes is w contiguous packets of S/w bytes
(jerasure's bitmatrix word layout); bit-row r of disk d is packet
d*w + r. Encoding XORs packets per the (m*w, k*w) coding bitmatrix;
decode inverts the surviving disks' generator rows over GF(2).

These codes run on the host XOR path (numpy bitwise_xor over packets):
RAID-6 m=2 workloads are XOR-bound, not MXU-bound — the TPU bitplane
matmul codec (ops/rs_codec.py) stays the hot path for the RS family.
"""
from __future__ import annotations

import functools

import numpy as np

from ceph_tpu.ec.interface import ErasureCodeError


# ---------------------------------------------------------------------------
# GF(2) linear algebra (dense uint8 {0,1} matrices)
# ---------------------------------------------------------------------------

def gf2_solve(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Solve A @ X = B over GF(2); raises if A is singular."""
    n = A.shape[0]
    M = np.concatenate([A.astype(np.uint8) & 1,
                        B.astype(np.uint8) & 1], axis=1)
    for col in range(n):
        piv = None
        for r in range(col, n):
            if M[r, col]:
                piv = r
                break
        if piv is None:
            raise ErasureCodeError("gf2_solve: singular matrix")
        if piv != col:
            M[[col, piv]] = M[[piv, col]]
        mask = M[:, col].astype(bool).copy()
        mask[col] = False
        M[mask] ^= M[col]
    return M[:, n:].copy()


def gf2_invertible(A: np.ndarray) -> bool:
    try:
        gf2_solve(A, np.eye(A.shape[0], dtype=np.uint8))
        return True
    except ErasureCodeError:
        return False


def gf2_apply(B: np.ndarray, packets: np.ndarray) -> np.ndarray:
    """out[r] = XOR of packets[c] where B[r, c] == 1.
    packets: (in_rows, packet_bytes) uint8."""
    out = np.zeros((B.shape[0], packets.shape[1]), dtype=np.uint8)
    for r in range(B.shape[0]):
        idx = np.nonzero(B[r])[0]
        if idx.size:
            out[r] = np.bitwise_xor.reduce(packets[idx], axis=0)
    return out


def _rot(w: int, i: int) -> np.ndarray:
    """R^i: ones at (j, (j + i) mod w)."""
    m = np.zeros((w, w), dtype=np.uint8)
    for j in range(w):
        m[j, (j + i) % w] = 1
    return m


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    return all(n % d for d in range(2, int(n ** 0.5) + 1))


# ---------------------------------------------------------------------------
# Constructions
# ---------------------------------------------------------------------------

def blaum_roth_blocks(k: int, w: int) -> list[np.ndarray]:
    """Q-blocks C^i over GF(2)[x]/M_p(x), p = w+1 prime."""
    if not _is_prime(w + 1):
        raise ErasureCodeError(f"blaum_roth: w+1={w + 1} must be prime")
    if k > w:
        raise ErasureCodeError(f"blaum_roth: k={k} > w={w}")
    # companion matrix of M_p(x) = 1 + x + ... + x^w  (x * x^j maps to
    # x^(j+1) for j < w-1; x * x^(w-1) = x^w = 1 + x + ... + x^(w-1))
    C = np.zeros((w, w), dtype=np.uint8)
    for j in range(w - 1):
        C[j + 1, j] = 1
    C[:, w - 1] = 1
    blocks = []
    X = np.eye(w, dtype=np.uint8)
    for _ in range(k):
        blocks.append(X.copy())
        X = (C @ X) & 1
    return blocks


def _mds_raid6(blocks: list[np.ndarray], w: int) -> bool:
    """Every 2-erasure pattern among (data..., P, Q) must decode."""
    k = len(blocks)
    n = k + 2
    G = generator(blocks, w)
    for a in range(n):
        for b in range(a + 1, n):
            keep = [d for d in range(n) if d not in (a, b)][:k]
            A = np.concatenate([G[d * w:(d + 1) * w] for d in keep])
            if not gf2_invertible(A):
                return False
    return True


def _mds_incremental(blocks: list[np.ndarray], w: int) -> bool:
    """MDS check for only the erasure patterns involving the LAST disk:
    for any pattern not touching it, that disk's identity rows make the
    system separable, so earlier verification still stands."""
    k = len(blocks)
    n = k + 2
    G = generator(blocks, w)
    i = k - 1
    for other in range(n):
        if other == i:
            continue
        keep = [d for d in range(n) if d not in (i, other)][:k]
        A = np.concatenate([G[d * w:(d + 1) * w] for d in keep])
        if not gf2_invertible(A):
            return False
    return True


# Pinned constructions: disk i -> (rotation offset a, extra bits).
# Found ONCE by _search_specs (deterministic) and embedded so plugin
# init is O(1); the MDS property is still re-verified at code build.
# Populated by tools/gen_bitmatrix_tables.py; runtime search covers any
# (k, w) not listed.
_PINNED: dict[tuple[int, int], list] = {
    (2, 7): [(0, []), (1, [(3, 0)])],
    (3, 7): [(0, []), (1, [(3, 0)]), (2, [(6, 2)])],
    (4, 7): [(0, []), (1, [(3, 0)]), (2, [(6, 2)]), (3, [(2, 1)])],
    (5, 7): [(0, []), (1, [(3, 0)]), (2, [(6, 2)]), (3, [(2, 1)]),
             (4, [(5, 5)])],
    (6, 7): [(0, []), (1, [(3, 0)]), (2, [(6, 3)]), (3, [(2, 1)]),
             (4, [(5, 4)]), (5, [(1, 2)])],
    (7, 7): [(0, []), (1, [(3, 0)]), (2, [(6, 4)]), (3, [(2, 1)]),
             (4, [(5, 5)]), (5, [(1, 2)]), (6, [(4, 6)])],
    (2, 5): [(0, []), (1, [(2, 0)])],
    (3, 5): [(0, []), (1, [(2, 0)]), (2, [(4, 2)])],
    (4, 5): [(0, []), (1, [(2, 0)]), (2, [(4, 2)]), (3, [(1, 1)])],
    (5, 5): [(0, []), (1, [(2, 0)]), (2, [(4, 3)]), (3, [(1, 1)]),
             (4, [(3, 4)])],
    (2, 8): [(0, []), (1, [(3, 0)])],
    (3, 8): [(0, []), (1, [(3, 0)]), (3, [(0, 1)])],
    (4, 8): [(0, []), (1, [(3, 0)]), (3, [(0, 1)]),
             (2, [(0, 0), (1, 1)])],
    (5, 8): [(0, []), (1, [(3, 0)]), (3, [(0, 1)]),
             (2, [(0, 0), (1, 1)]), (6, [(2, 2), (3, 7)])],
}

# w=8 constructions beyond k=5 need a structure our rotation+2-bit
# search family does not reach within budget (the published liber8tion
# tables go to k=8); callers get a clean error instead of a partial
# search burning minutes at plugin init.
MAX_K = {8: 5}


def _spec_block(w: int, a: int, extra: list) -> np.ndarray:
    m = _rot(w, a)
    for r, c in extra:
        m[r, c] ^= 1
    return m


def _search_specs(k: int, w: int) -> list:
    """Deterministic backtracking search for an MDS lowest-density
    construction: disk blocks R^a plus up to two extra bits (one
    suffices for prime w — the liberation codes; w=8 needs the wider
    family — liber8tion). Returns [(a, [(r, c), ...]), ...]."""
    # MDS-check budget: hard stop for the search (non-prime w needs the
    # wider 2-bit family and far more exploration)
    budget = [60000 if _is_prime(w) else 400000]

    def candidates(i: int):
        if _is_prime(w):
            # prime w: the liberation structure fixes disk i's rotation
            # at R^i; only the extra bit is searched
            offsets = [i % w]
        else:
            offsets = [i % w if i % w else 1] + \
                [a for a in range(1, w) if a != (i % w if i % w else 1)]
        y0 = (i * (w - 1) // 2) % w
        for nbits in (0, 1, 2):
            for a in offsets:
                if nbits == 0:
                    yield (a, [])
                elif nbits == 1:
                    for dr in range(w):
                        for c in range(w):
                            yield (a, [((y0 + dr) % w, c)])
                else:
                    cells = [(r, c) for r in range(w) for c in range(w)]
                    for p1 in range(len(cells)):
                        for p2 in range(p1 + 1, len(cells)):
                            yield (a, [cells[p1], cells[p2]])

    def search(specs: list, blocks: list):
        i = len(blocks)
        if i == k:
            return specs
        for a, extra in candidates(i):
            if budget[0] <= 0:
                return None
            budget[0] -= 1
            cand = _spec_block(w, a, extra)
            if _mds_incremental(blocks + [cand], w):
                out = search(specs + [(a, extra)], blocks + [cand])
                if out is not None:
                    return out
        return None

    specs = search([(0, [])], [np.eye(w, dtype=np.uint8)])
    if specs is None:
        raise ErasureCodeError(
            f"liberation family: no MDS construction found (k={k}, w={w})")
    return specs


def liberation_family_blocks(k: int, w: int) -> list[np.ndarray]:
    """Q-blocks R^a + extra bit(s): pinned table if available, else the
    deterministic search (lowest-density liberation property, Plank
    FAST'08; liber8tion for w=8)."""
    if k > w:
        raise ErasureCodeError(f"liberation family: k={k} > w={w}")
    if k > MAX_K.get(w, w):
        raise ErasureCodeError(
            f"liberation family: k={k} unsupported for w={w} "
            f"(max {MAX_K[w]} in this implementation)")
    specs = _PINNED.get((k, w)) or _search_specs(k, w)
    return [_spec_block(w, a, extra) for a, extra in specs]


@functools.lru_cache(maxsize=64)
def _blocks_cached(technique: str, k: int, w: int) -> tuple:
    if technique == "blaum_roth":
        return tuple(blaum_roth_blocks(k, w))
    return tuple(liberation_family_blocks(k, w))


def generator(blocks: list[np.ndarray], w: int) -> np.ndarray:
    """Full ((k+2)*w, k*w) generator: data identity rows, P = XOR of
    all data words, Q = the construction blocks."""
    k = len(blocks)
    G = np.zeros(((k + 2) * w, k * w), dtype=np.uint8)
    for d in range(k):
        G[d * w:(d + 1) * w, d * w:(d + 1) * w] = np.eye(w, dtype=np.uint8)
        G[k * w:(k + 1) * w, d * w:(d + 1) * w] = np.eye(w, dtype=np.uint8)
        G[(k + 1) * w:(k + 2) * w, d * w:(d + 1) * w] = blocks[d]
    return G


class RAID6BitCode:
    """One (k, w) bitmatrix RAID-6 code: packet-level encode/decode."""

    def __init__(self, technique: str, k: int, w: int):
        self.k, self.w = k, w
        self.blocks = [np.asarray(b) for b in
                       _blocks_cached(technique, k, w)]
        self.G = generator(self.blocks, w)
        if not _mds_raid6(self.blocks, w):
            raise ErasureCodeError(f"{technique} k={k} w={w}: not MDS")
        self._recovery_cache: dict[tuple, np.ndarray] = {}

    # chunk (S bytes) <-> packets (w, S/w)

    def _packets(self, chunks: dict[int, np.ndarray],
                 disks: list[int]) -> np.ndarray:
        size = next(len(chunks[d]) for d in disks)
        if size % self.w:
            raise ErasureCodeError(
                f"chunk size {size} not a multiple of w={self.w}")
        return np.concatenate(
            [np.asarray(chunks[d], dtype=np.uint8).reshape(self.w, -1)
             for d in disks])

    def encode(self, chunks: dict[int, np.ndarray]) -> None:
        """chunks[0..k-1] data in, chunks[k]=P chunks[k+1]=Q out."""
        data = self._packets(chunks, list(range(self.k)))
        coding = gf2_apply(self.G[self.k * self.w:], data)
        chunks[self.k][:] = coding[:self.w].reshape(-1)
        chunks[self.k + 1][:] = coding[self.w:].reshape(-1)

    def recovery_matrix(self, avail: tuple, want: tuple) -> np.ndarray:
        key = (avail, want)
        R = self._recovery_cache.get(key)
        if R is None:
            w = self.w
            A = np.concatenate([self.G[d * w:(d + 1) * w] for d in avail])
            inv = gf2_solve(A, np.eye(self.k * w, dtype=np.uint8))
            W = np.concatenate([self.G[d * w:(d + 1) * w] for d in want])
            R = (W.astype(np.int64) @ inv.astype(np.int64) % 2) \
                .astype(np.uint8)
            self._recovery_cache[key] = R
        return R

    def decode(self, want: list[int], chunks: dict[int, np.ndarray],
               available: set[int]) -> None:
        avail = tuple(sorted(available))[:self.k]
        if len(avail) < self.k:
            raise ErasureCodeError(
                f"cannot decode {want}: only {len(avail)} disks available")
        R = self.recovery_matrix(avail, tuple(sorted(want)))
        src = self._packets(chunks, list(avail))
        rec = gf2_apply(R, src)
        for row, d in enumerate(sorted(want)):
            chunks[d][:] = rec[row * self.w:(row + 1) * self.w].reshape(-1)
