"""S3-subset HTTP gateway.

Re-creation of the reference RGW request path shape
(src/rgw/rgw_asio_frontend.cc HTTP frontend -> rgw_process.cc:265
process_request -> RGWOp handlers -> RADOS store driver):

  * buckets:   PUT /bucket        create   (bucket index object with an
                                           omap entry per object, like
                                           cls_rgw's bucket index)
               GET /bucket        list objects (XML ListBucketResult)
               DELETE /bucket     remove (must be empty)
               GET /              list buckets
  * objects:   PUT /bucket/key    write (body = payload)
               GET /bucket/key    read (+ ETag = crc32c hex)
               HEAD /bucket/key   stat
               DELETE /bucket/key remove

Layout in RADOS: an index pool (+ optionally a separate, typically
erasure-coded, DATA pool for object/part blobs); bucket index object
`.bucket.<name>` whose omap maps object key -> JSON {size, etag};
object data in `<bucket>/<key>`. Multi-op semantics match S3's
read-after-write for new objects.

Multipart uploads (src/rgw/rgw_op.cc RGWInitMultipart/
RGWPutObj part path/RGWCompleteMultipart): POST /b/k?uploads initiates
and returns an UploadId; PUT /b/k?partNumber=N&uploadId=U stores parts
as `.mp.<id>.<n>` objects; POST /b/k?uploadId=U concatenates the parts
in part-number order into the final object and deletes them; DELETE
with uploadId aborts and reclaims parts.

Idiomatic divergences: no auth sigv4 (cephx-lite guards the RADOS
plane; HTTP is trusted-localhost like a behind-proxy deployment), XML
only where S3 clients require it.
"""
from __future__ import annotations

import asyncio
import json
import secrets
import time
from urllib.parse import parse_qs, unquote, urlsplit
from xml.sax.saxutils import escape

from ceph_tpu.mgr.mgr_client import MgrClient
from ceph_tpu.rados.client import IoCtx, ObjectNotFound
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.perf_counters import TYPE_AVG, PerfCountersCollection


def _index_oid(bucket: str) -> str:
    return f".bucket.{bucket}"


def _data_oid(bucket: str, key: str) -> str:
    return f"{bucket}/{key}"


class RGWGateway:
    """HTTP/1.0 S3-subset frontend bound to one RADOS pool; object
    DATA may live in a separate (typically erasure-coded) pool while
    bucket indexes stay in the replicated index pool — the reference's
    placement-target data_pool split (rgw zone placement pools)."""

    def __init__(self, ioctx: IoCtx, host: str = "127.0.0.1",
                 port: int = 0, data_ioctx: IoCtx | None = None,
                 name: str = "rgw.0"):
        self.io = ioctx
        self.data_io = data_ioctx if data_ioctx is not None else ioctx
        self.host, self.port = host, port
        self.name = name
        self._server: asyncio.Server | None = None
        self.addr: tuple[str, int] | None = None
        # per-daemon perf counters (src/rgw/rgw_perf_counters.cc: req,
        # op breakdown, byte counters), shipped to the mgr over the
        # backing RADOS client's messenger
        coll = PerfCountersCollection.instance()
        coll.remove(name)               # a restarted gateway re-registers
        self.perf = coll.create(name)
        self.perf.add("req", description="http requests processed")
        self.perf.add("op_get", description="object GET/HEAD ops")
        self.perf.add("op_put", description="object PUT ops")
        self.perf.add("op_del", description="object/bucket DELETE ops")
        self.perf.add("bytes_received",
                      description="request body bytes received")
        self.perf.add("bytes_sent", description="response bytes sent")
        self.perf.add("req_latency", type=TYPE_AVG,
                      description="request latency (seconds)")
        self.mgr_client = MgrClient(
            ioctx.client.messenger, name, "rgw",
            resolve=lambda: (ioctx.client.monc.mgrmap
                             or {}).get("active_addr"),
            status_cb=lambda: {
                "index_pool": self.io.pool_name,
                "data_pool": self.data_io.pool_name,
                "addr": list(self.addr) if self.addr else None})

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        self.io.client.monc.subscribe("mgrmap", 1)
        self.mgr_client.start()
        dout("rgw", 1, f"rgw-lite on {self.addr}")
        return self.addr

    async def stop(self) -> None:
        await self.mgr_client.stop()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- request plumbing ----------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), 30.0)
            parts = request.decode(errors="replace").split()
            if len(parts) < 2:
                return
            url = urlsplit(parts[1])
            method, path = parts[0].upper(), unquote(url.path)
            query = {k: v[0] for k, v in parse_qs(
                url.query, keep_blank_values=True).items()}
            headers_in: dict[str, str] = {}
            while True:
                line = await asyncio.wait_for(reader.readline(), 30.0)
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode(errors="replace").partition(":")
                headers_in[name.strip().lower()] = value.strip()
            try:
                length = int(headers_in.get("content-length", 0))
            except ValueError:
                length = -1
            if length < 0:
                code, headers, out = 400, {}, b"InvalidArgument"
                body = b""
            else:
                body = await reader.readexactly(length) if length else b""
                code, headers, out = await self._process_metered(
                    method, path, body, query, headers_in)
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                OSError):
            writer.close()
            return
        except Exception as e:
            dout("rgw", 1, f"request failed: {type(e).__name__} {e}")
            code, headers, out = 500, {}, b"InternalError"
        try:
            hdr = "".join(f"{k}: {v}\r\n" for k, v in headers.items())
            if "Content-Length" not in headers:
                hdr += f"Content-Length: {len(out)}\r\n"
            writer.write(
                f"HTTP/1.0 {code} {_REASON.get(code, '')}\r\n{hdr}"
                f"\r\n".encode() + out)
            await writer.drain()
        except OSError:
            pass
        finally:
            writer.close()

    async def _process_metered(self, method: str, path: str, body: bytes,
                               query: dict | None = None,
                               headers_in: dict | None = None
                               ) -> tuple[int, dict, bytes]:
        """_process with per-request perf accounting (request/op/byte
        counters + latency), so the gateway shows up in the aggregated
        cluster metrics like every other daemon."""
        t0 = time.monotonic()
        self.perf.inc("req")
        if body:
            self.perf.inc("bytes_received", len(body))
        try:
            code, headers, out = await self._process(
                method, path, body, query, headers_in)
        finally:
            self.perf.avg_add("req_latency", time.monotonic() - t0)
        if method in ("GET", "HEAD"):
            self.perf.inc("op_get")
        elif method == "PUT":
            self.perf.inc("op_put")
        elif method == "DELETE":
            self.perf.inc("op_del")
        if out:
            self.perf.inc("bytes_sent", len(out))
        return code, headers, out

    # -- S3 semantics --------------------------------------------------------

    async def _process(self, method: str, path: str, body: bytes,
                       query: dict | None = None,
                       headers_in: dict | None = None
                       ) -> tuple[int, dict, bytes]:
        query = query or {}
        headers_in = headers_in or {}
        parts = [p for p in path.split("/") if p]
        if not parts:
            if method == "GET":
                return await self._list_buckets()
            return 405, {}, b"MethodNotAllowed"
        bucket, key = parts[0], "/".join(parts[1:])
        if not key:
            if method == "PUT":
                return await self._create_bucket(bucket)
            if method == "GET":
                return await self._list_objects(bucket, query)
            if method == "DELETE":
                return await self._delete_bucket(bucket)
            return 405, {}, b"MethodNotAllowed"
        if method == "POST" and "uploads" in query:
            return await self._initiate_multipart(bucket, key)
        if method == "POST" and "uploadId" in query:
            return await self._complete_multipart(bucket, key,
                                                  query["uploadId"])
        if method == "PUT" and "uploadId" in query:
            return await self._put_part(bucket, key, query, body)
        if method == "DELETE" and "uploadId" in query:
            return await self._abort_multipart(bucket, key,
                                               query["uploadId"])
        if method == "PUT":
            return await self._put_object(bucket, key, body)
        if method == "GET":
            return await self._get_object(bucket, key,
                                          headers_in.get("range"))
        if method == "HEAD":
            return await self._head_object(bucket, key)
        if method == "DELETE":
            return await self._delete_object(bucket, key)
        return 405, {}, b"MethodNotAllowed"

    async def _bucket_exists(self, bucket: str) -> bool:
        try:
            await self.io.stat(_index_oid(bucket))
            return True
        except ObjectNotFound:
            return False

    async def _list_buckets(self) -> tuple[int, dict, bytes]:
        names = sorted(o[len(".bucket."):]
                       for o in await self.io.list_objects()
                       if o.startswith(".bucket."))
        inner = "".join(f"<Bucket><Name>{escape(n)}</Name></Bucket>"
                        for n in names)
        xml = (f"<ListAllMyBucketsResult><Buckets>{inner}</Buckets>"
               f"</ListAllMyBucketsResult>")
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    async def _create_bucket(self, bucket: str) -> tuple[int, dict, bytes]:
        if not await self._bucket_exists(bucket):
            # a re-PUT of an existing bucket must NOT touch the index:
            # write_full here would wipe its omap (S3 bucket PUT is
            # idempotent)
            await self.io.write_full(_index_oid(bucket), b"")
        return 200, {}, b""

    async def _delete_bucket(self, bucket: str) -> tuple[int, dict, bytes]:
        if not await self._bucket_exists(bucket):
            return 404, {}, b"NoSuchBucket"
        if await self.io.omap_get(_index_oid(bucket)):
            return 409, {}, b"BucketNotEmpty"
        await self.io.remove(_index_oid(bucket))
        return 204, {}, b""

    async def _list_objects(self, bucket: str,
                            query: dict | None = None
                            ) -> tuple[int, dict, bytes]:
        """ListObjects with the prefix/delimiter folding S3 clients use
        for directory-style browsing (RGWListBucket)."""
        if not await self._bucket_exists(bucket):
            return 404, {}, b"NoSuchBucket"
        query = query or {}
        prefix = query.get("prefix", "")
        delim = query.get("delimiter", "")
        index = await self.io.omap_get(_index_oid(bucket))
        items = []
        common: set[str] = set()
        for k in sorted(index):
            if not k.startswith(prefix):
                continue
            if delim:
                rest = k[len(prefix):]
                if delim in rest:
                    common.add(prefix + rest.split(delim, 1)[0] + delim)
                    continue
            meta = json.loads(index[k])
            items.append(f"<Contents><Key>{escape(k)}</Key>"
                         f"<Size>{meta['size']}</Size>"
                         f"<ETag>&quot;{meta['etag']}&quot;</ETag>"
                         f"</Contents>")
        prefixes = "".join(
            f"<CommonPrefixes><Prefix>{escape(p_)}</Prefix>"
            f"</CommonPrefixes>" for p_ in sorted(common))
        xml = (f"<ListBucketResult><Name>{escape(bucket)}</Name>"
               f"<Prefix>{escape(prefix)}</Prefix>"
               f"{''.join(items)}{prefixes}</ListBucketResult>")
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    async def _put_object(self, bucket: str, key: str,
                          body: bytes) -> tuple[int, dict, bytes]:
        if not await self._bucket_exists(bucket):
            return 404, {}, b"NoSuchBucket"
        from ceph_tpu.native import ec_native
        etag = f"{ec_native.crc32c(body):08x}"
        await self.data_io.write_full(_data_oid(bucket, key), body)
        # bucket index update AFTER the data lands (the reference's
        # cls_rgw index transaction orders prepare/complete likewise)
        await self.io.omap_set(_index_oid(bucket), {
            key: json.dumps({"size": len(body), "etag": etag}).encode()})
        return 200, {"ETag": f'"{etag}"'}, b""

    async def _get_object(self, bucket: str, key: str,
                          range_hdr: str | None = None
                          ) -> tuple[int, dict, bytes]:
        """GET, honoring `Range: bytes=a-b` with a 206 + Content-Range
        (S3 ranged GET; drives the OSD's ranged read path)."""
        oid = _data_oid(bucket, key)
        rng = None
        if range_hdr and range_hdr.startswith("bytes="):
            spec = range_hdr[len("bytes="):]
            start_s, _, end_s = spec.partition("-")
            if start_s.isdigit():
                rng = (int(start_s),
                       int(end_s) if end_s.isdigit() else None)
            elif end_s.isdigit():
                rng = (None, int(end_s))      # suffix: last N bytes
        try:
            if rng is not None:
                st = await self.data_io.stat(oid)
                total = st["size"]
                start, end = rng
                if start is None:
                    # bytes=-N (footer probes): the last N bytes
                    if end == 0:
                        return 416, {"Content-Range": f"bytes */{total}"
                                     }, b"InvalidRange"
                    start, end = max(0, total - end), total - 1
                else:
                    end = total - 1 if end is None else min(end, total - 1)
                if start >= total or start > end:
                    return 416, {"Content-Range": f"bytes */{total}"
                                 }, b"InvalidRange"
                data = await self.data_io.read(oid, offset=start,
                                          length=end - start + 1)
                return 206, {
                    "Content-Range": f"bytes {start}-{end}/{total}",
                    "Content-Type": "application/octet-stream"}, data
            data = await self.data_io.read(oid)
        except ObjectNotFound:
            return 404, {}, b"NoSuchKey"
        from ceph_tpu.native import ec_native
        return 200, {"ETag": f'"{ec_native.crc32c(data):08x}"',
                     "Content-Type": "application/octet-stream"}, data

    async def _head_object(self, bucket: str,
                           key: str) -> tuple[int, dict, bytes]:
        try:
            st = await self.data_io.stat(_data_oid(bucket, key))
        except ObjectNotFound:
            return 404, {}, b""
        # HEAD: the real object size IS the Content-Length (no body)
        return 200, {"Content-Length": str(st["size"])}, b""

    async def _delete_object(self, bucket: str,
                             key: str) -> tuple[int, dict, bytes]:
        try:
            await self.data_io.remove(_data_oid(bucket, key))
        except ObjectNotFound:
            return 404, {}, b"NoSuchKey"
        await self.io.omap_rm(_index_oid(bucket), [key])
        return 204, {}, b""


    # -- multipart (RGWInitMultipart / part put / RGWCompleteMultipart) ------

    @staticmethod
    def _part_oid(upload_id: str, n: int) -> str:
        return f".mp.{upload_id}.{n:05d}"

    @staticmethod
    def _upload_meta_oid(upload_id: str) -> str:
        return f".mp.{upload_id}.meta"

    async def _initiate_multipart(self, bucket: str,
                                  key: str) -> tuple[int, dict, bytes]:
        if not await self._bucket_exists(bucket):
            return 404, {}, b"NoSuchBucket"
        upload_id = secrets.token_hex(12)
        await self.io.write_full(
            self._upload_meta_oid(upload_id),
            json.dumps({"bucket": bucket, "key": key}).encode())
        xml = (f"<InitiateMultipartUploadResult>"
               f"<Bucket>{escape(bucket)}</Bucket>"
               f"<Key>{escape(key)}</Key>"
               f"<UploadId>{upload_id}</UploadId>"
               f"</InitiateMultipartUploadResult>")
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    async def _load_upload(self, upload_id: str) -> dict | None:
        try:
            return json.loads(
                await self.io.read(self._upload_meta_oid(upload_id)))
        except (ObjectNotFound, ValueError):
            return None

    async def _put_part(self, bucket: str, key: str, query: dict,
                        body: bytes) -> tuple[int, dict, bytes]:
        upload_id = query["uploadId"]
        meta = await self._load_upload(upload_id)
        if meta is None or (meta["bucket"], meta["key"]) != (bucket, key):
            return 404, {}, b"NoSuchUpload"
        try:
            n = int(query.get("partNumber", "0"))
        except ValueError:
            n = 0
        if not 1 <= n <= 10000:
            return 400, {}, b"InvalidPartNumber"
        from ceph_tpu.native import ec_native
        etag = f"{ec_native.crc32c(body):08x}"
        await self.data_io.write_full(self._part_oid(upload_id, n), body)
        return 200, {"ETag": f'"{etag}"'}, b""

    async def _upload_parts(self, upload_id: str) -> list[str]:
        prefix = f".mp.{upload_id}."
        return sorted(o for o in await self.data_io.list_objects()
                      if o.startswith(prefix)
                      and not o.endswith(".meta"))

    async def _complete_multipart(self, bucket: str, key: str,
                                  upload_id: str
                                  ) -> tuple[int, dict, bytes]:
        meta = await self._load_upload(upload_id)
        if meta is None or (meta["bucket"], meta["key"]) != (bucket, key):
            return 404, {}, b"NoSuchUpload"
        if not await self._bucket_exists(bucket):
            # the bucket died while the upload was in flight: completing
            # must not resurrect it through the index omap_set
            return 404, {}, b"NoSuchBucket"
        parts = await self._upload_parts(upload_id)
        if not parts:
            return 400, {}, b"InvalidRequest: no parts"
        # concatenate in part order via ranged appends: the final
        # object replaces any previous content. The rolling crc starts
        # at crc32c's default seed so the multipart ETag prefix matches
        # what GET recomputes over the same bytes
        from ceph_tpu.native import ec_native
        total = 0
        crc = 0xFFFFFFFF
        dst = _data_oid(bucket, key)
        for i, oid in enumerate(parts):
            blob = await self.data_io.read(oid)
            if i == 0:
                await self.data_io.write_full(dst, blob)
            else:
                await self.data_io.write(dst, blob, offset=total)
            crc = ec_native.crc32c(blob, crc)
            total += len(blob)
        etag = f"{crc:08x}-{len(parts)}"
        await self.io.omap_set(_index_oid(bucket), {
            key: json.dumps({"size": total, "etag": etag}).encode()})
        for oid in parts:
            try:
                await self.data_io.remove(oid)
            except ObjectNotFound:
                pass
        await self.io.remove(self._upload_meta_oid(upload_id))
        xml = (f"<CompleteMultipartUploadResult>"
               f"<Bucket>{escape(bucket)}</Bucket>"
               f"<Key>{escape(key)}</Key>"
               f"<ETag>&quot;{etag}&quot;</ETag>"
               f"</CompleteMultipartUploadResult>")
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    async def _abort_multipart(self, bucket: str, key: str,
                               upload_id: str) -> tuple[int, dict, bytes]:
        meta = await self._load_upload(upload_id)
        if meta is None or (meta["bucket"], meta["key"]) != (bucket, key):
            return 404, {}, b"NoSuchUpload"
        for oid in await self._upload_parts(upload_id):
            try:
                await self.data_io.remove(oid)
            except ObjectNotFound:
                pass
        await self.io.remove(self._upload_meta_oid(upload_id))
        return 204, {}, b""


_REASON = {200: "OK", 204: "No Content", 206: "Partial Content",
           400: "Bad Request", 404: "Not Found",
           405: "Method Not Allowed", 409: "Conflict",
           416: "Range Not Satisfiable", 500: "Internal Server Error"}
