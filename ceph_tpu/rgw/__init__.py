"""RGW-lite: S3-style HTTP object gateway over the librados subset.

The thin vertical slice of the reference gateway (src/rgw/: beast/asio
HTTP frontend rgw_asio_frontend.cc, process_request rgw_process.cc:265,
RADOS store driver src/rgw/driver/rados/): buckets and objects over
RADOS pools, with the bucket index kept in omap like the reference's
bucket index objects (cls_rgw).
"""
from ceph_tpu.rgw.gateway import RGWGateway

__all__ = ["RGWGateway"]
