"""LSMStore: persistent log-structured KeyValueDB (the RocksDBStore role).

Re-creation of the reference's RocksDBStore essentials
(src/kv/RocksDBStore.cc over the vendored src/rocksdb/) as a compact
log-structured merge engine:

  * every batch is appended to a crc-framed WAL and fsync'd before it
    is acknowledged (rocksdb WriteBatch + WAL semantics);
  * the memtable absorbs writes; when it exceeds the flush threshold it
    is written out as an immutable sorted-run file (SSTable role) and
    the WAL is truncated;
  * lookups go memtable -> runs newest-to-oldest; deletes are
    tombstones that shadow older runs;
  * when the run count exceeds the compaction trigger, runs are merged
    into one and tombstones are dropped (full compaction — the
    reference's leveled compaction collapsed to one level);
  * the MANIFEST (tmp+rename+fsync) names the live runs, so a crash
    mid-flush/mid-compaction falls back to the previous run set plus
    WAL replay.

Idiomatic divergences: runs are loaded into memory at open (block
cache = whole-file residency — state here is control-plane-sized);
values are latin1-mapped JSON rather than varint-framed blocks.
"""
from __future__ import annotations

import json
import os
import struct

from ceph_tpu.kv.keyvaluedb import KeyValueDB, KVTransaction
from ceph_tpu.utils.crash import SimulatedCrash  # noqa: F401 (re-export)

_TOMB = None          # tombstone marker inside tables


def _crc32c(data: bytes) -> int:
    from ceph_tpu.native import ec_native
    return ec_native.crc32c(data)


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class LSMStore(KeyValueDB):

    FLUSH_BYTES = 4 * 1024 * 1024     # memtable flush threshold
    COMPACT_RUNS = 6                  # full-compaction trigger

    def __init__(self, path: str, flush_bytes: int | None = None):
        self.path = path
        if flush_bytes is not None:
            self.FLUSH_BYTES = flush_bytes
        # "prefix\x00key" -> bytes | None(tombstone)
        self._memtable: dict[str, bytes | None] = {}
        self._mem_bytes = 0
        self._runs: list[dict[str, bytes | None]] = []   # newest first
        self._run_files: list[str] = []
        self._wal = None
        self._next_file = 1
        self.fail_after_wal = False     # SimulatedCrash hook

    # -- lifecycle -----------------------------------------------------------

    def open(self) -> None:
        os.makedirs(os.path.join(self.path, "sst"), exist_ok=True)
        manifest = os.path.join(self.path, "MANIFEST")
        if os.path.exists(manifest):
            with open(manifest) as f:
                m = json.load(f)
            self._run_files = list(m["runs"])
            self._next_file = m["next"]
            self._runs = [self._load_run(fn) for fn in self._run_files]
        self._replay_wal()
        self._wal = open(os.path.join(self.path, "wal.log"), "ab")

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    # -- WAL -----------------------------------------------------------------

    def _wal_path(self) -> str:
        return os.path.join(self.path, "wal.log")

    def _replay_wal(self) -> None:
        path = self._wal_path()
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            blob = f.read()
        off = 0
        while off + 8 <= len(blob):
            length, crc = struct.unpack_from("<II", blob, off)
            rec = blob[off + 8:off + 8 + length]
            if len(rec) < length or _crc32c(rec) != crc:
                break                       # torn tail: stop replay here
            for op in json.loads(rec):
                if op[0] == "set":
                    self._mem_set(f"{op[1]}\x00{op[2]}",
                                  op[3].encode("latin1"))
                elif op[0] == "rm":
                    self._mem_set(f"{op[1]}\x00{op[2]}", _TOMB)
                elif op[0] == "rmprefix":
                    self._rm_prefix_mem(op[1])
            off += 8 + length

    # -- batch submit --------------------------------------------------------

    def submit_transaction(self, txn: KVTransaction,
                           sync: bool = True) -> None:
        if not txn.ops:
            return
        rec = json.dumps(
            [(o[0], o[1], *([] if len(o) < 3 else [o[2]]),
              *([] if len(o) < 4 else [o[3].decode("latin1")]))
             for o in txn.ops]).encode()
        self._wal.write(struct.pack("<II", len(rec), _crc32c(rec)) + rec)
        self._wal.flush()
        if sync:
            os.fsync(self._wal.fileno())
        if self.fail_after_wal:
            raise SimulatedCrash("crash between WAL append and apply")
        for op in txn.ops:
            if op[0] == "set":
                self._mem_set(f"{op[1]}\x00{op[2]}", op[3])
            elif op[0] == "rm":
                self._mem_set(f"{op[1]}\x00{op[2]}", _TOMB)
            elif op[0] == "rmprefix":
                self._rm_prefix_mem(op[1])
        if self._mem_bytes >= self.FLUSH_BYTES:
            self._flush()

    def _mem_set(self, fq: str, value: bytes | None) -> None:
        old = self._memtable.get(fq)
        self._memtable[fq] = value
        self._mem_bytes += len(fq) + (len(value) if value else 0) \
            - (len(old) if old else 0)

    def _rm_prefix_mem(self, prefix: str) -> None:
        """Tombstone every key under `prefix` visible anywhere."""
        p = prefix + "\x00"
        names = {k for k in self._memtable if k.startswith(p)}
        for run in self._runs:
            names.update(k for k in run if k.startswith(p))
        for k in names:
            self._memtable[k] = _TOMB

    # -- flush / compaction --------------------------------------------------

    def _run_path(self, name: str) -> str:
        return os.path.join(self.path, "sst", name)

    def _load_run(self, name: str) -> dict[str, bytes | None]:
        with open(self._run_path(name), "rb") as f:
            blob = f.read()
        crc, = struct.unpack_from("<I", blob, 0)
        body = blob[4:]
        if _crc32c(body) != crc:
            raise IOError(f"sst {name}: crc mismatch")
        raw = json.loads(body)
        return {k: (v.encode("latin1") if v is not None else _TOMB)
                for k, v in raw.items()}

    def _write_run(self, table: dict[str, bytes | None]) -> str:
        name = f"{self._next_file:06d}.sst"
        self._next_file += 1
        body = json.dumps(
            {k: (v.decode("latin1") if v is not None else None)
             for k, v in sorted(table.items())}).encode()
        tmp = self._run_path(name) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(struct.pack("<I", _crc32c(body)) + body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._run_path(name))
        return name

    def _commit_manifest(self) -> None:
        tmp = os.path.join(self.path, "MANIFEST.tmp")
        with open(tmp, "w") as f:
            json.dump({"runs": self._run_files, "next": self._next_file},
                      f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, "MANIFEST"))
        _fsync_dir(self.path)

    def _flush(self) -> None:
        if not self._memtable:
            return
        name = self._write_run(self._memtable)
        self._run_files.insert(0, name)
        self._runs.insert(0, dict(self._memtable))
        self._commit_manifest()
        self._memtable.clear()
        self._mem_bytes = 0
        # WAL content is now durable in the run: start a fresh log
        self._wal.close()
        os.truncate(self._wal_path(), 0)
        self._wal = open(self._wal_path(), "ab")
        if len(self._run_files) > self.COMPACT_RUNS:
            self._compact()

    def _compact(self) -> None:
        """Merge every run into one; tombstones drop out (nothing older
        remains to shadow)."""
        merged: dict[str, bytes | None] = {}
        for run in reversed(self._runs):         # oldest first
            merged.update(run)
        merged = {k: v for k, v in merged.items() if v is not None}
        name = self._write_run(merged)
        old_files = self._run_files
        self._run_files = [name]
        self._runs = [merged]
        self._commit_manifest()
        for fn in old_files:
            try:
                os.unlink(self._run_path(fn))
            except OSError:
                pass

    def compact(self) -> None:
        """Explicit full compaction (rocksdb CompactRange)."""
        self._flush()
        if len(self._run_files) > 1:
            self._compact()

    # -- reads ---------------------------------------------------------------

    def get(self, prefix: str, key: str) -> bytes | None:
        fq = f"{prefix}\x00{key}"
        if fq in self._memtable:
            return self._memtable[fq]
        for run in self._runs:
            if fq in run:
                return run[fq]
        return None

    def iterate(self, prefix: str, start: str = ""):
        p = prefix + "\x00"
        view: dict[str, bytes | None] = {}
        for run in reversed(self._runs):
            for k, v in run.items():
                if k.startswith(p):
                    view[k] = v
        for k, v in self._memtable.items():
            if k.startswith(p):
                view[k] = v
        for k in sorted(view):
            key = k[len(p):]
            if view[k] is not None and key >= start:
                yield key, view[k]
