"""KeyValueDB: the storage engine contract under BlueStore/MonStore.

Re-creation of the reference's KeyValueDB abstraction
(src/kv/KeyValueDB.h): prefixed keyspaces (the column-family role),
atomic write batches (`KVTransaction` ~ KeyValueDB::Transaction),
point gets and ordered prefix iteration. Implementations: `MemDB`
(src/kv/MemDB.cc role — tests/ephemeral) and `LSMStore` in lsm.py
(the RocksDBStore role).
"""
from __future__ import annotations

from typing import Iterator


class KVTransaction:
    """Atomic batch of set/rmkey ops (KeyValueDB::TransactionImpl)."""

    def __init__(self):
        # (op, prefix, key, value|None); replayed in order
        self.ops: list[tuple] = []

    def set(self, prefix: str, key: str, value: bytes) -> "KVTransaction":
        self.ops.append(("set", prefix, key, bytes(value)))
        return self

    def rmkey(self, prefix: str, key: str) -> "KVTransaction":
        self.ops.append(("rm", prefix, key))
        return self

    def rmkeys_by_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append(("rmprefix", prefix))
        return self

    def __len__(self) -> int:
        return len(self.ops)


class KeyValueDB:
    """Abstract engine: prefixes ~ column families (KeyValueDB.h)."""

    def open(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def transaction(self) -> KVTransaction:
        return KVTransaction()

    def submit_transaction(self, txn: KVTransaction,
                           sync: bool = True) -> None:
        raise NotImplementedError

    def get(self, prefix: str, key: str) -> bytes | None:
        raise NotImplementedError

    def iterate(self, prefix: str,
                start: str = "") -> Iterator[tuple[str, bytes]]:
        """Ordered (key, value) pairs with key >= start, one prefix."""
        raise NotImplementedError


class MemDB(KeyValueDB):
    """In-memory engine (the reference's MemDB test backend)."""

    def __init__(self):
        self._data: dict[str, dict[str, bytes]] = {}

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    def submit_transaction(self, txn: KVTransaction,
                           sync: bool = True) -> None:
        for op in txn.ops:
            if op[0] == "set":
                self._data.setdefault(op[1], {})[op[2]] = op[3]
            elif op[0] == "rm":
                self._data.get(op[1], {}).pop(op[2], None)
            elif op[0] == "rmprefix":
                self._data.pop(op[1], None)

    def get(self, prefix: str, key: str) -> bytes | None:
        return self._data.get(prefix, {}).get(key)

    def iterate(self, prefix: str,
                start: str = "") -> Iterator[tuple[str, bytes]]:
        table = self._data.get(prefix, {})
        for k in sorted(table):
            if k >= start:
                yield k, table[k]
