"""KeyValueDB layer (src/kv/): engine contract + MemDB + LSMStore."""
from ceph_tpu.kv.keyvaluedb import KeyValueDB, KVTransaction, MemDB
from ceph_tpu.kv.lsm import LSMStore, SimulatedCrash as KVSimulatedCrash

__all__ = ["KeyValueDB", "KVTransaction", "MemDB", "LSMStore",
           "KVSimulatedCrash"]
