"""Stripe layout + stripe codec driver — the ECUtil equivalent.

StripeInfo reproduces the offset math of the reference's
`ECUtil::stripe_info_t` (src/osd/ECUtil.h:27-119): an object's logical byte
stream is striped over k data shards, stripe_width = k * chunk_size;
logical offsets map to per-shard chunk offsets.

encode/decode are the reference's `ECUtil::encode`/`decode`
(src/osd/ECUtil.cc:21-170) — the site SURVEY §2.2 names as "the batching
site for TPU dispatch". The reference loops stripe-by-stripe calling the
plugin per stripe; here, when the plugin exposes the batched stripe APIs
(`encode_stripes`/`decode_stripes`, the `tpu` plugin), ALL stripes go to
the device in one dispatch and come back as per-shard contiguous buffers.
Plugins without the batched API fall back to the reference's per-stripe
loop, so any registered plugin works.

HashInfo mirrors `ECUtil::HashInfo` (src/osd/ECUtil.h:141-199): cumulative
per-shard crc32c maintained across appends, stored in object metadata and
checked on reads/deep-scrub.
"""
from __future__ import annotations

import time
from typing import Iterable, Mapping

import numpy as np

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.utils import copytrack, sanitizer, tracer


class StripeInfo:
    """Logical <-> chunk offset arithmetic (ECUtil.h:27-119).

    Constructed from (k, stripe_width); stripe_width must be a multiple
    of k and of the plugin's alignment so chunk_size divides evenly.
    """

    def __init__(self, data_chunks: int, stripe_width: int):
        if stripe_width % data_chunks:
            raise ValueError(
                f"stripe_width {stripe_width} not divisible by k={data_chunks}")
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // data_chunks
        self.k = data_chunks

    # -- predicates --
    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def offset_length_is_same_stripe(self, off: int, length: int) -> bool:
        if length == 0:
            return True
        return off // self.stripe_width == (off + length - 1) // self.stripe_width

    # -- logical -> chunk --
    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.stripe_width

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        if offset % self.stripe_width:
            raise ValueError(f"offset {offset} not stripe aligned")
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        if offset % self.chunk_size:
            raise ValueError(f"chunk offset {offset} not chunk aligned")
        return (offset // self.chunk_size) * self.stripe_width

    def chunk_aligned_offset_len_to_chunk(self, off: int, length: int) -> tuple[int, int]:
        """(rounds offset down, length up) — ECUtil.cc:14."""
        if (off % self.stripe_width) % self.chunk_size:
            raise ValueError("offset residue not chunk aligned")
        if (length % self.stripe_width) % self.chunk_size:
            raise ValueError("length residue not chunk aligned")
        return ((off // self.stripe_width) * self.chunk_size,
                -(-length // self.stripe_width) * self.chunk_size)

    # -- range expansion --
    def offset_len_to_stripe_bounds(self, off: int, length: int) -> tuple[int, int]:
        start = self.logical_to_prev_stripe_offset(off)
        length = self.logical_to_next_stripe_offset((off - start) + length)
        return start, length

    def offset_len_to_chunk_bounds(self, off: int, length: int) -> tuple[int, int]:
        start = off - (off % self.chunk_size)
        tmp = (off - start) + length
        return start, -(-tmp // self.chunk_size) * self.chunk_size

    def offset_length_to_data_chunk_indices(self, off: int, length: int) -> tuple[int, int]:
        """[first, last) global data-chunk indices touched by the range."""
        return (off // self.chunk_size,
                (self.chunk_size - 1 + off + length) // self.chunk_size)


# ---------------------------------------------------------------------------
# Stripe codec driver
# ---------------------------------------------------------------------------

def _encode_frame(sinfo: StripeInfo, ec_impl, data, want):
    """Shared validation/framing for encode(): returns
    (stripes (S,k,C) | None, want set, k, n_chunks, mapping, batched)."""
    # numpy boundary: a sanitizer-guarded rx view unwraps HERE (with
    # its use-after-recycle check) — np.frombuffer can't take the proxy
    data = sanitizer.unwrap(data)
    if isinstance(data, (bytes, bytearray, memoryview)):
        # np.frombuffer windows the message bytes — no copy
        buf = np.frombuffer(data, dtype=np.uint8)
        copytrack.referenced("frame_to_buffer", buf.size)
    else:
        t0 = time.perf_counter()
        buf = np.ascontiguousarray(data, dtype=np.uint8).reshape(-1)
        if np.shares_memory(buf, data):
            copytrack.referenced("frame_to_buffer", buf.size)
        else:
            copytrack.copied("frame_to_buffer", buf.size,
                             time.perf_counter() - t0)
    if buf.size % sinfo.stripe_width:
        raise ErasureCodeError(
            f"input size {buf.size} not a multiple of stripe width "
            f"{sinfo.stripe_width}")
    k = ec_impl.get_data_chunk_count()
    n_chunks = ec_impl.get_chunk_count()
    if k != sinfo.k:
        raise ErasureCodeError(f"plugin k={k} != stripe k={sinfo.k}")
    want = set(want) if want is not None else set(range(n_chunks))
    if any(not 0 <= w < n_chunks for w in want):
        raise ErasureCodeError(f"want ids {sorted(want)} out of range "
                               f"0..{n_chunks - 1}")
    n_stripes = buf.size // sinfo.stripe_width
    mapping = ec_impl.get_chunk_mapping()
    batched = callable(getattr(ec_impl, "encode_stripes", None)) \
        and not mapping
    stripes = None if n_stripes == 0 else \
        buf.reshape(n_stripes, k, sinfo.chunk_size)
    return stripes, want, k, n_chunks, mapping, batched


def _encode_assemble(stripes: np.ndarray, parity: np.ndarray, k: int,
                     want, sp=None) -> dict[int, memoryview]:
    """Shard planes -> per-shard reply buffers, AT MOST one copy per
    byte — and zero for contiguous planes.

    A shard's chunks-per-stripe plane `stripes[:, i, :]` (or
    `parity[:, i-k, :]`) is C-contiguous whenever the write is a single
    stripe (S == 1, every one-stripe client op) or the axis being
    indexed has size 1 (m == 1 parity) — in that case the plane IS the
    reply buffer and a memoryview over it goes downstream as-is
    (message frames, object-store writes and crc all take buffer
    objects), metered referenced. Strided planes (multi-stripe, k or
    m >= 2) still pay the single extraction copy into a fresh
    bytearray — the remaining reply_assemble ledger entry."""
    t0 = time.perf_counter()
    S, _, C = stripes.shape
    out: dict[int, memoryview] = {}
    copied = 0
    referenced = 0
    for i in sorted(want):
        src = stripes[:, i, :] if i < k else parity[:, i - k, :]
        if src.flags.c_contiguous:
            # no materialization: the plane is a window over the encode
            # input (data shards) or the device result (parity)
            out[i] = memoryview(src.reshape(S * C))
            referenced += S * C
            continue
        buf = bytearray(S * C)
        np.copyto(np.frombuffer(buf, dtype=np.uint8).reshape(S, C), src)
        out[i] = memoryview(buf)
        copied += S * C
    dt = time.perf_counter() - t0
    if referenced:
        copytrack.referenced("reply_assemble", referenced)
    if copied:
        copytrack.copied("reply_assemble", copied, dt)
    if sp is not None:
        sp.set_tag("copy_bytes", copied)
        sp.set_tag("copy_us", round(dt * 1e6, 1))
    return out


def _encode_scalar(sinfo: StripeInfo, ec_impl, stripes, want, k, n_chunks,
                   mapping) -> dict[int, bytes]:
    """The reference's per-stripe loop through the scalar contract."""
    data_pos = mapping if mapping else list(range(k))
    out_chunks = []
    for s in range(stripes.shape[0]):
        chunks = {i: np.zeros(sinfo.chunk_size, dtype=np.uint8)
                  for i in range(n_chunks)}
        for rank, pos in enumerate(data_pos):
            chunks[pos] = stripes[s, rank].copy()
        ec_impl.encode_chunks(chunks)
        out_chunks.append(np.stack([chunks[i] for i in range(n_chunks)]))
    full = np.stack(out_chunks)
    # shard i = chunks of all stripes, contiguous (S major)
    return {i: full[:, i, :].tobytes() for i in sorted(want)}


def _encode_framed(sinfo: StripeInfo, ec_impl, stripes, want, k, n_chunks,
                   mapping, batched) -> dict[int, bytes]:
    """Inline dispatch of an already-validated frame."""
    with tracer.span("ec_encode") as sp:
        if sp is not None:
            sp.set_tag("bytes", int(stripes.size))
            sp.set_tag("k", k)
            sp.set_tag("m", n_chunks - k)
            sp.set_tag("stripes", stripes.shape[0])
            sp.set_tag("batched", batched)
        if batched:
            parity = np.asarray(ec_impl.encode_stripes(stripes))
            return _encode_assemble(stripes, parity, k, want, sp=sp)
        return _encode_scalar(sinfo, ec_impl, stripes, want, k, n_chunks,
                              mapping)


def encode(sinfo: StripeInfo, ec_impl, data: bytes | np.ndarray,
           want: Iterable[int] | None = None) -> dict[int, bytes]:
    """Encode a stripe-aligned logical buffer into per-shard buffers.

    Equivalent of ECUtil::encode (ECUtil.cc:134): input length must be a
    multiple of stripe_width; output maps shard id -> contiguous buffer of
    one chunk per stripe. One batched device dispatch when the plugin
    supports it, else the reference's per-stripe loop.
    """
    stripes, want, k, n_chunks, mapping, batched = _encode_frame(
        sinfo, ec_impl, data, want)
    if stripes is None:
        return {i: b"" for i in sorted(want)}
    return _encode_framed(sinfo, ec_impl, stripes, want, k, n_chunks,
                          mapping, batched)


async def encode_async(sinfo: StripeInfo, ec_impl,
                       data: bytes | np.ndarray,
                       want: Iterable[int] | None = None,
                       service=None) -> dict[int, bytes]:
    """encode() through the process-wide offload service: the device
    dispatch enters the admission queue and coalesces with concurrent
    callers' stripes (one staged device batch across PGs/daemons)
    instead of dispatching inline. Without a service — or on a plugin
    with no batched API — this is exactly encode()."""
    stripes, want, k, n_chunks, mapping, batched = _encode_frame(
        sinfo, ec_impl, data, want)
    if stripes is None:
        return {i: b"" for i in sorted(want)}
    if not (batched and service is not None):
        return _encode_framed(sinfo, ec_impl, stripes, want, k, n_chunks,
                              mapping, batched)
    with tracer.span("ec_encode") as sp:
        if sp is not None:
            sp.set_tag("bytes", int(stripes.size))
            sp.set_tag("k", k)
            sp.set_tag("m", n_chunks - k)
            sp.set_tag("stripes", stripes.shape[0])
            sp.set_tag("batched", True)
            sp.set_tag("offload", True)
        parity = np.asarray(await service.encode(ec_impl, stripes))
        return _encode_assemble(stripes, parity, k, want, sp=sp)


def _reconstruct_stack(ec_impl, stacked: Mapping[int, np.ndarray],
                       helpers) -> tuple[tuple[int, ...], np.ndarray]:
    """The dispatch contract of batched reconstruction, in ONE place
    (first-k helper order, (n, k, C) stacking) — shared by the inline
    and offload-service paths of both degraded read and shard
    recovery."""
    k = ec_impl.get_data_chunk_count()
    use = tuple(helpers[:k])
    if len(use) < k:
        raise ErasureCodeError(
            f"cannot decode: {len(use)} shards available, need {k}")
    return use, np.stack([stacked[i] for i in use], axis=1)  # (n, k, C)


def _reconstruct_unstack(rec: np.ndarray, want) -> dict[int, np.ndarray]:
    return {wid: rec[:, j, :] for j, wid in enumerate(want)}


def _batched_reconstruct(ec_impl, stacked: Mapping[int, np.ndarray],
                         helpers: list[int], want: list[int]) -> dict[int, np.ndarray]:
    """One-dispatch reconstruction of `want` shards from per-shard
    (n, chunk_size) planes via the plugin's decode_stripes batch API."""
    use, src = _reconstruct_stack(ec_impl, stacked, helpers)
    rec = np.asarray(ec_impl.decode_stripes(use, tuple(want), src))
    return _reconstruct_unstack(rec, want)


def _decode_concat_frame(sinfo: StripeInfo, ec_impl,
                         to_decode: Mapping[int, bytes]):
    """Shared framing for decode_concat(): validates the shard buffers
    and resolves the healthy-read case. Returns (done_bytes, work):
    exactly one is non-None; `work` is (stacked, avail_ids, missing,
    want, k, n_stripes, mapping)."""
    k = ec_impl.get_data_chunk_count()
    arrays = {i: np.frombuffer(sanitizer.unwrap(b), dtype=np.uint8)
              for i, b in to_decode.items()}
    if not arrays:
        raise ErasureCodeError("no chunks to decode")
    total = next(iter(arrays.values())).size
    if total % sinfo.chunk_size:
        raise ErasureCodeError("shard buffer not chunk aligned")
    for i, a in arrays.items():
        if a.size != total:
            raise ErasureCodeError(f"shard {i} length {a.size} != {total}")
    n_stripes = total // sinfo.chunk_size
    if n_stripes == 0:
        return b"", None

    mapping = ec_impl.get_chunk_mapping()
    want = [mapping[i] if mapping else i for i in range(k)]
    avail_ids = sorted(arrays)
    missing = [i for i in want if i not in arrays]

    stacked = {i: arrays[i].reshape(n_stripes, sinfo.chunk_size)
               for i in avail_ids}
    if not missing:
        # healthy read: the result is just the rank-ordered interleave of
        # the data shards — no plugin call needed
        out = np.empty((n_stripes, k, sinfo.chunk_size), dtype=np.uint8)
        for rank, cid in enumerate(want):
            out[:, rank, :] = stacked[cid]
        return out.tobytes(), None
    return None, (stacked, avail_ids, missing, want, k, n_stripes, mapping)


def _decode_concat_assemble(sinfo: StripeInfo, stacked, recovered, want,
                            k: int, n_stripes: int) -> bytes:
    out = np.empty((n_stripes, k, sinfo.chunk_size), dtype=np.uint8)
    for rank, cid in enumerate(want):
        out[:, rank, :] = stacked[cid] if cid in stacked \
            else recovered[cid]
    return out.tobytes()


def decode_concat(sinfo: StripeInfo, ec_impl,
                  to_decode: Mapping[int, bytes]) -> bytes:
    """Reconstruct and concatenate the data shards in rank order — the
    ECUtil::decode concat variant (ECUtil.cc:21-59) feeding degraded reads.

    `to_decode` maps shard id -> equal-length multi-chunk buffer.
    """
    done, work = _decode_concat_frame(sinfo, ec_impl, to_decode)
    if done is not None:
        return done
    return _decode_concat_framed(sinfo, ec_impl, work)


def _decode_concat_framed(sinfo: StripeInfo, ec_impl, work) -> bytes:
    """Inline reconstruction of an already-validated frame."""
    stacked, avail_ids, missing, want, k, n_stripes, mapping = work
    with tracer.span("ec_decode") as sp:
        if sp is not None:
            sp.set_tag("bytes", int(n_stripes * sinfo.chunk_size
                                    * len(stacked)))
            sp.set_tag("k", k)
            sp.set_tag("missing", missing)
            sp.set_tag("stripes", n_stripes)
        if callable(getattr(ec_impl, "decode_stripes", None)) \
                and not mapping:
            recovered = _batched_reconstruct(ec_impl, stacked, avail_ids,
                                             missing)
            return _decode_concat_assemble(sinfo, stacked, recovered,
                                           want, k, n_stripes)

        # per-stripe fallback through the scalar contract (reference loop)
        parts = []
        for s in range(n_stripes):
            chunks = {i: stacked[i][s].tobytes() for i in avail_ids}
            parts.append(ec_impl.decode_concat(chunks, sinfo.chunk_size))
        return b"".join(parts)


async def decode_concat_async(sinfo: StripeInfo, ec_impl,
                              to_decode: Mapping[int, bytes],
                              service=None) -> bytes:
    """decode_concat() with the reconstruction dispatch routed through
    the offload service (degraded reads coalesce across PGs when they
    share an erasure pattern). Healthy reads never touch the device and
    return synchronously either way."""
    done, work = _decode_concat_frame(sinfo, ec_impl, to_decode)
    if done is not None:
        return done
    stacked, avail_ids, missing, want, k, n_stripes, mapping = work
    if not (service is not None and not mapping
            and callable(getattr(ec_impl, "decode_stripes", None))):
        return _decode_concat_framed(sinfo, ec_impl, work)
    with tracer.span("ec_decode") as sp:
        if sp is not None:
            sp.set_tag("k", k)
            sp.set_tag("missing", missing)
            sp.set_tag("stripes", n_stripes)
            sp.set_tag("offload", True)
        use, src = _reconstruct_stack(ec_impl, stacked, avail_ids)
        rec = np.asarray(await service.decode(ec_impl, use,
                                              tuple(missing), src))
        recovered = _reconstruct_unstack(rec, missing)
        return _decode_concat_assemble(sinfo, stacked, recovered, want,
                                       k, n_stripes)


def _decode_shards_frame(sinfo: StripeInfo, ec_impl,
                         to_decode: Mapping[int, bytes], need: list[int],
                         fragments: bool = False):
    """Shared repair-plan validation for decode_shards(): returns
    (arrays, helpers, plan_counts, sub, repair_per_chunk, n_chunks) —
    one copy, so plan-contract fixes (like the ADVICE-r2 homogeneity
    guard) apply to the inline and offload paths alike.

    `fragments` declares that the buffers were FETCHED per the plugin's
    sub-chunk repair plan (strided runs). Without it, whole-chunk
    buffers that happen to satisfy a repair plan's preconditions (a
    gather that topped up to >= d shards on a clay pool) must NOT be
    sliced by that plan — contiguous chunk thirds are not the plan's
    strided sub-chunk runs, and the mis-slice would silently decode
    garbage (and inflate the output q-fold)."""
    arrays = {i: np.frombuffer(sanitizer.unwrap(b), dtype=np.uint8)
              for i, b in to_decode.items()}
    if not arrays:
        raise ErasureCodeError("no chunks to decode")
    sub = ec_impl.get_sub_chunk_count()
    minimum = ec_impl.minimum_to_decode(need, set(arrays))
    if not fragments and any(
            sum(cnt for _, cnt in runs) != sub
            for runs in minimum.values()):
        # sub-chunk plan over whole-chunk buffers: decode from the
        # provided whole chunks instead
        minimum = {i: [(0, sub)] for i in sorted(arrays)}
    missing_helpers = sorted(set(minimum) - set(arrays))
    if missing_helpers:
        raise ErasureCodeError(
            f"repair plan needs shards {missing_helpers} that were not "
            f"fetched (have {sorted(arrays)})")
    subchunk_size = sinfo.chunk_size // sub
    # the repair plan must be homogeneous: every helper contributes the
    # same number of sub-chunks per chunk, or the fixed-stride slicing
    # below would mis-slice the fetched buffers (ADVICE r2)
    plan_counts = {i: sum(cnt for _, cnt in runs)
                   for i, runs in minimum.items()}
    if len(set(plan_counts.values())) != 1:
        raise ErasureCodeError(
            f"heterogeneous repair plan (sub-chunks per chunk by shard): "
            f"{plan_counts}")
    repair_per_chunk = next(iter(plan_counts.values())) * subchunk_size
    helpers = sorted(minimum)
    sizes = {arrays[i].size for i in helpers}
    if len(sizes) != 1:
        raise ErasureCodeError(
            f"helper shard buffers differ in length: "
            f"{ {i: arrays[i].size for i in helpers} }")
    total = sizes.pop()
    if total % repair_per_chunk:
        raise ErasureCodeError("shard buffer not aligned to repair unit")
    return arrays, helpers, plan_counts, sub, repair_per_chunk, \
        total // repair_per_chunk


async def decode_shards_async(sinfo: StripeInfo, ec_impl,
                              to_decode: Mapping[int, bytes],
                              need: Iterable[int],
                              service=None,
                              fragments: bool = False) -> dict[int, bytes]:
    """decode_shards() with the repair dispatch routed through the
    offload service. Whole-chunk plans on batch-capable plugins ride
    the DecodeJob (n, k, C) shape; single-shard SUB-CHUNK plans (the
    CLAY regenerating repair, fed by a runs-gather that fetched only
    repair_per_chunk bytes per helper chunk — declared by
    `fragments=True`) ride the service's repair job — coalesced per
    erasure pattern and run off the event loop. Mapped plugins and
    multi-shard sub-chunk plans keep the inline path."""
    need_l = sorted(set(need))
    if (fragments and service is not None and len(need_l) == 1
            and ec_impl.get_sub_chunk_count() > 1
            and not ec_impl.get_chunk_mapping()):
        arrays, helpers, plan_counts, sub, rpc, n_chunks = \
            _decode_shards_frame(sinfo, ec_impl, to_decode, need_l,
                                 fragments=True)
        if n_chunks > 0 and rpc < sinfo.chunk_size:
            with tracer.span("ec_recover") as sp:
                if sp is not None:
                    sp.set_tag("need", need_l)
                    sp.set_tag("helpers", helpers)
                    sp.set_tag("chunks", n_chunks)
                    sp.set_tag("sub_chunks", sub)
                    sp.set_tag("sub_chunks_fetched_per_chunk",
                               next(iter(plan_counts.values())))
                    sp.set_tag("offload", True)
                frags = np.stack([arrays[h].reshape(n_chunks, rpc)
                                  for h in helpers], axis=1)
                out = np.asarray(await service.repair(
                    ec_impl, tuple(helpers), tuple(need_l), frags,
                    sinfo.chunk_size))
                return {need_l[0]:
                        np.ascontiguousarray(out).tobytes()}
    if not (service is not None
            and ec_impl.get_sub_chunk_count() == 1
            and not ec_impl.get_chunk_mapping()
            and callable(getattr(ec_impl, "decode_stripes", None))):
        return decode_shards(sinfo, ec_impl, to_decode, need_l,
                             fragments=fragments)
    arrays, helpers, _plan, _sub, _rpc, n_chunks = _decode_shards_frame(
        sinfo, ec_impl, to_decode, need_l)
    if n_chunks == 0:
        return decode_shards(sinfo, ec_impl, to_decode, need_l,
                             fragments=fragments)
    with tracer.span("ec_recover") as sp:
        if sp is not None:
            sp.set_tag("need", need_l)
            sp.set_tag("helpers", helpers)
            sp.set_tag("chunks", n_chunks)
            sp.set_tag("offload", True)
        stacked = {i: arrays[i].reshape(n_chunks, sinfo.chunk_size)
                   for i in helpers}
        use, src = _reconstruct_stack(ec_impl, stacked, helpers)
        rec = np.asarray(await service.decode(ec_impl, use, tuple(need_l),
                                              src))
        return {nid: np.ascontiguousarray(plane).tobytes()
                for nid, plane in
                _reconstruct_unstack(rec, need_l).items()}


def decode_shards(sinfo: StripeInfo, ec_impl, to_decode: Mapping[int, bytes],
                  need: Iterable[int],
                  fragments: bool = False) -> dict[int, bytes]:
    """Reconstruct whole shards (data or parity) — the per-shard
    ECUtil::decode variant (ECUtil.cc:61-131) used by shard recovery.

    `to_decode` holds whole-chunk shard buffers, or — with
    `fragments=True` — sub-chunk fragments fetched per
    minimum_to_decode (each shard buffer contains
    repair_data_per_chunk bytes per chunk); `need` lists shard ids to
    rebuild. Returns full-size rebuilt shards.
    """
    need = sorted(set(need))
    arrays, helpers, plan_counts, sub, repair_per_chunk, n_chunks = \
        _decode_shards_frame(sinfo, ec_impl, to_decode, need,
                             fragments=fragments)

    with tracer.span("ec_recover") as sp:
        if sp is not None:
            sp.set_tag("need", need)
            sp.set_tag("helpers", helpers)
            sp.set_tag("chunks", n_chunks)
            # the sub-chunk repair plan (CLAY fetches fractions of each
            # helper chunk; RS fetches whole chunks = sub_chunks)
            sp.set_tag("sub_chunks", sub)
            sp.set_tag("sub_chunks_fetched_per_chunk",
                       next(iter(plan_counts.values())))
        if (sub == 1 and not ec_impl.get_chunk_mapping()
                and callable(getattr(ec_impl, "decode_stripes", None))
                and n_chunks > 0):
            # whole-chunk repair on a batch-capable plugin: ONE device
            # dispatch for all n_chunks repair units instead of a host
            # round trip per chunk — the recovery path is the most
            # bandwidth-hungry consumer (reference batching site:
            # src/osd/ECUtil.cc:61-131)
            stacked = {i: arrays[i].reshape(n_chunks, sinfo.chunk_size)
                       for i in helpers}
            recovered = _batched_reconstruct(ec_impl, stacked, helpers,
                                             need)
            return {nid: np.ascontiguousarray(plane).tobytes()
                    for nid, plane in recovered.items()}

        outs = {i: [] for i in need}
        for c in range(n_chunks):
            chunks = {i: arrays[i][c * repair_per_chunk:
                                   (c + 1) * repair_per_chunk].tobytes()
                      for i in helpers}
            decoded = ec_impl.decode(need, chunks, sinfo.chunk_size)
            for i in need:
                if len(decoded[i]) != sinfo.chunk_size:
                    raise ErasureCodeError(
                        f"decode returned {len(decoded[i])} bytes for "
                        f"shard {i}")
                outs[i].append(decoded[i])
        return {i: b"".join(parts) for i, parts in outs.items()}


# ---------------------------------------------------------------------------
# Per-shard cumulative chunk hashes
# ---------------------------------------------------------------------------

class HashInfo:
    """Cumulative per-shard crc32c across appends (ECUtil.h:141-199).

    Seeds at -1 like the reference's bufferlist crc32c; `append` must be
    called with the shard map of every append in order, with old_size
    equal to the pre-append per-shard size (torn-write detection).
    """

    def __init__(self, num_chunks: int = 0):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0xFFFFFFFF] * num_chunks
        self.projected_total_chunk_size = 0

    def has_chunk_hash(self) -> bool:
        return bool(self.cumulative_shard_hashes)

    def append(self, old_size: int, to_append: Mapping[int, bytes]) -> None:
        if old_size != self.total_chunk_size:
            raise ValueError(
                f"append at {old_size} but shard size is {self.total_chunk_size}")
        if not to_append:
            return
        sizes = {len(b) for b in to_append.values()}
        if len(sizes) != 1:
            raise ValueError(f"unequal shard append sizes {sizes}")
        size = sizes.pop()
        if self.has_chunk_hash():
            if set(to_append) != set(range(len(self.cumulative_shard_hashes))):
                raise ValueError(
                    f"append must cover shards 0.."
                    f"{len(self.cumulative_shard_hashes) - 1}, got "
                    f"{sorted(to_append)}")
            from ceph_tpu.native import ec_native
            for shard, buf in to_append.items():
                self.cumulative_shard_hashes[shard] = ec_native.crc32c(
                    buf, self.cumulative_shard_hashes[shard])
        self.total_chunk_size += size

    def clear(self) -> None:
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0xFFFFFFFF] * len(
            self.cumulative_shard_hashes)

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    def get_total_logical_size(self, sinfo: StripeInfo) -> int:
        return self.total_chunk_size * (sinfo.stripe_width // sinfo.chunk_size)

    def set_projected_total_logical_size(self, sinfo: StripeInfo,
                                         logical: int) -> None:
        self.projected_total_chunk_size = \
            sinfo.aligned_logical_offset_to_chunk_offset(logical)

    def to_dict(self) -> dict:
        return {"total_chunk_size": self.total_chunk_size,
                "cumulative_shard_hashes": list(self.cumulative_shard_hashes)}

    @classmethod
    def from_dict(cls, d: dict) -> "HashInfo":
        h = cls()
        h.total_chunk_size = int(d["total_chunk_size"])
        h.cumulative_shard_hashes = [int(x) for x in
                                     d["cumulative_shard_hashes"]]
        return h
