"""PGInstance: one placement group living on one OSD.

Re-creation of the reference's PG/PrimaryLogPG/PeeringState essentials
(src/osd/PG.cc, src/osd/PrimaryLogPG.cc:1816,1982 do_request/do_op,
src/osd/PeeringState.h:452 GetInfo->GetLog->GetMissing->Activate):

  * the primary serializes client ops, stamps each with an eversion,
    appends to the PGLog and fans the write out through its PGBackend;
  * on every map change the PG re-peers: the primary collects peer
    infos+logs, elects the authoritative log (max last_update, the
    reference's find_best_info), merges it (PGLog::merge_log), pulls
    what it is missing, pushes what the replicas are missing, and only
    then goes active;
  * ops arriving while peering are queued (waiting_for_active), not
    failed — clients never see transient peering (src/osd/PG.cc
    waiting_for_active semantics).

Idiomatic divergences: peering is one coroutine instead of a
boost::statechart; a replica whose log is unmergeable (behind the tail)
is backfilled by full-collection push; object data rides the message
data segment one object at a time.
"""
from __future__ import annotations

import asyncio
import json
from typing import TYPE_CHECKING

from ceph_tpu.crush.crush import CRUSH_NONE
from ceph_tpu.crush.osdmap import PG
from ceph_tpu.msg.messages import (Message, MOSDPGInfo, MOSDPGLog,
                                   MOSDPGPush, MOSDPGPushReply, MOSDPGQuery,
                                   MOSDRepScrubMap)
from ceph_tpu.objectstore.store import StoreError, Transaction
from ceph_tpu.objectstore.types import CollectionId, Ghobject
from ceph_tpu.osd.pglog import ZERO, Eversion, LogEntry, PGLog
from ceph_tpu.qa import interleave
from ceph_tpu.utils import tracer
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.work_queue import WRITE_OP_KINDS, mark_op_event

if TYPE_CHECKING:
    from ceph_tpu.osd.daemon import OSD

PEER_TIMEOUT = 5.0
PGMETA_OID = "_pgmeta_"


class PeerSilent(Exception):
    """An up acting peer did not answer a peering round."""


class PGInstance:
    """One PG on one OSD: log + backend + peering driver."""

    def __init__(self, host: "OSD", pgid: PG, pool):
        self.host = host
        self.pgid = pgid
        self.pool = pool
        self.log = PGLog()
        self.acting: list[int] = []
        self.up: list[int] = []
        self.state = "initial"          # initial|peering|active|replica|stray
        self.last_epoch_started = 0
        self.seq = 0                    # per-PG op sequence (eversion minor)
        self._active_event = asyncio.Event()
        self._peer_task: asyncio.Task | None = None
        # acting member -> boot addr at the current interval (up_from
        # analog: a changed addr with an unchanged acting set means a
        # peer restarted and the interval must roll)
        self._interval_addrs: dict[int, str] = {}
        # peering scratch: peer osd -> {"info":..., "entries":...}
        self._peer_logs: dict[int, dict] = {}
        self._peer_waiters: dict[int, asyncio.Future] = {}
        self._push_waiters: dict[str, asyncio.Future] = {}
        # async recovery: oid -> behind peers still needing a push;
        # activation of those peers is deferred until their set drains
        self._pending_recovery: dict[str, set[int]] = {}
        # objects in the current recovery round at its start — with
        # len(_pending_recovery) remaining, this yields the completion
        # fraction published through the mgr report path
        self.recovery_total = 0
        self._deferred_activate: dict[int, dict] = {}
        self._recovery_inflight: dict[str, asyncio.Future] = {}
        self._recovery_task: asyncio.Task | None = None
        # scrub: (tid, peer) -> future resolving to the peer's scrub map
        self._scrub_waiters: dict[tuple, asyncio.Future] = {}
        # scrub reservations: (tid, peer) -> future resolving True on
        # grant / False on reject (MOSDScrubReserve round-trips)
        self._reserve_waiters: dict[tuple, asyncio.Future] = {}
        self.last_scrub: dict | None = None
        self._scrub_lock = asyncio.Lock()
        # scrub observability: live round progress, wall-clock stamps,
        # cumulative counters, and the inconsistent-object registry
        # (list-inconsistent-obj + the mgr PG_DAMAGED check source) —
        # entries persist until a clean same-or-deeper round retires
        # them, so health clears only on a verified-clean rescan
        self.scrub_progress = None
        self.last_scrub_stamp = 0.0
        self.last_deep_scrub_stamp = 0.0
        self.scrub_stats = {"objects_scrubbed": 0, "bytes_hashed": 0,
                            "errors_found": 0, "errors_repaired": 0}
        self.inconsistent_objects: dict[str, dict] = {}
        # write gate: scrub blocks new modifies and drains in-flight ones
        # so repairs never race an acknowledged write (the reference's
        # scrub-range write blocking)
        self._write_gate = asyncio.Event()
        self._write_gate.set()
        self._active_writes = 0
        self._writes_drained = asyncio.Event()
        self._writes_drained.set()
        # replica-side meta-persist coalescing: batched sub-op drains
        # deliver many entries in one loop slice — persist once per
        # slice, not once per sub-op (see persist_meta_soon). The flag
        # only dedupes the scheduled callback; acks ride the flush so
        # no sub-op is acknowledged before its entry is durable.
        self._persist_scheduled = False
        self._persist_acks: list[tuple] = []
        # snaps this primary has finished trimming (persisted in meta)
        self.purged_snaps: set[int] = set()
        self._snaptrim_task: asyncio.Task | None = None
        # watch/notify (primary, in-memory: clients linger-re-register
        # across primary changes): oid -> cookie -> watcher record
        self.watchers: dict[str, dict[int, dict]] = {}
        self._notify_seq = 0
        # notify_id -> {"pending": set[cookie], "acks": [...], "fut": ...}
        self._notifies: dict[int, dict] = {}
        if pool.type == "erasure":
            from ceph_tpu.osd.ec_backend import ECBackend
            self.backend = ECBackend(self)
        else:
            from ceph_tpu.osd.backend import ReplicatedBackend
            self.backend = ReplicatedBackend(self)
        self.backend.ensure_collections()
        self._load_meta()

    # -- identity ------------------------------------------------------------

    @property
    def primary(self) -> int:
        for o in self.acting:
            if o != CRUSH_NONE:
                return o
        return CRUSH_NONE

    def is_primary(self) -> bool:
        return self.primary == self.host.whoami

    def acting_peers(self) -> set[int]:
        return {o for o in self.acting
                if o not in (CRUSH_NONE, self.host.whoami)}

    def info(self) -> dict:
        return {"last_update": list(self.log.head),
                "last_complete": list(self.log.last_complete),
                "log_tail": list(self.log.tail),
                "last_epoch_started": self.last_epoch_started}

    def next_version(self) -> Eversion:
        self.seq += 1
        return (self.host.osdmap.epoch, self.seq)

    # -- persistence (superblock-style pg meta in the pg collection) ---------

    def _meta_gh(self) -> Ghobject:
        return Ghobject(pool=self.pgid.pool, name=PGMETA_OID)

    def persist_meta(self) -> None:
        """Durable PG meta: a small static attr (head/tail/missing/seq)
        plus ONE omap key per log entry, written incrementally — only
        entries that changed since the last persist are (re)written.
        Re-serializing the whole 1000-entry window per op dominated the
        write path (profiled); the reference stores log entries as
        individual omap keys for the same reason
        (src/osd/PGLog.cc _write_log_and_missing)."""
        blob = json.dumps({"seq": self.seq,
                           "les": self.last_epoch_started,
                           "head": list(self.log.head),
                           "tail": list(self.log.tail),
                           "missing": {o: list(v) for o, v in
                                       self.log.missing.items()},
                           "purged_snaps": sorted(self.purged_snaps)}
                          ).encode()
        cid = self.backend.coll()
        gh = self._meta_gh()
        txn = Transaction()
        if not self.host.store.exists(cid, gh):
            txn.touch(cid, gh)
        txn.setattr(cid, gh, "pgmeta", blob)
        full, dirty = self.log.take_dirty()
        if full:
            # the meta omap is shared (SnapMapper keys live there too):
            # remove only the log-prefixed keys, never omap_clear
            try:
                stale = [k for k in self.host.store.omap_get(cid, gh)
                         if k.startswith(PGLog.KEY_PREFIX)]
            except StoreError:
                stale = []
            if stale:
                txn.omap_rmkeys(cid, gh, stale)
            txn.omap_setkeys(cid, gh, {
                PGLog.entry_key(e.version):
                    json.dumps(e.to_dict()).encode()
                for e in self.log.entries})
        else:
            rm = [k for k, v in dirty.items() if v is None]
            if rm:
                txn.omap_rmkeys(cid, gh, rm)
            sets = {k: json.dumps(v.to_dict()).encode()
                    for k, v in dirty.items() if v is not None}
            if sets:
                txn.omap_setkeys(cid, gh, sets)
        try:
            self.host.store.queue_transaction(txn)
        except Exception:
            # the delta never reached disk: hand it back or those
            # entries vanish from the persisted omap forever
            self.log.restore_dirty(full, dirty)
            raise

    def persist_meta_soon(self, ack: tuple | None = None) -> None:
        """Coalesced replica-side persist: a pipelined primary's batch
        envelopes deliver many sub-ops per loop slice, and each used to
        re-serialize + write the meta blob individually. One call_soon
        flush per slice persists them all (the in-memory log is updated
        synchronously; only the disk write coalesces — the same
        window a journaling store batches into one commit). The PRIMARY
        path keeps its synchronous persist: the dup-replay invariant
        needs the intent durable within the ordered slice.

        `ack` is a deferred (conn, reply) pair sent only AFTER the
        persist succeeds: a sub-op is never acknowledged while its log
        entry is not durable — a persist failure drops the acks, the
        primary's sub-op wait times out, and the client resends
        (exactly the pre-coalescing failure behavior). Flushed
        explicitly by flush_persist() at daemon stop."""
        if ack is not None:
            self._persist_acks.append(ack)
        if self._persist_scheduled:
            return
        self._persist_scheduled = True
        asyncio.get_running_loop().call_soon(self._persist_flush)

    def _persist_flush(self) -> None:
        self._persist_scheduled = False
        acks, self._persist_acks = self._persist_acks, []
        try:
            self.persist_meta()
        except Exception as e:
            # the delta was handed back by persist_meta's failure path;
            # the UNSENT acks make the primary time the sub-ops out, so
            # nothing is counted replicated that is not persisted
            dout("osd", 1, f"pg {self.pgid} coalesced meta persist "
                           f"failed: {type(e).__name__} {e} (delta "
                           f"restored; sub-op acks withheld)")
            return
        for conn, reply in acks:
            try:
                conn.send_message(reply)
            except Exception:
                pass            # dead peer conn: its timeout handles it

    def flush_persist(self) -> None:
        """Synchronously flush the coalesced persist (daemon stop:
        nothing may stay dirty past umount; unconditional — a
        previously failed flush left dirty state behind with no
        callback armed)."""
        self._persist_flush()

    def _load_meta(self) -> None:
        cid = self.backend.coll()
        gh = self._meta_gh()
        try:
            blob = self.host.store.getattr(cid, gh, "pgmeta")
        except StoreError:
            return
        meta = json.loads(blob)
        if "log" in meta:           # legacy inline-entries format
            self.log = PGLog.from_dict(meta["log"])
        else:
            self.log = PGLog.from_omap(
                meta, self.host.store.omap_get(cid, gh))
        self.seq = meta.get("seq", self.log.head[1])
        self.last_epoch_started = meta.get("les", 0)
        self.purged_snaps = set(meta.get("purged_snaps", []))

    def list_objects(self) -> list[str]:
        from ceph_tpu.objectstore.types import CEPH_NOSNAP
        from ceph_tpu.osd.ec_backend import PREV_SUFFIX
        cid = self.backend.coll()
        return sorted(gh.name for gh in self.host.store.collection_list(cid)
                      if gh.name != PGMETA_OID
                      and not gh.name.endswith(PREV_SUFFIX)
                      and gh.snap == CEPH_NOSNAP)

    def recovery_objects(self) -> list[str]:
        """Everything recovery/backfill must move: heads plus headless
        objects whose clones/snapdir survive a head delete."""
        from ceph_tpu.osd import snaps
        names = set(self.list_objects())
        names |= snaps.headless_snap_objects(self.host.store,
                                             self.backend.coll())
        names.discard(PGMETA_OID)
        return sorted(names)

    def _purge_stray(self, oid: str) -> None:
        """Drop a stray object found during backfill: unlike a client
        delete, its snapshot state goes with it."""
        self.backend.local_apply(oid, "purge", b"")

    # -- map advance ---------------------------------------------------------

    def advance_map(self, up: list[int], acting: list[int]) -> None:
        """New osdmap epoch: if the acting set changed — or any acting
        member RESTARTED without ever being marked down (same set, new
        boot address) — re-peer (the reference starts a new peering
        interval, PeeringState advance_map/start_peering_interval; a
        restart inside the heartbeat grace changes up_from and is a new
        interval per check_new_interval, which PastIntervals records —
        here the boot address plays the up_from role). Without this, a
        sub-op lost in a kill+revive-within-grace window is never
        repaired: no epoch changes the acting set, so no peering runs
        and the revived peer serves its stale shard forever (found by
        the thrashing model checker)."""
        addrs = {o: self.host.osdmap.get_addr(o) for o in acting
                 if o != CRUSH_NONE and o in self.host.osdmap.osds}
        restarted = addrs != self._interval_addrs
        if acting == self.acting and not restarted:
            if self.state in ("active", "replica"):
                return
            if (self.state == "peering" and self._peer_task is not None
                    and not self._peer_task.done()):
                # same interval, peering already in flight: a second task
                # would clobber the first's _peer_waiters (ADVICE r4)
                return
        self._interval_addrs = addrs
        interval_changed = acting != self.acting or restarted
        self.up, self.acting = list(up), list(acting)
        if interval_changed:
            self.backend.fail_inflight("peering interval change")
        self._cancel_peering()
        if self.host.whoami not in self.acting:
            self.state = "stray"
            self._active_event.clear()
            return
        if self.is_primary():
            self.state = "peering"
            self._active_event.clear()
            self._peer_task = asyncio.get_running_loop().create_task(
                self._peer())
        else:
            # replica: wait for the primary's activation
            self.state = "replica"
            self._active_event.clear()

    def _cancel_peering(self) -> None:
        if self._peer_task is not None and not self._peer_task.done():
            self._peer_task.cancel()
        self._peer_task = None
        if self._recovery_task is not None and \
                not self._recovery_task.done():
            self._recovery_task.cancel()
        self._recovery_task = None
        if self._snaptrim_task is not None and \
                not self._snaptrim_task.done():
            self._snaptrim_task.cancel()
        self._snaptrim_task = None
        self._pending_recovery.clear()
        self.recovery_total = 0
        self._deferred_activate.clear()
        for fut in self._peer_waiters.values():
            if not fut.done():
                fut.cancel()
        self._peer_waiters.clear()

    async def wait_active(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(self._active_event.wait(), timeout)

    # -- peering (primary coroutine) -----------------------------------------

    async def _peer(self) -> None:
        """Retry until every acting peer answers: going active without a
        live acting peer's log would leave it permanently stale (the
        reference blocks in Peering until the interval changes)."""
        backoff = 0.2
        while True:
            try:
                await self._peer_inner()
                return
            except asyncio.CancelledError:
                raise
            except PeerSilent as e:
                dout("osd", 3, f"osd.{self.host.whoami} pg {self.pgid}: "
                               f"{e}; retrying peering")
            except Exception as e:
                dout("osd", 2, f"osd.{self.host.whoami} pg {self.pgid}: "
                               f"peering failed: {type(e).__name__} {e}")
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 2.0)

    async def _peer_inner(self) -> None:
        # drain the pipelined execution window first: ops admitted in
        # the previous interval must settle (fail_inflight already
        # errored their sub-op futures, so this is fast) before peers
        # are queried — no op's fan-out may straddle two intervals, and
        # the authoritative log election must not race in-flight
        # appends. Bounded: a write wedged on a dead peer exits via its
        # own sub-op timeout, not ours.
        if self._active_writes:
            self._writes_drained.clear()
            try:
                await asyncio.wait_for(self._writes_drained.wait(), 2.0)
            except asyncio.TimeoutError:
                dout("osd", 2, f"pg {self.pgid}: {self._active_writes} "
                               f"pipelined writes still in flight at "
                               f"peering; proceeding (they fail out to "
                               f"resend)")
        pgid_key = [self.pgid.pool, self.pgid.ps]
        epoch = self.host.osdmap.epoch
        # GetInfo+GetLog: ask every acting peer for info + log in one round
        replies: dict[int, dict] = {}
        waits = []
        for peer in self.acting_peers():
            fut = asyncio.get_running_loop().create_future()
            self._peer_waiters[peer] = fut
            await self.host.send_osd(peer, MOSDPGQuery(
                {"pgid": pgid_key, "from": self.host.whoami,
                 "epoch": epoch}))
            waits.append((peer, fut))
        silent: list[int] = []
        for peer, fut in waits:
            try:
                replies[peer] = await asyncio.wait_for(fut, PEER_TIMEOUT)
            except asyncio.TimeoutError:
                if self.host.osdmap.is_up(peer):
                    silent.append(peer)
            finally:
                self._peer_waiters.pop(peer, None)
        if silent:
            raise PeerSilent(f"acting peers {silent} silent during peering")

        # find_best_info: max last_update wins (self is a candidate)
        auth_osd, auth_head = self.host.whoami, self.log.head
        for peer, rep in replies.items():
            head = tuple(rep["info"]["last_update"])
            if head > auth_head:
                auth_osd, auth_head = peer, head

        if auth_osd != self.host.whoami:
            # GetMissing: merge the authoritative log
            auth = replies[auth_osd]
            auth_entries = [LogEntry.from_dict(e) for e in auth["entries"]]
            auth_tail = tuple(auth["info"]["log_tail"])
            if auth_tail > self.log.head:
                # we are behind the auth's log TAIL: its retained entries
                # cannot bridge our gap, and a plain merge would silently
                # lose every write older than the window (ADVICE r4) —
                # backfill the full authoritative object set instead
                await self._backfill_from(auth_osd, auth_entries,
                                          auth_head, auth_tail)
            else:
                self.log.merge_log(auth_entries, auth_head)
                self.seq = max(self.seq, self.log.head[1])
        # recover the PRIMARY itself before serving anything: merged
        # missing plus anything persisted from an earlier interval when
        # we were a recovering replica (the reference's own-missing set).
        # When we ARE the auth (recovering replica won the election),
        # pull from the peer with the highest head — most likely to
        # still hold the object
        source = auth_osd
        if source == self.host.whoami and replies:
            source = max(replies,
                         key=lambda p: tuple(
                             replies[p]["info"]["last_update"]))
        real_missing = {o: n for o, n in self.log.missing.items()
                        if tuple(n) != ZERO}
        if real_missing and source == self.host.whoami:
            # we are missing acked objects and have NO peer to pull from
            # (sole survivor): going active would serve ENOENT for them
            # and clearing the missing set would destroy the only record
            # — stay in peering until a peer returns or the interval
            # changes (the reference blocks on unfound objects likewise)
            raise PeerSilent(
                f"missing {len(real_missing)} objects with no pull "
                f"source (sole survivor)")
        for oid, need in list(self.log.missing.items()):
            if tuple(need) == ZERO:
                # rewind-to-none tombstone: the authoritative history
                # DELETED this object — reconstructing it from surviving
                # shards (or their rollback generations) would resurrect
                # an acked delete (found by the thrashing model checker)
                self.backend.local_apply(oid, "delete", b"")
            else:
                await self.backend.pull_object(
                    source, oid, need,
                    fallbacks=[p for p in sorted(replies) if p != source])
        self.log.clear_missing()

        # Activate: up-to-date replicas immediately; behind replicas get
        # a persisted `recovering` marker and their pushes run in the
        # BACKGROUND (reservation-throttled) so client I/O proceeds
        # while they backfill (the reference's async recovery/backfill
        # with AsyncReserver; activation per peer when its data is in)
        log_dict = self.log.to_dict()
        my_objects = None
        pending: dict[str, set[int]] = {}
        deferred: dict[int, dict] = {}
        for peer, rep in replies.items():
            peer_head = tuple(rep["info"]["last_update"])
            entries = self.log.entries_since(peer_head)
            act_payload = {"pgid": pgid_key, "op": "activate",
                           "epoch": epoch, "from": self.host.whoami,
                           "log": log_dict}
            if entries is None:
                # peer is behind the log tail: backfill everything, and
                # ship the authoritative object list so the replica can
                # drop strays (deletes it missed past the log window
                # would otherwise resurrect if it later became primary)
                if my_objects is None:
                    my_objects = self.recovery_objects()
                need_oids = list(my_objects)
                act_payload["objects"] = my_objects
            else:
                need_oids = sorted({e.oid for e in entries})
            if not need_oids:
                await self.host.send_osd(peer, MOSDPGInfo(act_payload))
                continue
            for oid in need_oids:
                pending.setdefault(oid, set()).add(peer)
            # only the SHAPE is remembered: the payload is rebuilt from
            # the live log/object set at activation time — a snapshot
            # from peering time would rewind the peer's log past writes
            # replicated to it during background recovery, and its
            # stale object list would delete legitimately-written
            # objects as strays
            deferred[peer] = {"backfill": entries is None}
            # the peer must KNOW it is missing these objects: if the
            # primary dies mid-backfill and the peer wins the next
            # election, its persisted missing set makes it pull them
            # before going active instead of serving ENOENT
            await self.host.send_osd(peer, MOSDPGInfo(
                {"pgid": pgid_key, "op": "recovering", "epoch": epoch,
                 "from": self.host.whoami,
                 "missing": {o: list(self.log.head) for o in need_oids}}))
        self._pending_recovery = pending
        self.recovery_total = len(pending)
        self._deferred_activate = deferred
        self.last_epoch_started = epoch
        self.persist_meta()
        self.state = "active"
        self._active_event.set()
        self.host.requeue_waiting(self)
        dout("osd", 3, f"osd.{self.host.whoami} pg {self.pgid} active "
                       f"(acting {self.acting}, head {self.log.head}, "
                       f"recovering {len(pending)} objects to "
                       f"{sorted(deferred)})")
        if pending:
            self._recovery_task = asyncio.get_running_loop().create_task(
                self._drain_recovery())
        self.maybe_snaptrim()

    # -- async recovery / backfill (primary side) ----------------------------

    async def _drain_recovery(self) -> None:
        """Push pending objects to behind peers under the host's
        recovery reservations; activate each peer once its set drains
        (AsyncReserver semantics, doc/dev/osd_internals/
        backfill_reservation.rst)."""
        try:
            while self._pending_recovery:
                oid = next(iter(self._pending_recovery))
                # reservation first (host-wide slot), THEN the op queue's
                # recovery class: the shard worker must never block on a
                # slot held by another PG's backfill
                await self.host.recovery_reservations.acquire()
                done = asyncio.get_running_loop().create_future()

                async def work(oid=oid, done=done):
                    try:
                        await self.recover_object_now(oid)
                    finally:
                        self.host.recovery_reservations.release()
                        if not done.done():
                            done.set_result(None)
                # obj=oid: the recovery item admits through the PG's
                # pipelined window alongside client ops to OTHER
                # objects, but serializes FIFO against any client op
                # touching the object being rebuilt
                # nbytes: a push moves whole shard chunks, so bill the
                # recovery entity one full per-IO byte budget (~2 cost
                # units) rather than metering the exact object size —
                # the tag clocks need relative pressure, not a ledger
                self.host.op_queue.enqueue(
                    (self.pgid.pool, self.pgid.ps), work,
                    klass="recovery", obj=oid,
                    nbytes=self.host.op_queue.sched.cost_per_io_bytes)
                await done
                if oid in self._pending_recovery:
                    # push failed and was re-queued: back off instead of
                    # hammering an unreachable peer
                    await asyncio.sleep(0.3)
            await self._activate_recovered()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            dout("osd", 1, f"pg {self.pgid} background recovery failed: "
                           f"{type(e).__name__} {e} (interval change "
                           f"will retry)")

    async def recover_object_now(self, oid: str) -> None:
        """Recover one object to every behind peer NOW — also called by
        the write path before touching a degraded object (the
        reference's wait_for_degraded_object). A push already in flight
        is AWAITED, never raced: the push's reconstruct gathers shard
        state that a concurrent write could supersede mid-build."""
        inflight = self._recovery_inflight.get(oid)
        if inflight is not None:
            await asyncio.shield(inflight)
            return
        peers = self._pending_recovery.pop(oid, None)
        if not peers:
            return
        fut = asyncio.get_running_loop().create_future()
        self._recovery_inflight[oid] = fut
        failed: set[int] = set()
        try:
            for peer in sorted(peers):
                try:
                    await self.backend.push_object(peer, oid)
                    self.host.perf.inc("recovery_push")
                except Exception as e:
                    dout("osd", 3, f"recovery push of {oid} to osd.{peer} "
                                   f"failed: {type(e).__name__} {e}")
                    failed.add(peer)
        finally:
            if failed:
                # a swallowed failure must NOT let the peer activate
                # with a hole (activation clears its missing record):
                # keep the oid pending so the drain retries — a truly
                # dead peer exits via the next interval change
                self._pending_recovery.setdefault(oid, set()).update(
                    failed)
            self._recovery_inflight.pop(oid, None)
            if not fut.done():
                fut.set_result(None)

    async def _activate_recovered(self) -> None:
        deferred, self._deferred_activate = self._deferred_activate, {}
        log_dict = self.log.to_dict()
        for peer, shape in deferred.items():
            act_payload = {"pgid": [self.pgid.pool, self.pgid.ps],
                           "op": "activate",
                           "epoch": self.last_epoch_started,
                           "from": self.host.whoami, "log": log_dict}
            if shape.get("backfill"):
                act_payload["objects"] = self.recovery_objects()
            try:
                await self.host.send_osd(peer, MOSDPGInfo(act_payload))
            except Exception as e:
                dout("osd", 3, f"deferred activate to osd.{peer} failed: "
                               f"{type(e).__name__} {e}")

    async def _backfill_from(self, auth_osd: int, auth_entries, auth_head,
                             auth_tail) -> None:
        """Full-resync path for a primary behind the auth peer's log tail:
        adopt the auth log wholesale, pull every object the auth holds,
        delete local strays (the reference falls through to backfill when
        `entries_since` cannot bridge the gap, PGLog.h:1254)."""
        fut = asyncio.get_running_loop().create_future()
        self._peer_waiters[auth_osd] = fut
        try:
            await self.host.send_osd(auth_osd, MOSDPGQuery(
                {"pgid": [self.pgid.pool, self.pgid.ps],
                 "from": self.host.whoami,
                 "epoch": self.host.osdmap.epoch, "want": "objects"}))
            reply = await asyncio.wait_for(fut, PEER_TIMEOUT)
        except asyncio.TimeoutError:
            raise PeerSilent(f"auth peer {auth_osd} silent during backfill")
        finally:
            self._peer_waiters.pop(auth_osd, None)
        if "objects" not in reply:
            # a stale reply from an earlier peering round can resolve this
            # waiter (handle_log matches on peer, not round); treating it
            # as an empty object set would delete every local object
            raise PeerSilent(
                f"auth peer {auth_osd} answered backfill query without "
                f"an object list (stale reply)")
        auth_objects = set(reply["objects"])
        for oid in sorted(auth_objects):
            await self.backend.pull_object(auth_osd, oid, None)
        for oid in self.recovery_objects():
            if oid not in auth_objects:
                self._purge_stray(oid)
        new_log = PGLog()
        new_log.entries = list(auth_entries)
        new_log.head, new_log.tail = auth_head, auth_tail
        new_log._rebuild_reqids()
        self.log = new_log
        self.seq = max(self.seq, auth_head[1])

    async def pull_transport(self, peer: int, oid: str) -> None:
        """Fetch one object's state from `peer` (replicated pull; the EC
        backend reconstructs instead — see ECBackend.pull_object)."""
        key = f"pull:{oid}"
        fut = asyncio.get_running_loop().create_future()
        self._push_waiters[key] = fut
        try:
            await self.host.send_osd(peer, MOSDPGPush(
                {"pgid": [self.pgid.pool, self.pgid.ps], "op": "pull",
                 "from": self.host.whoami, "oid": oid}))
            await asyncio.wait_for(fut, PEER_TIMEOUT)
        finally:
            self._push_waiters.pop(key, None)

    async def send_push(self, peer: int, oid: str, data: bytes,
                        attrs: dict | None, delete: bool,
                        omap: dict | None = None,
                        snap_state: dict | None = None,
                        snap: int | None = None,
                        ss_blob: str | None = None) -> None:
        payload = {"pgid": [self.pgid.pool, self.pgid.ps], "op": "push",
                   "from": self.host.whoami, "oid": oid, "delete": delete}
        if attrs:
            payload["attrs"] = {k: v.decode("latin1")
                                for k, v in attrs.items()}
        if omap is not None:
            payload["omap"] = {k: v.decode("latin1")
                               for k, v in omap.items()}
        if snap_state is not None:
            payload["snap_state"] = snap_state
        if snap is not None:        # EC: this push carries a CLONE chunk
            payload["snap"] = snap
        if ss_blob is not None:     # EC: replicate the SnapSet/snapdir
            payload["ss"] = ss_blob
        if data:
            # recovery-bandwidth observability: the failure-storm bench
            # derives recovery MB/s from this counter's delta
            self.host.perf.inc("recovery_bytes_pushed", len(data))
        await self.host.send_osd(peer, MOSDPGPush(payload, data))

    # -- peering message handlers (both roles) -------------------------------

    async def handle_query(self, conn, msg: MOSDPGQuery) -> None:
        """A primary wants our info + log (GetInfo+GetLog combined);
        `want: objects` additionally returns the collection listing (the
        backfill scan)."""
        payload = {"pgid": [self.pgid.pool, self.pgid.ps],
                   "from": self.host.whoami, "info": self.info(),
                   "entries": [e.to_dict() for e in self.log.entries]}
        if msg.payload.get("want") == "objects":
            payload["objects"] = self.recovery_objects()
        conn.send_message(MOSDPGLog(payload))

    def handle_log(self, msg: MOSDPGLog) -> None:
        peer = msg.payload["from"]
        fut = self._peer_waiters.get(peer)
        if fut is not None and not fut.done():
            fut.set_result(msg.payload)

    async def handle_push(self, conn, msg: MOSDPGPush) -> None:
        p = msg.payload
        if p["op"] == "pull":
            # serve the object back to the puller
            oid = p["oid"]
            snap_state = self.backend.snap_state_for_push(oid)
            if self.backend.local_exists(oid):
                data, attrs = self.backend.read_for_push(oid)
                omap = self.backend.omap_for_push(oid)
                payload = {"pgid": p["pgid"], "op": "push",
                           "from": self.host.whoami, "oid": oid,
                           "delete": False,
                           "attrs": {k: v.decode("latin1")
                                     for k, v in attrs.items()},
                           "omap": {k: v.decode("latin1")
                                    for k, v in omap.items()},
                           "reply_to": "pull"}
            else:
                payload = {"pgid": p["pgid"], "op": "push",
                           "from": self.host.whoami, "oid": oid,
                           "delete": True, "reply_to": "pull"}
                data = b""
            if snap_state is not None:
                payload["snap_state"] = snap_state
            conn.send_message(MOSDPGPush(payload, data))
            return
        # incoming object state
        attrs = {k: v.encode("latin1")
                 for k, v in p.get("attrs", {}).items()}
        omap = ({k: v.encode("latin1") for k, v in p["omap"].items()}
                if "omap" in p else None)
        self.backend.apply_push(p["oid"], msg.data, attrs, p["delete"],
                                omap=omap, snap_state=p.get("snap_state"),
                                snap=p.get("snap"), ss_blob=p.get("ss"))
        if p.get("snap") is None and p.get("ss") is None:
            # only the HEAD push resolves the missing record: clone/
            # snapdir pushes are auxiliary state for the same object
            self.log.mark_recovered(p["oid"])
        if p.get("reply_to") == "pull":
            fut = self._push_waiters.get(f"pull:{p['oid']}")
            if fut is not None and not fut.done():
                fut.set_result(None)
        else:
            conn.send_message(MOSDPGPushReply(
                {"pgid": p["pgid"], "oid": p["oid"],
                 "from": self.host.whoami}))

    # -- snaptrim (primary background task) ----------------------------------

    def maybe_snaptrim(self) -> None:
        """Start trimming snaps the monitor has removed (pool
        removed_snaps vs our purged set) — called on activation and on
        every map advance that updates the pool record."""
        if not self.is_primary() or self.state != "active":
            return
        todo = set(getattr(self.pool, "removed_snaps", ())) \
            - self.purged_snaps
        if not todo:
            return
        if self._snaptrim_task is not None and \
                not self._snaptrim_task.done():
            return
        self._snaptrim_task = asyncio.get_running_loop().create_task(
            self._snaptrim(sorted(todo)))

    async def _snaptrim(self, snapids: list[int]) -> None:
        from ceph_tpu.osd import snaps as snapmod
        try:
            for snapid in snapids:
                names = snapmod.snapmapper_objects(
                    self.host.store, self.backend.coll(), self._meta_gh(),
                    snapid)
                for oid in names:
                    # each trim rides the op queue under the DECLARED
                    # snaptrim background class (profile.py): dmclock
                    # paces snap GC against client I/O, its reservation
                    # keeps it moving. obj=oid serializes against
                    # client ops touching the clone being trimmed; the
                    # done-future carries the trim's exception out so
                    # the retry-on-next-map-advance path still sees it
                    done = asyncio.get_running_loop().create_future()

                    async def work(oid=oid, snapid=snapid, done=done):
                        try:
                            await self._do_modify(
                                "snaptrim", oid,
                                {"oid": oid, "snapid": snapid}, b"")
                        except BaseException as e:
                            if not done.done():
                                done.set_exception(e)
                            if isinstance(e, asyncio.CancelledError):
                                raise
                        else:
                            if not done.done():
                                done.set_result(None)

                    if self.host.op_queue.enqueue(
                            (self.pgid.pool, self.pgid.ps), work,
                            klass="snaptrim", obj=oid,
                            nbytes=self.host.op_queue.sched
                            .cost_per_io_bytes):
                        await done
                    else:
                        await self._do_modify(
                            "snaptrim", oid,
                            {"oid": oid, "snapid": snapid}, b"")
                    await asyncio.sleep(0)     # yield between objects
                self.purged_snaps.add(snapid)
                self.persist_meta()
                dout("osd", 3, f"pg {self.pgid} snaptrim {snapid}: "
                               f"{len(names)} objects")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            dout("osd", 2, f"pg {self.pgid} snaptrim failed: "
                           f"{type(e).__name__} {e} (retried on next "
                           f"map advance)")
        else:
            # a snap removed WHILE this batch ran would otherwise wait
            # for an unrelated future epoch: re-check before parking
            self._snaptrim_task = None
            self.maybe_snaptrim()

    # -- scrub ---------------------------------------------------------------

    async def block_writes(self, timeout: float = 10.0) -> None:
        self._write_gate.clear()
        if self._active_writes:
            self._writes_drained.clear()
            try:
                await asyncio.wait_for(self._writes_drained.wait(), timeout)
            except asyncio.TimeoutError:
                dout("scrub", 1, f"pg {self.pgid}: {self._active_writes} "
                                 f"writes still in flight after drain "
                                 f"timeout; scrubbing anyway")

    def unblock_writes(self) -> None:
        self._write_gate.set()

    async def scrub(self, deep: bool = False) -> dict:
        """Primary-driven scrub of this PG (scrub_pg in osd/scrub.py)."""
        from ceph_tpu.osd.scrub import scrub_pg
        return await scrub_pg(self, deep)

    async def handle_scrub_request(self, conn, msg) -> None:
        # Replica side: scan exactly the name range the primary asked
        # for, unpaced — the primary takes the QoS grant per range and
        # holds the write gate while replies are outstanding, so local
        # pacing here would only stretch the gated window.
        from ceph_tpu.osd.scrub import build_scrub_map
        p = msg.payload
        rng = p.get("range")
        conn.send_message(MOSDRepScrubMap(
            {"pgid": p["pgid"], "tid": p["tid"], "from": self.host.whoami,
             "map": await build_scrub_map(
                 self, p.get("deep", False),
                 oid_range=tuple(rng) if rng is not None else None,
                 paced=False)}))

    def handle_scrub_map(self, msg) -> None:
        p = msg.payload
        fut = self._scrub_waiters.get((p["tid"], p["from"]))
        if fut is not None and not fut.done():
            fut.set_result(p["map"])

    def handle_recovering(self, msg: MOSDPGInfo) -> None:
        """Primary says: you are a recovery/backfill target for these
        objects. Persisting the missing set means a failover to THIS
        replica pulls them before going active instead of silently
        serving ENOENT (pg_missing_t persistence)."""
        p = msg.payload
        if p.get("epoch", 0) < self.last_epoch_started:
            # a delayed marker from a PREVIOUS interval's primary must
            # not poison a node that has since re-peered with newer data
            return
        for oid, need in p.get("missing", {}).items():
            self.log.missing[oid] = tuple(need)
        self.persist_meta()

    def handle_activate(self, msg: MOSDPGInfo) -> None:
        """Primary says: adopt this log, you are consistent now."""
        p = msg.payload
        if p.get("epoch", 0) < self.last_epoch_started:
            return      # stale activation from a superseded interval
        if "objects" in p:
            # backfill activation: anything we hold outside the
            # authoritative set is a stray from before our outage
            auth_objects = set(p["objects"])
            for oid in self.recovery_objects():
                if oid not in auth_objects:
                    self._purge_stray(oid)
        auth = PGLog.from_dict(p["log"])
        self.log = auth
        self.log.clear_missing()
        self.seq = max(self.seq, self.log.head[1])
        self.last_epoch_started = p["epoch"]
        self.state = "replica"
        self.persist_meta()
        self._active_event.set()

    # -- client op execution (primary only) ----------------------------------

    # ops that mutate object state and therefore get a log entry —
    # derived from the canonical mutating set (work_queue, which the
    # per-client accountant also classifies by) minus "call": a class
    # method's ENVELOPE is not logged, the mutations it stages
    # server-side get their own entries
    MOD_OPS = WRITE_OP_KINDS - {"call"}
    # the reference rejects omap on EC pools (PrimaryLogPG.cc
    # pool.info.supports_omap()). truncate/zero ride the EC write plan
    # (per-shard truncate sub-ops / zero-fill RMW); snapshots work via
    # per-shard clone/rollback/trim sub-ops with the SnapSet replicated
    # onto every shard's snapdir. User xattrs replicate onto every
    # shard, like the reference.
    EC_UNSUPPORTED = frozenset({"omap_set", "omap_rm", "omap_get",
                                "omap_vals"})

    async def do_op(self, op: dict, data: bytes,
                    conn=None) -> tuple[int, dict, bytes]:
        """Execute one client op; returns (rc, out, outdata) — the
        do_osd_ops dispatch table (src/osd/PrimaryLogPG.cc:5989). Traced
        as the `pg_op` stage of the op's trace (nested under the
        daemon's osd_op span; the EC/store spans nest under this)."""
        if not tracer.active():
            return await self._do_op(op, data, conn)
        # structural span (no stage claim of its own): elided on
        # unsampled traces — osd_op spans the same interval and the
        # EC/store children reparent under it via the live context
        with tracer.span_sampled_only("pg_op",
                                      f"osd.{self.host.whoami}") as sp:
            if sp is not None:      # hot-toggle race: may disable mid-call
                sp.set_tag("pg", f"{self.pgid.pool}.{self.pgid.ps}")
                sp.set_tag("op", op.get("op"))
                sp.set_tag("oid", op.get("oid"))
                sp.set_tag("bytes", len(data))
            rc, out, outdata = await self._do_op(op, data, conn)
            if sp is not None:
                sp.set_tag("rc", rc)
            return rc, out, outdata

    async def _do_op(self, op: dict, data: bytes,
                     conn=None) -> tuple[int, dict, bytes]:
        if not self._active_event.is_set():
            # never BLOCK a queue shard on a peering PG: the daemon parks
            # ops at ingest and re-parks at dequeue; an op that still
            # races an interval flip bounces to the client, which
            # refreshes the map and resends (landing parked)
            from ceph_tpu.osd.backend import IntervalChange
            raise IntervalChange(f"pg {self.pgid} not active ({self.state})")
        mark_op_event("started")
        oid = op["oid"]
        kind = op["op"]
        if self.pool.type == "erasure" and kind in self.EC_UNSUPPORTED:
            return -95, {"error": f"EOPNOTSUPP: {kind} on an ec pool"}, b""

        if kind in self.MOD_OPS:
            return await self._do_modify(kind, oid, op, data)

        snapid = op.get("snapid")
        if snapid is not None and kind in ("read", "stat"):
            return await self._do_snap_read(kind, oid, op, snapid)

        if kind == "read":
            try:
                out = await self.backend.execute_read(
                    oid, op.get("off", 0), op.get("len", 0))
            except StoreError as e:
                return self._store_rc(e), {"error": str(e)}, b""
            return 0, {}, out
        if kind == "stat":
            try:
                size = await self.backend.execute_stat(oid)
            except StoreError as e:
                return self._store_rc(e), {"error": str(e)}, b""
            return 0, {"size": size}, b""
        if kind == "list_snaps":
            from ceph_tpu.osd import snaps
            if self.pool.type == "erasure":
                ss = await self.backend.gather_snapset(oid)
                head_exists = await self.backend.object_exists(oid)
            else:
                ss = snaps.load_snapset(self.host.store,
                                        self.backend.coll(),
                                        self.backend.ghobject(oid))
                head_exists = self.backend.local_exists(oid)
            if ss is None and not head_exists:
                return -2, {"error": "ENOENT"}, b""
            return 0, {"seq": ss.seq if ss else 0,
                       "clones": list(ss.clones) if ss else [],
                       "head_exists": head_exists}, b""
        if kind == "getxattr":
            if not await self.backend.object_exists(oid):
                return -2, {"error": "ENOENT"}, b""
            try:
                val = self.host.store.getattr(
                    self.backend.coll(), self.backend.ghobject(oid),
                    "u:" + op["name"])
            except StoreError as e:
                # only a MISSING LOCAL CHUNK falls back to the shard
                # gather: an ENODATA from a healthy chunk is already
                # authoritative (attrs replicate to every shard) and
                # must not cost a cluster round trip per negative probe
                if self.pool.type == "erasure" and e.code == "ENOENT":
                    try:
                        uattrs = await self._ec_gather_uattrs(oid)
                    except StoreError as ge:
                        if ge.code == "ENOENT":
                            return -2, {"error": str(ge)}, b""
                        return -5, {"error": f"EIO: {ge}"}, b""
                    if op["name"] in uattrs:
                        return 0, {}, uattrs[op["name"]].encode("latin1")
                return -61, {"error": f"ENODATA: xattr {op['name']!r}"}, b""
            return 0, {}, val
        if kind == "getxattrs":
            try:
                attrs = self.host.store.getattrs(
                    self.backend.coll(), self.backend.ghobject(oid))
                xattrs = {k[2:]: v.decode("latin1")
                          for k, v in attrs.items()
                          if k.startswith("u:")}
            except StoreError as e:
                if self.pool.type == "erasure" and e.code == "ENOENT":
                    try:
                        return 0, {"xattrs":
                                   await self._ec_gather_uattrs(oid)}, b""
                    except StoreError as ge:
                        if ge.code == "ENOENT":
                            return -2, {"error": str(ge)}, b""
                        return -5, {"error": f"EIO: {ge}"}, b""
                return self._store_rc(e), {"error": str(e)}, b""
            return 0, {"xattrs": xattrs}, b""
        if kind == "omap_get":
            try:
                omap = self.host.store.omap_get(
                    self.backend.coll(), self.backend.ghobject(oid))
            except StoreError as e:
                return self._store_rc(e), {"error": str(e)}, b""
            return 0, {"omap": {k: v.decode("latin1")
                                for k, v in omap.items()}}, b""
        if kind == "omap_vals":
            try:
                omap = self.host.store.omap_get_values(
                    self.backend.coll(), self.backend.ghobject(oid),
                    op.get("keys", []))
            except StoreError as e:
                return self._store_rc(e), {"error": str(e)}, b""
            return 0, {"omap": {k: v.decode("latin1")
                                for k, v in omap.items()}}, b""
        if kind == "call":
            return await self._do_call(oid, op, data)
        if kind in ("watch", "unwatch", "notify", "list_watchers"):
            return await self._do_watch_op(kind, oid, op, data, conn)
        if kind == "list":
            return 0, {"objects": self.list_objects()}, b""
        return -22, {"error": f"unknown op {kind!r}"}, b""

    # -- watch/notify (primary, src/osd/Watch.h + PrimaryLogPG
    # do_osd_ops WATCH/NOTIFY/NOTIFY_ACK; divergence: watcher state is
    # in-memory on the primary — clients linger-re-register across
    # primary changes instead of the reference's persisted obc watchers)

    async def _do_watch_op(self, kind: str, oid: str, op: dict,
                           data: bytes, conn) -> tuple[int, dict, bytes]:
        from ceph_tpu.msg.messages import MWatchNotify
        if kind == "watch":
            if not await self.backend.object_exists(oid):
                return -2, {"error": "ENOENT"}, b""
            if conn is None:
                return -22, {"error": "watch needs a connection"}, b""
            self.watchers.setdefault(oid, {})[int(op["cookie"])] = {
                "conn": conn, "peer": getattr(conn, "peer_addr", None)}
            return 0, {}, b""
        if kind == "unwatch":
            ws = self.watchers.get(oid, {})
            ws.pop(int(op["cookie"]), None)
            self._abandon_watcher(int(op["cookie"]))
            if not ws:
                self.watchers.pop(oid, None)
            return 0, {}, b""
        if kind == "list_watchers":
            ws = self.watchers.get(oid, {})
            return 0, {"watchers": [
                {"cookie": c, "peer": list(w["peer"]) if w["peer"]
                 else None} for c, w in sorted(ws.items())]}, b""
        # notify: fan out to every live watcher, gather acks until all
        # answer or the (bounded) timeout passes; dead connections are
        # dropped immediately rather than waited out
        self._notify_seq += 1
        notify_id = self._notify_seq
        ws = self.watchers.get(oid, {})
        stale = [c for c, w in ws.items() if w["conn"]._closed]
        for c in stale:
            ws.pop(c, None)
        pending = set(ws)
        if not pending:
            return 0, {"notify_id": notify_id, "acks": [],
                       "timeouts": []}, b""
        fut = asyncio.get_running_loop().create_future()
        st = {"pending": pending, "acks": [], "dead": [], "fut": fut}
        self._notifies[notify_id] = st
        try:
            for cookie, w in list(ws.items()):
                w["conn"].send_message(MWatchNotify(
                    {"oid": oid, "notify_id": notify_id,
                     "cookie": cookie,
                     "pgid": [self.pgid.pool, self.pgid.ps]}, data))
            timeout = min(float(op.get("timeout", 3.0)), 30.0)
            try:
                await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                pass
            return 0, {"notify_id": notify_id, "acks": st["acks"],
                       "timeouts": sorted(set(st["pending"])
                                          | set(st["dead"]))}, b""
        finally:
            self._notifies.pop(notify_id, None)

    def handle_notify_ack(self, msg) -> None:
        """MWatchNotifyAck from a watcher (arrives on its own
        connection, outside the op queue)."""
        p = msg.payload
        n = self._notifies.get(int(p["notify_id"]))
        if n is None:
            return
        cookie = int(p["cookie"])
        if cookie in n["pending"]:
            n["pending"].discard(cookie)
            n["acks"].append([cookie, msg.data.decode("latin1")])
            if not n["pending"] and not n["fut"].done():
                n["fut"].set_result(None)

    def _abandon_watcher(self, cookie: int) -> None:
        """A watcher died or unwatched: any in-flight notify gather must
        stop waiting for it NOW, not at its timeout."""
        for st in self._notifies.values():
            if cookie in st["pending"]:
                st["pending"].discard(cookie)
                st["dead"].append(cookie)
                if not st["pending"] and not st["fut"].done():
                    st["fut"].set_result(None)

    def drop_watchers_for_conn(self, conn) -> None:
        """Connection reset: its watches die with it (the reference's
        watch timeout/disconnect handling)."""
        for oid in list(self.watchers):
            ws = self.watchers[oid]
            for cookie in [c for c, w in ws.items() if w["conn"] is conn]:
                ws.pop(cookie, None)
                self._abandon_watcher(cookie)
            if not ws:
                self.watchers.pop(oid, None)

    async def _do_call(self, oid: str, op: dict,
                       data: bytes) -> tuple[int, dict, bytes]:
        """CEPH_OSD_OP_CALL: run a registered object-class method on the
        primary; its staged mutations apply atomically through the
        normal modify path (PrimaryLogPG do_osd_ops CALL dispatch ->
        ClassHandler)."""
        from ceph_tpu.cls import ClassCallError, ClassHandler, MethodContext
        from ceph_tpu.cls.registry import CLS_METHOD_WR
        if not isinstance(data, (bytes, bytearray)):
            # the registry contract hands cls methods BYTES indata
            # (they json.loads it); zero-copy rx delivers a memoryview,
            # and cls inputs are small control blobs — materialize
            data = bytes(data)
        if op.get("reqid"):
            # a retried CALL whose first execution committed must not
            # re-run the method against post-commit state: its first
            # staged mutation always carries sub-reqid [.., 100]
            done_ver = self.log.lookup_reqid((*op["reqid"], 100))
            if done_ver is not None:
                return 0, {"version": list(done_ver), "dup": True}, b""
        try:
            m = ClassHandler.resolve(op.get("cls", ""), op.get("method", ""))
        except ClassCallError as e:
            return e.rc, {"error": str(e)}, b""
        ctx = MethodContext(self, oid)
        try:
            out = await m.fn(ctx, data)
        except ClassCallError as e:
            return e.rc, {"error": str(e)}, b""
        if not ctx.has_writes:
            return 0, {}, out or b""
        if not (m.flags & CLS_METHOD_WR):
            return -1, {"error": "EPERM: read-only method staged writes"}, \
                b""
        if self.pool.type == "erasure" and (ctx._staged_xattrs
                                            or ctx._staged_omap):
            return -95, {"error": "EOPNOTSUPP: xattr/omap on ec pool"}, b""
        sub = [0]

        async def apply(kind2: str, extra: dict, data2: bytes) -> dict:
            o = {"oid": oid, **extra}
            if op.get("snapc"):
                # staged cls mutations clone-on-write like plain ops
                o["snapc"] = op["snapc"]
            if op.get("reqid"):
                # distinct dup-index key per staged sub-mutation
                o["reqid"] = [*op["reqid"], 100 + sub[0]]
            sub[0] += 1
            rc2, out2, _ = await self._do_modify(kind2, oid, o, data2)
            if rc2 < 0:
                raise ClassCallError(rc2, str(out2))
            return out2
        try:
            last = {}
            if ctx.staged is not None:
                if ctx.staged[0] == "delete":
                    last = await apply("delete", {}, b"")
                else:
                    last = await apply("write_full", {}, ctx.staged[1])
            for name, value in ctx._staged_xattrs.items():
                last = await apply("setxattr", {"name": name}, value)
            if ctx._staged_omap:
                last = await apply(
                    "omap_set",
                    {"kv": {k: v.decode("latin1")
                            for k, v in ctx._staged_omap.items()}}, b"")
        except ClassCallError as e:
            return e.rc, {"error": str(e)}, b""
        return 0, last, out or b""

    async def _ec_gather_uattrs(self, oid: str) -> dict:
        """User xattrs from any live shard (the degraded-primary path:
        the local chunk is gone but >= k shards still exist). Raises
        StoreError on gather failure — a transient EIO must surface as
        EIO, never masquerade as "attr does not exist"."""
        _, _, meta = await self.backend._gather_chunks(
            oid, chunk_off=0, chunk_len=0)
        return meta.get("uattrs", {})

    async def _do_snap_read(self, kind: str, oid: str, op: dict,
                            snapid: int) -> tuple[int, dict, bytes]:
        """Snap-directed read/stat (find_object_context: head, covering
        clone, or ENOENT when the object did not exist at that snap).
        On EC pools the clone is striped like the head: resolution uses
        the replicated snapdir, the data comes from a clone-chunk
        gather + decode."""
        from ceph_tpu.osd import snaps
        store, cid = self.host.store, self.backend.coll()
        head = self.backend.ghobject(oid)
        if self.pool.type == "erasure":
            ss = await self.backend.gather_snapset(oid)
            if ss is not None and snapid <= ss.seq:
                # clone resolution never consults head existence: skip
                # that gather (it costs a cluster round trip when the
                # primary's local chunk is missing)
                head_exists = False
            else:
                head_exists = await self.backend.object_exists(oid)
            src = snaps.resolve_read(ss, snapid, head_exists)
            if src is None:
                return -2, {"error": f"ENOENT at snap {snapid}"}, b""
            off, ln = op.get("off", 0), op.get("len", 0)
            snap = None if src == "head" else src
            try:
                if kind == "stat":
                    return 0, {"size": await self.backend.execute_stat(
                        oid, snap=snap)}, b""
                return 0, {}, await self.backend.execute_read(
                    oid, off, ln, snap=snap)
            except StoreError as e:
                return self._store_rc(e), {"error": str(e)}, b""
        ss = snaps.load_snapset(store, cid, head)
        src = snaps.resolve_read(ss, snapid, store.exists(cid, head))
        if src is None:
            return -2, {"error": f"ENOENT at snap {snapid}"}, b""
        gh = head if src == "head" else snaps.clone_gh(head, src)
        try:
            if kind == "stat":
                return 0, {"size": store.stat(cid, gh)["size"]}, b""
            data = store.read(cid, gh)
        except StoreError as e:
            return self._store_rc(e), {"error": str(e)}, b""
        off, ln = op.get("off", 0), op.get("len", 0)
        return 0, {}, data[off:off + ln] if ln > 0 else data[off:]

    @staticmethod
    def _store_rc(e: StoreError) -> int:
        return -2 if e.code == "ENOENT" else -5

    async def _do_modify(self, kind: str, oid: str, op: dict,
                         data: bytes) -> tuple[int, dict, bytes]:
        reqid = tuple(op["reqid"]) if op.get("reqid") else None
        if reqid is not None:
            done_ver = self.log.lookup_reqid(reqid)
            if done_ver is not None and \
                    await self.backend.verify_dup_committed(oid,
                                                            done_ver):
                # client retry of an op that already committed (its reply
                # was lost in a failover): answer from the log instead of
                # re-executing — appends would double-apply, deletes
                # would answer ENOENT for a success (PrimaryLogPG dup-op
                # check via the pg log's reqid index). An unverifiable
                # EC dup (entry logged, shards never applied) falls
                # through and re-executes at a fresh version.
                return 0, {"version": list(done_ver), "dup": True}, b""
        deadline = asyncio.get_running_loop().time() + 30.0
        while True:
            if self._write_gate.is_set():
                # fast path first: the open-gate case (every write
                # outside a scrub drain) pays NO await — wait_for spun
                # up a task + timer per modify (profiled on the
                # pipelined hot path). The is_set check + increment run
                # in one resume slice (no await between), so
                # block_writes cannot observe a zero counter while this
                # write proceeds (TOCTOU)
                self._active_writes += 1
                break
            await asyncio.wait_for(
                self._write_gate.wait(),
                max(0.1, deadline - asyncio.get_running_loop().time()))
        try:
            return await self._do_modify_inner(kind, oid, op, data)
        finally:
            self._active_writes -= 1
            if self._active_writes == 0:
                self._writes_drained.set()

    async def _do_modify_inner(self, kind: str, oid: str, op: dict,
                               data: bytes) -> tuple[int, dict, bytes]:
        if oid in self._pending_recovery or oid in self._recovery_inflight:
            # degraded object: an extent write to a peer missing the
            # base would splice into zeros — recover it everywhere
            # first (the reference's wait_for_degraded_object)
            await self.recover_object_now(oid)
        if kind == "create":
            exists = await self.backend.object_exists(oid)
            if exists:
                if op.get("exclusive"):
                    return -17, {"error": "EEXIST"}, b""
                return 0, {}, b""
            if self.pool.type == "erasure":
                kind, data = "write_full", b""
        elif kind in ("delete", "rmxattr", "omap_rm", "truncate", "zero"):
            # mutations of an object's EXISTING state require the object
            # (the reference returns ENOENT; setxattr/omap_set create)
            if not await self.backend.object_exists(oid):
                return -2, {"error": "ENOENT"}, b""
        if kind == "rollback":
            from ceph_tpu.osd import snaps as snapmod
            head = self.backend.ghobject(oid)
            if self.pool.type == "erasure":
                ss = await self.backend.gather_snapset(oid)
                head_exists = await self.backend.object_exists(oid)
            else:
                ss = snapmod.load_snapset(self.host.store,
                                          self.backend.coll(), head)
                head_exists = self.backend.local_exists(oid)
            if snapmod.resolve_read(ss, op["snapid"],
                                    head_exists) is None:
                return -2, {"error": f"ENOENT at snap {op['snapid']}"}, b""
            data = str(op["snapid"]).encode()
        elif kind == "snaptrim":
            data = str(op["snapid"]).encode()
        # make_writeable (PrimaryLogPG.cc): the first mutation after new
        # snaps appear in the client's SnapContext preserves the current
        # state as a clone, via its own logged+replicated op
        snapc = op.get("snapc")
        if snapc and snapc.get("snaps") and kind != "snaptrim":
            await self._make_writeable(oid, snapc, op.get("reqid"))
        if kind == "zero":
            # re-executed on replicas: the length rides the data segment
            data = str(op.get("len", 0)).encode()
        elif kind == "truncate" and op.get("size") is not None:
            op = dict(op, off=op["size"])
        elif kind == "setxattr":
            data = json.dumps({"name": op["name"],
                               "value": bytes(data).decode("latin1")
                               }).encode()
        elif kind == "rmxattr":
            data = op["name"].encode()
        elif kind == "omap_set":
            data = json.dumps(op["kv"]).encode()
        elif kind == "omap_rm":
            data = json.dumps(op["keys"]).encode()
        # the commit section: the object's write-ordering lock (FIFO —
        # same-object ops commit in arrival order; pipelined ops to
        # OTHER objects proceed concurrently) held across the ordered
        # slice AND the execution slice, so log intent and local apply
        # can never interleave with another writer of this object
        async with self.backend.obj_lock(oid):
            version, entry = self._log_intent(kind, oid, op)
            try:
                if interleave.armed():
                    # schedule explorer: widen the gap between the
                    # ordered slice and the execution slice, where
                    # pipelined same-PG ops genuinely overlap
                    await interleave.yield_point("pg_execute")
                await self.backend.execute_write(oid, kind, data, entry,
                                                 off=op.get("off", 0))
            finally:
                # completions land in ANY order under pipelining (a
                # failed execution settles too — peering owns its
                # entry's fate); last_complete advances contiguously
                self.log.mark_complete(version)
        return 0, {"version": list(version)}, b""

    def _log_intent(self, kind: str, oid: str,
                    op: dict) -> tuple[Eversion, LogEntry]:
        """The ordered synchronous slice of a modify: version
        allocation, log-intent append, dup-index stamp, and the durable
        meta persist run in ONE event-loop slice (no await), so
        concurrent pipelined ops can never interleave inside it —
        appends stay strictly monotonic per PG and a retry of an op
        that failed anywhere past this point hits the dup index instead
        of re-executing against partially-applied state. The EC backend
        verifies a dup hit is actually readable before answering it
        (see verify_dup_committed) since its entry can be logged while
        no shard applied. The entry starts INCOMPLETE: the pipelined
        execution slice settles it via log.mark_complete, in any
        order."""
        version = self.next_version()
        entry = LogEntry(version=version,
                         op="delete" if kind == "delete" else "modify",
                         oid=oid, prior_version=self._prior(oid),
                         reqid=tuple(op["reqid"]) if op.get("reqid")
                         else None)
        self.log.append(entry, complete=False)
        self.persist_meta()
        return version, entry

    async def _make_writeable(self, oid: str, snapc: dict,
                              reqid) -> None:
        from ceph_tpu.osd import snaps as snapmod
        if self.pool.type == "erasure":
            ss = await self.backend.gather_snapset(oid)
        else:
            ss = snapmod.load_snapset(self.host.store, self.backend.coll(),
                                      self.backend.ghobject(oid))
        seq = ss.seq if ss else 0
        new = [s for s in snapc["snaps"] if s > seq]
        if not new:
            return
        head_exists = await self.backend.object_exists(oid)
        payload = json.dumps({"cloneid": max(new), "snaps": sorted(new),
                              "seq_only": not head_exists}).encode()
        async with self.backend.obj_lock(oid):
            entry = LogEntry(version=self.next_version(), op="modify",
                             oid=oid, prior_version=self._prior(oid),
                             reqid=(*reqid, 90) if reqid else None)
            self.log.append(entry, complete=False)
            self.persist_meta()
            try:
                await self.backend.execute_write(oid, "clone", payload,
                                                 entry)
            finally:
                self.log.mark_complete(entry.version)

    def _prior(self, oid: str) -> Eversion:
        # O(1) via the log's per-object index — the reverse entry scan
        # ran once per write and dominated the ordered slice at a full
        # 1000-entry window (profiled under the pipelined hot path)
        return self.log.last_version_of(oid)
