"""ECBackend: the erasure-coded PGBackend — the TPU codec's production
caller.

Re-creation of the reference EC write/read pipeline
(src/osd/ECBackend.cc, src/osd/ECCommon.cc):
  * writes stripe-encode the object through the pool's EC plugin and fan
    per-shard sub-writes to the acting set's positions, acking the
    client only when ALL live shards commit (ECCommon.cc:704 start_rmw,
    :789 try_reads_to_commit; sub-write apply ECBackend.cc:936);
  * reads gather any k shards — degraded reads reconstruct missing
    chunks via the plugin decode (ReadPipeline, ECCommon.cc:597
    objects_read_and_reconstruct, minimum_to_decode :281);
  * per-shard chunk crc32c rides an object attr and is verified when a
    shard is served (HashInfo, src/osd/ECUtil.h:141; verify at read
    ECBackend.cc:1092-1120);
  * recovery reconstructs a lost position's chunk from k survivors and
    pushes it (RecoveryOp, ECBackend.h:191).

Idiomatic divergences: whole-object writes (write_full) instead of the
RMW partial-overwrite pipeline, so no ExtentCache; chunks live in the
PG's collection with their shard index as an attr instead of
shard-suffixed collections (one OSD holds at most one shard of a PG);
encode/decode go through the batched ec_util driver — on a TPU backend
one device dispatch per stripe batch.
"""
from __future__ import annotations

import asyncio
import json

from ceph_tpu.crush.crush import CRUSH_NONE
from ceph_tpu.ec import registry
from ceph_tpu.msg.messages import (MOSDECSubOpRead, MOSDECSubOpReadReply,
                                   MOSDECSubOpWrite, MOSDECSubOpWriteReply)
from ceph_tpu.objectstore.store import StoreError
from ceph_tpu.osd import ec_util
from ceph_tpu.osd.backend import (SUBOP_TIMEOUT, IntervalChange, PGBackend)
from ceph_tpu.osd.pglog import LogEntry
from ceph_tpu.utils.dout import dout

READ_TIMEOUT = 5.0


class ECBackend(PGBackend):
    """Erasure-coded writes/reads over the acting set's shard positions."""

    def __init__(self, pg):
        super().__init__(pg)
        profile = dict(pg.host.osdmap.ec_profiles[pg.pool.ec_profile])
        self.ec_impl = registry.factory(profile.get("plugin", "jerasure"),
                                        profile)
        self.k = self.ec_impl.get_data_chunk_count()
        self.n = self.ec_impl.get_chunk_count()
        width = pg.pool.stripe_width or self.k * 4096
        self.sinfo = ec_util.StripeInfo(self.k, width)
        # read gather plumbing: tid -> future resolving to (payload, data)
        self._read_waiters: dict[int, asyncio.Future] = {}

    # -- helpers -------------------------------------------------------------

    def _live_positions(self) -> dict[int, int]:
        """shard index -> osd id for every non-hole acting position."""
        return {i: o for i, o in enumerate(self.pg.acting)
                if o != CRUSH_NONE and self.host.osdmap.is_up(o)}

    def _pad(self, data: bytes) -> bytes:
        w = self.sinfo.stripe_width
        pad = (-len(data)) % w
        return data + b"\x00" * pad if pad or data else b"\x00" * w

    def _chunk_attrs(self, shard: int, size: int, hinfo: dict,
                     version) -> dict:
        return {"shard": str(shard).encode(),
                "ec_size": str(size).encode(),
                "hinfo": json.dumps(hinfo).encode(),
                "version": json.dumps(list(version)).encode()}

    # -- write path (RMWPipeline-lite) ---------------------------------------

    async def execute_write(self, oid: str, op: str, data: bytes,
                            entry: LogEntry) -> None:
        live = self._live_positions()
        if len(live) < self.pg.pool.min_size:
            # the reference blocks the op until min_size is met; our
            # client resends until the interval heals
            raise IntervalChange(
                f"ec pg {self.pg.pgid}: {len(live)} live shards < "
                f"min_size {self.pg.pool.min_size}")
        tid = self.new_tid()
        peers = {o for o in live.values() if o != self.host.whoami}
        fut = self._start_waiting(tid, peers)

        if op in ("write_full", "push"):
            padded = self._pad(data)
            shards = ec_util.encode(self.sinfo, self.ec_impl, padded)
            hinfo = ec_util.HashInfo(self.n)
            hinfo.append(0, shards)
            hd = hinfo.to_dict()
            payloads = {i: (self._chunk_attrs(i, len(data), hd,
                                              entry.version), shards[i])
                        for i in live}
        elif op in ("delete", "remove"):
            payloads = {i: (None, b"") for i in live}
        else:
            raise StoreError("EINVAL", f"unknown ec op {op!r}")

        failed = []
        for idx, osd in live.items():
            attrs, chunk = payloads[idx]
            if osd == self.host.whoami:
                self._apply_chunk(oid, op, chunk, attrs)
                continue
            try:
                await self.host.send_osd(osd, MOSDECSubOpWrite(
                    {"pgid": [self.pg.pgid.pool, self.pg.pgid.ps],
                     "tid": tid, "from": self.host.whoami, "oid": oid,
                     "op": op, "shard": idx,
                     "attrs": ({k: v.decode("latin1")
                                for k, v in attrs.items()}
                               if attrs else None),
                     "entry": entry.to_dict()}, chunk))
            except Exception as e:
                # an unreachable peer the map hasn't caught up on: the
                # write must NOT be acked with a subset of live shards —
                # a fake ack here lets an acked write become undecodable
                # after m more failures (ADVICE r4). Fail the op; the
                # client retries until heartbeats push the peer out of
                # the acting set (the reference blocks degraded EC writes
                # the same way).
                dout("osd", 3, f"ec sub-write to osd.{osd} failed: "
                               f"{type(e).__name__} {e}")
                failed.append(osd)
        if failed:
            self._inflight.pop(tid, None)
            raise IntervalChange(
                f"ec sub-writes to osds {failed} failed; "
                f"retry next interval")
        await asyncio.wait_for(fut, SUBOP_TIMEOUT)

    def _apply_chunk(self, oid: str, op: str, chunk: bytes,
                     attrs: dict | None) -> None:
        if op in ("write_full", "push"):
            self.local_apply(oid, "push", chunk, attrs=attrs)
        else:
            self.local_apply(oid, "delete", b"")

    # -- read path (ReadPipeline-lite) ---------------------------------------

    async def _gather_chunks(
            self, oid: str,
            exclude_osds: frozenset = frozenset(),
            allow_rollback: bool = False,
    ) -> tuple[dict[int, bytes], int, dict]:
        """Collect shard chunks until a version-consistent decodable set
        exists; returns ({shard: chunk}, logical size, hinfo dict).

        Shards carry the eversion of the write that produced them: mixing
        chunks of two writes would decode garbage (the reference guards
        with HashInfo comparison), so only the newest version holding >= k
        chunks is used. `exclude_osds` keeps a recovery target's own stale
        chunk out of its reconstruction. Raises StoreError ENOENT when no
        shard exists anywhere, EIO when shards exist but no version is
        decodable (transient: peers down/slow — NOT proof of deletion).

        If a NEWER version than the best decodable one was observed, the
        default is EIO (serving the older version would roll back a
        possibly-acked write). Recovery passes `allow_rollback=True`: a
        partial never-acked fan-out must not wedge peering forever, so
        the divergent suffix is rewound to the older consistent version
        (the reference's peering rewinds uncommitted divergent entries
        the same way); meta["rolled_back"] reports it.
        """
        # per observed version: {shard: (chunk, ec_size, hinfo)}
        by_version: dict[tuple, dict[int, tuple]] = {}

        def add(shard: int, data: bytes, size: int, hd: dict, ver) -> None:
            by_version.setdefault(tuple(ver), {})[shard] = (data, size, hd)

        def best() -> tuple | None:
            for ver in sorted(by_version, reverse=True):
                if len(by_version[ver]) >= self.k:
                    return ver
            return None

        if self.host.whoami not in exclude_osds and self.local_exists(oid):
            from ceph_tpu.native import ec_native
            data, attrs = self.read_for_push(oid)
            shard = int(attrs["shard"])
            hd = json.loads(attrs["hinfo"])
            # the coordinator's own chunk gets the same crc gate a remote
            # sub-read would: local bit-rot must not poison the decode
            want_crc = ec_util.HashInfo.from_dict(hd).get_chunk_hash(shard)
            if ec_native.crc32c(data) == want_crc:
                add(shard, data, int(attrs["ec_size"]), hd,
                    json.loads(attrs.get("version", b"[0, 0]")))
            else:
                dout("osd", 1, f"ec local shard {shard} of {oid}: crc "
                               f"mismatch, reconstructing around it")

        # two rounds: ask a minimum set first (k shards total, preferring
        # data positions), top up with the remaining positions only when
        # the first round can't decode — the reference reads exactly
        # minimum_to_decode and falls back to extra shards on miss
        candidates = [(idx, osd)
                      for idx, osd in sorted(self._live_positions().items())
                      if osd != self.host.whoami
                      and osd not in exclude_osds]
        need_first = max(0, self.k - sum(len(v) for v in
                                         by_version.values()))
        rounds = [candidates[:need_first], candidates[need_first:]]
        waits: dict[asyncio.Future, int] = {}
        deadline = asyncio.get_running_loop().time() + READ_TIMEOUT

        async def send_round(batch) -> set:
            futs = set()
            for idx, osd in batch:
                tid = self.new_tid()
                fut = asyncio.get_running_loop().create_future()
                self._read_waiters[tid] = fut
                waits[fut] = tid
                try:
                    await self.host.send_osd(osd, MOSDECSubOpRead(
                        {"pgid": [self.pg.pgid.pool, self.pg.pgid.ps],
                         "tid": tid, "from": self.host.whoami, "oid": oid}))
                    futs.add(fut)
                except Exception as e:
                    # unreachable peer: just a missing chunk, not a failed
                    # read — the top-up round covers it
                    dout("osd", 3, f"ec sub-read to osd.{osd} failed: "
                                   f"{type(e).__name__} {e}")
                    fut.cancel()
            return futs

        try:
            pending = await send_round(rounds[0])
            topped_up = False
            half = deadline - READ_TIMEOUT / 2
            # early exit at k decodable chunks: one slow-but-up shard must
            # not stall every read for the full timeout
            while True:
                now = asyncio.get_running_loop().time()
                # top up when the minimum round can no longer decode on
                # its own: chunks of DIFFERENT versions don't combine, so
                # count the best single version, not the cross-version
                # sum; a half-spent deadline also triggers the top-up
                # (slow peer + stale local chunk could otherwise starve
                # a servable read)
                have_best = max((len(v) for v in by_version.values()),
                                default=0)
                if best() is None and not topped_up and (
                        not pending
                        or len(pending) + have_best < self.k
                        or now > half):
                    pending |= await send_round(rounds[1])
                    topped_up = True
                if not pending or best() is not None:
                    break
                wake = deadline if topped_up else min(deadline, half)
                timeout = wake - asyncio.get_running_loop().time()
                if timeout <= 0:
                    if topped_up:
                        break
                    continue    # hit the half mark: run the top-up branch
                done, pending = await asyncio.wait(
                    pending, timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED)
                for fut in done:
                    payload, data = fut.result()
                    if payload.get("found"):
                        add(payload["shard"], data, payload["ec_size"],
                            payload.get("hinfo") or {},
                            payload.get("version", (0, 0)))
        finally:
            for fut, tid in waits.items():
                fut.cancel()
                self._read_waiters.pop(tid, None)
        ver = best()
        if ver is None:
            if not by_version:
                raise StoreError("ENOENT", f"{oid} has no shards anywhere")
            raise StoreError(
                "EIO", f"{oid}: no version has {self.k} shards "
                f"(saw {({v: sorted(s) for v, s in by_version.items()})})")
        newest = max(by_version)
        rolled_back = False
        if newest > ver:
            # a NEWER committed write exists but is currently undecodable:
            # serving the older decodable version would silently roll back
            # an acked write — answer EIO until recovery restores it
            # (ADVICE r4; the reference's rollforward machinery guarantees
            # the same by never exposing a pre-rollforward state)
            if not allow_rollback:
                raise StoreError(
                    "EIO", f"{oid}: newest version {newest} has only "
                    f"{len(by_version[newest])} of {self.k} shards; "
                    f"refusing to serve older {ver}")
            rolled_back = True
            dout("osd", 1, f"ec {oid}: rolling divergent partial write "
                           f"{newest} ({len(by_version[newest])} shards) "
                           f"back to {ver}")
        shards = by_version[ver]
        got = {shard: data for shard, (data, _, _) in shards.items()}
        any_shard = next(iter(shards.values()))
        return got, any_shard[1], {"hinfo": any_shard[2], "version": ver,
                                   "rolled_back": rolled_back}

    async def execute_read(self, oid: str, offset: int,
                           length: int) -> bytes:
        got, ec_size, _ = await self._gather_chunks(oid)
        data = ec_util.decode_concat(self.sinfo, self.ec_impl, got)[:ec_size]
        if length <= 0:
            return data[offset:]
        return data[offset:offset + length]

    async def object_exists(self, oid: str) -> bool:
        if self.local_exists(oid):
            return True
        try:
            await self._gather_chunks(oid)
            return True
        except StoreError as e:
            # EIO = shards exist but are (transiently) undecodable: the
            # object exists; only authoritative absence is False
            return e.code != "ENOENT"

    async def execute_stat(self, oid: str) -> int:
        if self.local_exists(oid):
            _, attrs = self.read_for_push(oid)
            return int(attrs["ec_size"])
        _, ec_size, _ = await self._gather_chunks(oid)
        return ec_size

    def object_size(self, oid: str) -> int:
        _, attrs = self.read_for_push(oid)
        return int(attrs["ec_size"])

    # -- sub-op handlers (shard side) ----------------------------------------

    async def handle_sub_op(self, conn, msg) -> None:
        p = msg.payload
        if isinstance(msg, MOSDECSubOpWrite):
            attrs = ({k: v.encode("latin1") for k, v in p["attrs"].items()}
                     if p.get("attrs") else None)
            self._apply_chunk(p["oid"], p["op"], msg.data, attrs)
            entry = LogEntry.from_dict(p["entry"])
            if entry.version > self.pg.log.head:
                self.pg.log.append(entry)
            self.pg.log.mark_recovered(p["oid"])
            self.pg.persist_meta()
            conn.send_message(MOSDECSubOpWriteReply(
                {"pgid": p["pgid"], "tid": p["tid"],
                 "from": self.host.whoami}))
            return
        # sub-read: serve our chunk, crc-verified (ECBackend.cc:1092)
        found = self.local_exists(p["oid"])
        payload = {"pgid": p["pgid"], "tid": p["tid"],
                   "from": self.host.whoami, "oid": p["oid"],
                   "found": False, "shard": -1, "ec_size": -1}
        data = b""
        if found:
            from ceph_tpu.native import ec_native
            data, attrs = self.read_for_push(p["oid"])
            shard = int(attrs["shard"])
            hdict = json.loads(attrs["hinfo"])
            hinfo = ec_util.HashInfo.from_dict(hdict)
            have = ec_native.crc32c(data)
            want = hinfo.get_chunk_hash(shard)
            if have != want:
                # a corrupt shard must not poison a decode: answer EIO
                # (not-found) so the reader reconstructs from survivors
                dout("osd", 1, f"ec shard {shard} of {p['oid']}: crc "
                               f"mismatch {have:#x} != {want:#x} (EIO)")
                data = b""
            else:
                payload.update({"found": True, "shard": shard,
                                "ec_size": int(attrs["ec_size"]),
                                "hinfo": hdict,
                                "version": json.loads(
                                    attrs.get("version", b"[0, 0]"))})
        conn.send_message(MOSDECSubOpReadReply(payload, data))

    def handle_sub_op_reply(self, msg) -> None:
        p = msg.payload
        if isinstance(msg, MOSDECSubOpWriteReply):
            self.sub_op_ack(p["tid"], p["from"])
            return
        fut = self._read_waiters.get(p["tid"])
        if fut is not None and not fut.done():
            fut.set_result((p, msg.data))

    # -- recovery (RecoveryOp-lite: reconstruct + push) ----------------------

    async def _rewrite_consistent(self, oid: str, got: dict[int, bytes],
                                  ec_size: int) -> None:
        """Converge every live shard on one consistent state by
        re-asserting the rolled-back content as a fresh full write: a
        divergent partial fan-out leaves SOME shards at the newer
        version, and reconstructing just one position would leave the
        acting set mixed (every later read would EIO)."""
        data = ec_util.decode_concat(self.sinfo, self.ec_impl,
                                     got)[:ec_size]
        version = self.pg.next_version()
        entry = LogEntry(version=version, op="modify", oid=oid,
                         prior_version=self.pg._prior(oid))
        await self.execute_write(oid, "write_full", data, entry)
        self.pg.log.append(entry)
        self.pg.persist_meta()

    async def _reconstruct(self, oid: str, idx: int,
                           exclude: frozenset) -> tuple[bytes, dict] | None:
        """Chunk for position `idx` + its attrs, reconstructed from any k
        survivors (never from the target itself — its copy may be stale).
        None when the acting set was instead converged by a divergence
        rewrite (the caller's push is already done). Transient <k
        availability (EIO with no rollback possible) propagates so
        peering retries instead of recording a deletion."""
        got, ec_size, meta = await self._gather_chunks(
            oid, exclude_osds=exclude, allow_rollback=True)
        if meta["rolled_back"]:
            await self._rewrite_consistent(oid, got, ec_size)
            return None
        if idx in got:
            chunk = got[idx]
        else:
            chunk = ec_util.decode_shards(self.sinfo, self.ec_impl,
                                          got, [idx])[idx]
        return chunk, self._chunk_attrs(idx, ec_size, meta["hinfo"],
                                        meta["version"])

    async def push_object(self, peer: int, oid: str) -> None:
        """Reconstruct `peer`'s positional chunk from k survivors and
        push it (the reference recovery reads min-to-decode and
        re-encodes the missing shard, RecoveryOp ECBackend.h:191)."""
        try:
            idx = self.pg.acting.index(peer)
        except ValueError:
            return
        try:
            rec = await self._reconstruct(oid, idx,
                                          exclude=frozenset([peer]))
        except StoreError as e:
            if e.code != "ENOENT":
                raise
            await self.pg.send_push(peer, oid, b"", None, delete=True)
            return
        if rec is None:
            return      # divergence rewrite already updated every shard
        chunk, attrs = rec
        await self.pg.send_push(peer, oid, chunk, attrs, delete=False)

    async def pull_object(self, auth_peer: int, oid: str, need) -> None:
        """We (the primary) lack this object: reconstruct OUR positional
        chunk from the survivors instead of copying the auth peer's (its
        chunk is a different position)."""
        me = self.pg.acting.index(self.host.whoami)
        try:
            rec = await self._reconstruct(
                oid, me, exclude=frozenset([self.host.whoami]))
        except StoreError as e:
            if e.code != "ENOENT":
                raise
            self.local_apply(oid, "delete", b"")
            return
        if rec is None:
            return      # divergence rewrite already updated every shard
        chunk, attrs = rec
        self.local_apply(oid, "push", chunk, attrs=attrs)
