"""ECBackend: the erasure-coded PGBackend — the TPU codec's production
caller.

Re-creation of the reference EC write/read pipeline
(src/osd/ECBackend.cc, src/osd/ECCommon.cc, src/osd/ECTransaction.cc):
  * writes are PLANNED (ECTransaction::get_write_plan,
    src/osd/ECTransaction.h:34): the touched logical range is
    stripe-aligned, missing stripe fragments are read back from shards
    (the RMW pipeline, ECCommon.cc:704 start_rmw / :715
    try_state_to_reads), only the affected stripes are re-encoded — in
    ONE batched device dispatch — and per-shard extent sub-writes fan
    out to the acting set (ECCommon.cc:890-921); append and ranged
    overwrite are first-class (ECTransaction.cc:498-535 stripe-aligned
    zero-padding);
  * reads fetch ONLY the chunk extents of touched stripes
    (ECCommon.cc:281 get_min_avail_to_read_shards, :503
    get_want_to_read_shards); degraded reads reconstruct missing chunks
    from any k survivors via the plugin decode;
  * shard integrity rides a per-chunk crc32c list in an object attr,
    verified shard-side whenever a chunk is served — the analog of the
    reference's BlueStore Checksummer protection that ec_overwrites
    pools rely on (src/os/bluestore/Checksummer.h; the append-only
    HashInfo of src/osd/ECUtil.h:141 survives in ec_util for the tools
    layer, but a cumulative hash cannot absorb partial overwrites);
  * recovery reconstructs a lost position's chunk from k survivors and
    pushes it (RecoveryOp, ECBackend.h:191).

Idiomatic divergences: chunks live in the PG's collection with their
shard index as an attr instead of shard-suffixed collections (one OSD
holds at most one shard of a PG); no ExtentCache — the RMW read leans
on the batched gather instead; encode/decode go through the batched
ec_util driver — on a TPU backend one device dispatch per stripe batch.
"""
from __future__ import annotations

import asyncio
import json
import time

from ceph_tpu.crush.crush import CRUSH_NONE
from ceph_tpu.ec import registry
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.offload import get_service_or_none
from ceph_tpu.qa import faultinject
from ceph_tpu.msg.messages import (MOSDECSubOpRead, MOSDECSubOpReadReply,
                                   MOSDECSubOpWrite, MOSDECSubOpWriteReply)
from ceph_tpu.objectstore.store import StoreError
from ceph_tpu.osd import ec_util
from ceph_tpu.osd.backend import (SUBOP_TIMEOUT, IntervalChange, PGBackend)
from ceph_tpu.osd.pglog import LogEntry
from ceph_tpu.utils import tracer
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.work_queue import mark_op_event

READ_TIMEOUT = 5.0

# shard-side rollback generation: before every sub-write apply, the
# current shard state is cloned to <oid>+PREV_SUFFIX. A divergent chain
# of partial fan-outs can otherwise fragment shard versions until NO
# version holds k chunks — with in-place overwrites the old consistent
# stripes would be gone for good (the reference keeps rollback extents
# in ECTransaction / rolls forward via ECDummyOp for the same reason;
# found by the thrashing model checker).
PREV_SUFFIX = "\x00prev"


class ECBackend(PGBackend):
    """Erasure-coded writes/reads over the acting set's shard positions."""

    def __init__(self, pg):
        super().__init__(pg)
        profile = dict(pg.host.osdmap.ec_profiles[pg.pool.ec_profile])
        self.ec_impl = registry.factory(profile.get("plugin", "jerasure"),
                                        profile)
        self.k = self.ec_impl.get_data_chunk_count()
        self.n = self.ec_impl.get_chunk_count()
        width = pg.pool.stripe_width or self.k * 4096
        self.sinfo = ec_util.StripeInfo(self.k, width)
        from ceph_tpu.native import ec_native
        self._crc32c = ec_native.crc32c
        # the per-chunk shard csum engine (BlueStore Checksummer analog);
        # its async path submits through the offload service. None when
        # the chunk size isn't a power of two (bitmatrix techniques pad
        # to w*64, e.g. liberation's 4480): Checksummer enforces the
        # reference's pow2 csum_block_size, and those pools take the
        # native sync path anyway
        from ceph_tpu.utils.checksummer import Checksummer
        c = self.sinfo.chunk_size
        self._checksummer = Checksummer("crc32c", c) \
            if c & (c - 1) == 0 else None
        # crc of an all-zero chunk: hole stripes materialize as zeros
        self._zcrc = self._crc32c(b"\x00" * self.sinfo.chunk_size)
        # read gather plumbing: tid -> future resolving to (payload, data)
        self._read_waiters: dict[int, asyncio.Future] = {}
        # per-object write ordering lives in PGBackend._obj_locks now
        # (obj_lock): the PG's modify path holds it across log intent +
        # this backend's RMW/fan-out, and the replicated backend shares
        # the same discipline under pipelined execution
        # observability: extent bytes served to sub-reads (tests assert
        # ranged reads move << object size)
        self.sub_read_bytes_served = 0
        # repair-bandwidth accounting (the failure-storm bench's
        # repair-bytes ratio): actual bytes fetched by recovery
        # reconstruction gathers vs what a full-stripe gather (k whole
        # chunks) would have moved for the same repairs
        self.repair_bytes_fetched = 0
        self.repair_bytes_full = 0

    # -- helpers -------------------------------------------------------------

    def _live_positions(self) -> dict[int, int]:
        """shard index -> osd id for every non-hole acting position."""
        return {i: o for i, o in enumerate(self.pg.acting)
                if o != CRUSH_NONE and self.host.osdmap.is_up(o)}


    def _pad(self, data: bytes) -> bytes:
        w = self.sinfo.stripe_width
        pad = (-len(data)) % w
        # already aligned (every full-stripe client write): hand the
        # buffer through untouched — the `data + b""` form copied the
        # whole payload on the encode hot path. Unaligned tails arrive
        # as zero-copy memoryviews off the wire; only they pay the
        # materialize-and-pad.
        if not pad:
            return data
        return bytes(data) + b"\x00" * pad

    def _offload_svc(self):
        """The offload service, for DEVICE-batched plugins only: the
        jerasure family exposes the same batched API but computes on
        host, where queueing per-op work behind a linger deadline only
        adds latency (code-review finding)."""
        if getattr(self.ec_impl, "device_batched", False):
            return get_service_or_none()
        return None

    async def _encode(self, data: bytes) -> dict[int, bytes]:
        """One batched encode dispatch through the process-wide offload
        service — concurrent PGs' stripes coalesce into one device
        batch — sampled into the daemon's `ec_encode_us` histogram
        (ec_util opens the per-dispatch span with bytes/k/m tags)."""
        t0 = time.perf_counter()
        shards = await ec_util.encode_async(self.sinfo, self.ec_impl, data,
                                            service=self._offload_svc())
        self.host.perf.hist_add("ec_encode_us",
                                (time.perf_counter() - t0) * 1e6)
        return shards

    def _csums(self, shard_buf: bytes) -> list[int]:
        """Per-chunk crc32c list of a shard buffer (Checksummer analog).
        One native batch call per buffer: a per-chunk Python/ctypes loop
        was ~25us per chunk and dominated the write path (profiled)."""
        c = self.sinfo.chunk_size
        if shard_buf and len(shard_buf) % c == 0:
            from ceph_tpu.native import ec_native
            import numpy as np
            return [int(x) for x in ec_native.crc32c_blocks(
                np.frombuffer(shard_buf, dtype=np.uint8), c)]
        return [self._crc32c(shard_buf[i:i + c])
                for i in range(0, len(shard_buf), c)]

    async def _csums_shards(
            self, shards: dict[int, bytes]) -> dict[int, list[int]]:
        """Per-chunk crc32c lists for ALL shards of one write in a
        single CrcJob through the offload service: the n per-shard
        checksum calls become one batch that also coalesces with
        concurrent writers and runs off the event loop (the BlueStore
        Checksummer's batch shape, src/common/Checksummer.h:195-234)."""
        c = self.sinfo.chunk_size
        # only the device-plugin pools ride the queue (a jerasure pool
        # gains nothing from the linger wait its writes would pay), and
        # only when the crc work is big enough to beat the queue round
        # trip — the native kernel does a tiny op's csums in ~30 µs,
        # cheaper than any linger
        svc = self._offload_svc()
        lens = {len(b) for b in shards.values()}
        total_blocks = sum(len(b) for b in shards.values()) // c
        if (svc is None or self._checksummer is None or not shards
                or lens == {0} or any(ln % c for ln in lens)
                or (total_blocks < 256 and not svc.crc_device)):
            return {i: self._csums(b) for i, b in shards.items()}
        order = sorted(shards)
        # ONE scatter CrcJob over the per-shard buffers: the fragments
        # stack straight into the offload service's warm staging pages
        # (the old b"".join here paid an unmetered full copy of every
        # csum'd byte before the job was even submitted)
        crcs = await self._checksummer.calculate_async(
            [shards[i] for i in order], service=svc)
        out: dict[int, list[int]] = {}
        row = 0
        for i in order:
            n = len(shards[i]) // c
            out[i] = [int(x) for x in crcs[row:row + n]]
            row += n
        return out

    def _chunk_attrs(self, shard: int, size: int, version,
                     csums: list[int]) -> dict:
        return {"shard": str(shard).encode(),
                "ec_size": str(size).encode(),
                "csum": json.dumps(csums).encode(),
                "version": json.dumps(list(version)).encode()}

    def _verified_local_extent(
            self, oid: str, chunk_off: int, chunk_len: int,
            prev: bool = False,
            snap: int | None = None) -> tuple[bytes, int, int, tuple] | None:
        """Read [chunk_off, chunk_off+chunk_len) of the local shard blob
        (or its rollback generation, or a snap CLONE's chunk — clones
        carry the head's attrs from clone time, so the same crc/version
        verification applies) with per-chunk crc verification; None if
        absent or corrupt."""
        if prev:
            oid = oid + PREV_SUFFIX
        cid = self.coll()
        if snap is not None:
            from ceph_tpu.osd import snaps as snapmod
            gh = snapmod.clone_gh(self.ghobject(oid), snap)
            if not self.host.store.exists(cid, gh):
                return None
        else:
            if not self.local_exists(oid):
                return None
            gh = self.ghobject(oid)
        try:
            data = self.host.store.read(cid, gh, chunk_off,
                                        None if chunk_len < 0 else chunk_len)
            attrs = self.host.store.getattrs(cid, gh)
        except StoreError as e:
            # a FileStore blob whose crc gate refuses the read: treat as
            # a missing local chunk and reconstruct around it
            dout("osd", 1, f"ec local shard of {oid} unreadable: {e}")
            return None
        shard = int(attrs["shard"])
        csums = json.loads(attrs.get("csum", b"[]"))
        c = self.sinfo.chunk_size
        haves = self._csums(data) if data else []
        for i, have in enumerate(haves):
            s = chunk_off // c + i
            want = csums[s] if s < len(csums) else None
            if have != want:
                dout("osd", 1, f"ec shard {shard} of {oid}: chunk {s} crc "
                               f"{have:#x} != {want} (EIO)")
                return None
        return (data, shard, int(attrs["ec_size"]),
                tuple(json.loads(attrs.get("version", b"[0, 0]"))))

    # -- write path (RMWPipeline) --------------------------------------------

    async def execute_write(self, oid: str, op: str, data: bytes,
                            entry: LogEntry, off: int = 0) -> None:
        """Runs under the caller's obj_lock (PG._do_modify holds it
        across log intent + this call; _rewrite_consistent takes it for
        the recovery-side rewrite) — pipelined ops to DIFFERENT objects
        overlap here, same-object RMWs serialize."""
        with tracer.span("ec_write", f"osd.{self.host.whoami}") as sp:
            if sp is not None:
                sp.set_tag("op", op)
                sp.set_tag("oid", oid)
                sp.set_tag("bytes", len(data))
                sp.set_tag("k", self.k)
                sp.set_tag("m", self.n - self.k)
            await self._execute_write_locked(oid, op, data, entry, off)

    async def _execute_write_locked(self, oid: str, op: str, data: bytes,
                                    entry: LogEntry, off: int) -> None:
        if not isinstance(data, (bytes, bytearray)) and \
                op not in ("write_full", "push", "write"):
            # control-kind payloads (json / decimal-coded op args —
            # setxattr, zero lengths, clone/rollback args) arrive as
            # zero-copy memoryviews off the wire; their decoders below
            # need bytes semantics. The bulk kinds keep the view all
            # the way into the encode batch.
            data = bytes(data)
        live = self._live_positions()
        if len(live) < self.pg.pool.min_size:
            # the reference blocks the op until min_size is met; our
            # client resends until the interval heals
            raise IntervalChange(
                f"ec pg {self.pg.pgid}: {len(live)} live shards < "
                f"min_size {self.pg.pool.min_size}")

        if op in ("write_full", "push"):
            padded = self._pad(data)
            shards = await self._encode(padded) \
                if padded else {i: b"" for i in range(self.n)}
            csums = await self._csums_shards(shards)
            # WRITEFULL replaces data, not xattrs: the full-state shard
            # rewrite must carry the user attrs forward (the primary's
            # copy is authoritative — xattrs replicate to every shard)
            uattrs = self._local_user_attrs(oid)
            payloads = {
                i: ({"op": "write_full",
                     "attrs": self._encode_attrs({**self._chunk_attrs(
                         i, len(data), entry.version,
                         csums[i]), **uattrs})},
                    shards[i])
                for i in live}
        elif op in ("delete", "remove"):
            payloads = {i: ({"op": "delete"}, b"") for i in live}
        elif op == "setxattr":
            kv = json.loads(data)
            size, ver = await self._current_state(oid)
            if tuple(ver) == (0, 0):
                # xattr-on-absent creates the object: ONE sub-op writes
                # empty shards carrying the attr, atomically under this
                # object's lock (a separate exists-check + create would
                # race a concurrent data write)
                uat = {"u:" + kv["name"]: kv["value"].encode("latin1"),
                       **self._local_user_attrs(oid)}
                payloads = {
                    i: ({"op": "write_full",
                         "attrs": self._encode_attrs({
                             **self._chunk_attrs(i, 0, entry.version,
                                                 self._csums(b"")),
                             **uat})}, b"")
                    for i in live}
            else:
                payloads = {i: ({"op": "setxattr", "name": kv["name"],
                                 "value": kv["value"]}, b"")
                            for i in live}
        elif op == "rmxattr":
            payloads = {i: ({"op": "rmxattr",
                             "name": bytes(data).decode()}, b"")
                        for i in live}
        elif op == "zero":
            # same store semantics as the replicated txn.zero here: a
            # ranged write of zeros (extends past the end like a write)
            payloads = await self._plan_rmw(oid, "write",
                                            off, b"\x00" * int(data),
                                            entry, live)
            if payloads is None:
                return
        elif op == "truncate":
            cur_size, _ver = await self._current_state(oid)
            if off == cur_size:
                return
            if off > cur_size:
                # GROW rides the zero-fill RMW: the old tail stripe may
                # carry residue past cur_size (a prior mid-stripe
                # shrink keeps the stripe's bytes), and growing the
                # logical size would expose it as data — the RMW plan
                # re-encodes that stripe with explicit zeros (found by
                # the thrashing model checker)
                payloads = await self._plan_rmw(
                    oid, "write", cur_size, b"\x00" * (off - cur_size),
                    entry, live, cur_state=(cur_size, _ver))
            else:
                payloads = self._plan_shrink(off, entry, live)
        elif op in ("write", "append"):
            payloads = await self._plan_rmw(oid, op, off, data, entry, live)
            if payloads is None:        # zero-length no-op past the plan
                return
        elif op == "rollback":
            # EC rollback re-asserts the CLONE'S CONTENT as a fresh full
            # write instead of a per-shard clone-to-head copy: a shard
            # whose clone chunk is a recovery hole would silently no-op
            # the copy and diverge from the acting set (found in review).
            # The gather reconstructs the clone from any k holders.
            from ceph_tpu.osd import snaps as snapmod
            ss = await self.gather_snapset(oid)
            src = snapmod.resolve_read(ss, int(data), True)
            if src is None or src == "head":
                return                  # caller pre-resolved; no-op here
            content = await self.execute_read(oid, 0, 0, snap=src)
            await self._execute_write_locked(oid, "write_full", content,
                                             entry, 0)
            return
        elif op == "clone":
            # stamp the LOGICAL size into the per-shard clone record
            # (each shard would otherwise record its chunk-blob size and
            # list_snaps would report padded nonsense)
            args = json.loads(data)
            args["size"], _ = await self._current_state(oid)
            payloads = {i: ({"op": "clone", "args": json.dumps(args),
                             "version": list(entry.version)}, b"")
                        for i in live}
        elif op in ("snaptrim", "purge"):
            # snapshot maintenance ops are deterministic per-shard STORE
            # ops: every shard trims/purges ITS OWN chunk blobs, and the
            # SnapSet replicates onto every shard's snapdir — exactly how
            # chunk data and xattrs already replicate (the reference
            # generates the same per-shard transactions in
            # ECTransaction::generate_transactions for ec pool snaps)
            payloads = {i: ({"op": op,
                             "args": bytes(data).decode("latin1"),
                             "version": list(entry.version)}, b"")
                        for i in live}
        else:
            raise StoreError("EINVAL", f"unknown ec op {op!r}")
        await self._fan_out(oid, payloads, entry, live)

    @staticmethod
    def _encode_attrs(attrs: dict) -> dict:
        return {k: v.decode("latin1") for k, v in attrs.items()}

    async def _plan_rmw(self, oid: str, op: str, off: int, data: bytes,
                        entry: LogEntry, live: dict,
                        cur_state: tuple | None = None) -> dict | None:
        """get_write_plan + generate_transactions analog
        (src/osd/ECTransaction.h:34, :97): stripe-align the touched
        range, read back only the stripe fragments the new data does not
        fully cover, re-encode the touched stripes in one batched
        dispatch, and emit per-shard extent sub-writes. `cur_state`
        passes an already-gathered (size, version) to avoid a second
        gather under the same object lock."""
        w, c = self.sinfo.stripe_width, self.sinfo.chunk_size
        cur_size, cur_ver = cur_state if cur_state is not None \
            else await self._current_state(oid)
        if op == "append":
            off = cur_size
        if not data:
            return None                     # zero-length write: no-op
        new_size = max(cur_size, off + len(data))
        first = off // w
        last = -(-(off + len(data)) // w)   # exclusive
        if new_size > cur_size and cur_size % w and cur_size // w < first:
            # growing past a mid-stripe tail: that tail stripe must be
            # rewritten too, or its residue past cur_size (left by a
            # shrink) surfaces as logical data once the size grows over
            # it (found by the thrashing model checker). The in-between
            # hole stripes get dense explicit zeros — O(gap) work,
            # acceptable at this stripe scale (a sparse two-extent plan
            # is the optimization if huge seeks ever matter).
            first = cur_size // w
        old_n = -(-cur_size // w)
        read_upto = min(last, old_n)
        need_read = any(
            not (off <= s * w and (s + 1) * w <= off + len(data))
            for s in range(first, read_upto))
        existing = b""
        if need_read:
            got, _, _ = await self._gather_chunks(
                oid, chunk_off=first * c,
                chunk_len=(read_upto - first) * c)
            existing = await ec_util.decode_concat_async(
                self.sinfo, self.ec_impl, got,
                service=self._offload_svc())
        region = bytearray((last - first) * w)
        region[:len(existing)] = existing
        if existing:
            # bytes past the CURRENT logical size are stale tail-stripe
            # residue (a mid-stripe truncate keeps the stripe's
            # data+parity consistent but logically cut): they must read
            # back as zeros or a gap-leaving write resurrects them into
            # the zero-filled gap (found by the thrashing model checker)
            base_tail = cur_size - first * w
            if 0 <= base_tail < len(region):
                region[base_tail:] = b"\x00" * (len(region) - base_tail)
        start = off - first * w
        region[start:start + len(data)] = data
        # bytes past new_size inside the tail stripe are padding: zero
        # them explicitly in case the read-back carried old padding
        tail = new_size - first * w
        if tail < len(region):
            region[tail:] = b"\x00" * (len(region) - tail)

        # the bufferlist region goes to the codec as-is (np.frombuffer
        # views a bytearray zero-copy); the old bytes(region) paid a
        # full extra copy per RMW merge
        shards = await self._encode(region)
        csums = await self._csums_shards(shards)
        new_n = -(-new_size // w)
        payloads = {}
        for i in live:
            # hole stripes between the old tail and the write need no
            # updates: _apply_extent fills missing csum slots with the
            # zero-chunk crc, matching the store's gap zero-fill
            updates = [[first + s_rel, crc]
                       for s_rel, crc in enumerate(csums[i])]
            payloads[i] = ({"op": "extent_write",
                            "chunk_off": first * c,
                            "new_size": new_size,
                            "new_chunks": new_n,
                            "csum_updates": updates,
                            "shard": i,
                            "version": list(entry.version)}, shards[i])
        return payloads

    def _plan_shrink(self, size: int, entry: LogEntry,
                     live: dict) -> dict:
        """Per-shard shrink plan: an extent_write with no data — the
        shared apply path truncates the blob to the new chunk count and
        trims/refreshes the csum list (the reference's EC truncate rides
        generate_transactions the same way, src/osd/ECTransaction.cc).
        No re-encode is needed: whole tail stripes drop, and the
        partially-cut tail stripe keeps consistent data+parity — reads
        slice to ec_size, and every RMW re-zeroes past it before reuse
        (see _plan_rmw's residue handling)."""
        w = self.sinfo.stripe_width
        new_chunks = -(-size // w)
        return {i: ({"op": "extent_write", "chunk_off": 0,
                     "new_size": size, "new_chunks": new_chunks,
                     "csum_updates": [], "shard": i,
                     "version": list(entry.version)}, b"")
                for i in live}

    def _local_user_attrs(self, oid: str) -> dict[str, bytes]:
        """This OSD's copy of the object's user xattrs (replicated onto
        every shard, so any live holder — the primary included — is an
        authoritative source)."""
        try:
            attrs = self.host.store.getattrs(self.coll(),
                                             self.ghobject(oid))
        except StoreError:
            return {}
        return {k: v for k, v in attrs.items() if k.startswith("u:")}

    async def verify_dup_committed(self, oid, version) -> bool:
        """A dup hit is answerable only when the write is actually
        READABLE at its version: an EC entry is logged before the shard
        fan-out, so a failure can leave it applied on too few (or zero)
        shards. ENOENT means a later delete committed — done. A gather
        at an OLDER version means the write never landed — re-execute.
        A gather at a NEWER version is AMBIGUOUS (the entry may have
        been cleanly superseded, or may never have applied before the
        later write): neither "done" nor re-execution is safe, so the
        op errors out honestly and the client's model keeps both
        outcomes. Gather EIO is the same ambiguity."""
        try:
            _, _, meta = await self._gather_chunks(oid, chunk_off=0,
                                                   chunk_len=0)
        except StoreError as e:
            if e.code == "ENOENT":
                # no shard anywhere: EITHER a later delete committed
                # (done) OR this very entry was a first write that
                # never applied (must re-execute). The log's newest
                # entry for the oid tells them apart.
                return self._log_tombstoned(oid)
            raise StoreError(
                "EIO", f"{oid}: dup retry unverifiable ({e})")
        got = tuple(meta["version"])
        want = tuple(version)
        if got == want:
            return True
        if got < want:
            return False              # never landed: safe to re-execute
        raise StoreError(
            "EIO", f"{oid}: dup retry at {want} superseded by {got}; "
            f"outcome unknowable")

    async def _current_state(self, oid: str) -> tuple[int, tuple]:
        """(logical size, version) of the object, 0/(0,0) if absent."""
        loc = self._verified_local_extent(oid, 0, 0)
        if loc is not None:
            return loc[2], loc[3]
        try:
            got, size, meta = await self._gather_chunks(
                oid, chunk_off=0, chunk_len=0)
            return size, meta["version"]
        except StoreError as e:
            if e.code == "ENOENT":
                return 0, (0, 0)
            raise

    async def _fan_out(self, oid: str, payloads: dict, entry: LogEntry,
                       live: dict) -> None:
        tid = self.new_tid()
        peers = {o for o in live.values() if o != self.host.whoami}
        fut = self._start_waiting(tid, peers)
        failed = []
        entry_dict = entry.to_dict()    # once, not per peer
        for idx, osd in live.items():
            sub, chunk = payloads[idx]
            if osd == self.host.whoami:
                self._apply_sub_write(oid, idx, sub, chunk)
                continue
            try:
                await self.host.send_osd(osd, MOSDECSubOpWrite(
                    {"pgid": [self.pg.pgid.pool, self.pg.pgid.ps],
                     "tid": tid, "from": self.host.whoami, "oid": oid,
                     "shard": idx, "sub": sub,
                     "entry": entry_dict}, chunk))
            except Exception as e:
                # an unreachable peer the map hasn't caught up on: the
                # write must NOT be acked with a subset of live shards —
                # a fake ack here lets an acked write become undecodable
                # after m more failures (ADVICE r4). Fail the op; the
                # client retries until heartbeats push the peer out of
                # the acting set (the reference blocks degraded EC writes
                # the same way).
                dout("osd", 3, f"ec sub-write to osd.{osd} failed: "
                               f"{type(e).__name__} {e}")
                failed.append(osd)
        if failed:
            self._inflight.pop(tid, None)
            raise IntervalChange(
                f"ec sub-writes to osds {failed} failed; "
                f"retry next interval")
        mark_op_event("sub_ops_sent")
        await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        mark_op_event("commit")

    def _stash_prev(self, oid: str) -> None:
        """Clone the current shard state to the rollback generation."""
        cid = self.coll()
        gh, pgh = self.ghobject(oid), self.ghobject(oid + PREV_SUFFIX)
        if not self.host.store.exists(cid, gh):
            return
        from ceph_tpu.objectstore.store import Transaction
        txn = Transaction()
        if self.host.store.exists(cid, pgh):
            txn.remove(cid, pgh)
        txn.clone(cid, gh, pgh)
        self.host.store.queue_transaction(txn)

    def _apply_sub_write(self, oid: str, shard: int, sub: dict,
                         chunk: bytes) -> None:
        kind = sub["op"]
        self._stash_prev(oid)
        if kind == "write_full":
            attrs = {k: v.encode("latin1") for k, v in sub["attrs"].items()}
            self.local_apply(oid, "push", chunk, attrs=attrs)
        elif kind == "extent_write":
            self._apply_extent(oid, sub, chunk)
        elif kind == "setxattr":
            # user xattrs replicate onto EVERY shard (the reference
            # stores object attrs alongside each shard the same way)
            self.local_apply(oid, "setxattr", json.dumps(
                {"name": sub["name"], "value": sub["value"]}).encode())
        elif kind == "rmxattr":
            self.local_apply(oid, "rmxattr", sub["name"].encode())
        elif kind == "delete":
            self.local_apply(oid, "delete", b"")
        elif kind in ("clone", "snaptrim", "purge"):
            self.local_apply(oid, kind, sub["args"].encode("latin1"))
        else:
            raise StoreError("EINVAL", f"unknown ec sub-op {kind!r}")
        if chunk and faultinject.armed():
            # injected shard bit-rot AFTER the apply: the per-chunk crc
            # attr now disagrees with the blob, exactly like silent
            # media rot — the read/scrub crc gates must catch it
            off = faultinject.maybe_bitrot(len(chunk))
            if off is not None:
                self.host.store.corrupt(
                    self.coll(), self.ghobject(oid),
                    sub.get("chunk_off", 0) + off)

    def _apply_extent(self, oid: str, sub: dict, chunk: bytes) -> None:
        """Apply a per-shard extent sub-write: splice the chunk extent
        into the shard blob (gaps zero-fill via store semantics), merge
        the per-chunk csum updates, refresh size/version attrs
        (the per-shard ObjectStore::Transaction of
        src/osd/ECTransaction.cc:97 generate_transactions)."""
        from ceph_tpu.objectstore.store import Transaction
        cid, gh = self.coll(), self.ghobject(oid)
        store = self.host.store
        old_csum: list[int] = []
        if store.exists(cid, gh):
            try:
                old_csum = json.loads(store.getattr(cid, gh, "csum"))
            except StoreError:
                old_csum = []
        new_chunks = sub["new_chunks"]
        csums = [old_csum[s] if s < len(old_csum) else self._zcrc
                 for s in range(new_chunks)]
        for s, crc in sub["csum_updates"]:
            if s < new_chunks:
                csums[s] = crc
        txn = Transaction()
        if not store.exists(cid, gh):
            txn.touch(cid, gh)
        if chunk:
            txn.write(cid, gh, sub["chunk_off"], chunk)
        c = self.sinfo.chunk_size
        txn.truncate(cid, gh, new_chunks * c)
        txn.setattrs(cid, gh, self._chunk_attrs(
            sub["shard"], sub["new_size"], sub["version"], csums))
        store.queue_transaction(txn)

    # -- read path (ReadPipeline) --------------------------------------------

    async def _gather_chunks(
            self, oid: str,
            exclude_osds: frozenset = frozenset(),
            allow_rollback: bool = False,
            chunk_off: int = 0,
            chunk_len: int = -1,
            snap: int | None = None,
    ) -> tuple[dict[int, bytes], int, dict]:
        """Collect shard chunk EXTENTS [chunk_off, chunk_off+chunk_len)
        until a version-consistent decodable set exists; returns
        ({shard: extent}, logical size, meta). chunk_len < 0 means to the
        end of the shard; chunk_len == 0 fetches no data (stat).

        Shards carry the eversion of the write that produced them: mixing
        chunks of two writes would decode garbage (the reference guards
        with per-shard hashes), so only the newest version holding >= k
        extents is used. `exclude_osds` keeps a recovery target's own
        stale chunk out of its reconstruction. Raises StoreError ENOENT
        when no shard exists anywhere, EIO when shards exist but no
        version is decodable (transient: peers down/slow — NOT proof of
        deletion).

        If a NEWER version than the best decodable one was observed, the
        default is EIO (serving the older version would roll back a
        possibly-acked write). Recovery passes `allow_rollback=True`: a
        partial never-acked fan-out must not wedge peering forever, so
        the divergent suffix is rewound to the older consistent version
        (the reference's peering rewinds uncommitted divergent entries
        the same way); meta["rolled_back"] reports it.
        """
        # per observed version: {shard: (extent, ec_size)}
        by_version: dict[tuple, dict[int, tuple]] = {}
        uattrs_by: dict[tuple, dict] = {}

        def add(shard: int, data: bytes, size: int, ver,
                uattrs: dict | None = None) -> None:
            by_version.setdefault(tuple(ver), {})[shard] = (data, size)
            if uattrs:
                uattrs_by.setdefault(tuple(ver), {}).update(uattrs)

        def best() -> tuple | None:
            for ver in sorted(by_version, reverse=True):
                if len(by_version[ver]) >= self.k:
                    return ver
            return None

        if self.host.whoami not in exclude_osds:
            loc = self._verified_local_extent(oid, chunk_off, chunk_len,
                                              snap=snap)
            if loc is not None:
                data, shard, size, ver = loc
                add(shard, data, size, ver,
                    {k[2:]: v.decode("latin1") for k, v in
                     self._local_user_attrs(oid).items()})

        # two rounds: ask a minimum set first (k shards total, preferring
        # data positions), top up with the remaining positions only when
        # the first round can't decode — the reference reads exactly
        # minimum_to_decode and falls back to extra shards on miss
        candidates = [(idx, osd)
                      for idx, osd in sorted(self._live_positions().items())
                      if osd != self.host.whoami
                      and osd not in exclude_osds]
        need_first = max(0, self.k - sum(len(v) for v in
                                         by_version.values()))
        rounds = [candidates[:need_first], candidates[need_first:]]
        waits: dict[asyncio.Future, int] = {}
        deadline = asyncio.get_running_loop().time() + READ_TIMEOUT

        async def send_round(batch) -> set:
            futs = set()
            for idx, osd in batch:
                tid = self.new_tid()
                fut = asyncio.get_running_loop().create_future()
                self._read_waiters[tid] = fut
                waits[fut] = tid
                try:
                    await self.host.send_osd(osd, MOSDECSubOpRead(
                        {"pgid": [self.pg.pgid.pool, self.pg.pgid.ps],
                         "tid": tid, "from": self.host.whoami, "oid": oid,
                         "chunk_off": chunk_off, "chunk_len": chunk_len,
                         "snap": snap}))
                    futs.add(fut)
                except Exception as e:
                    # unreachable peer: just a missing chunk, not a failed
                    # read — the top-up round covers it
                    dout("osd", 3, f"ec sub-read to osd.{osd} failed: "
                                   f"{type(e).__name__} {e}")
                    fut.cancel()
            return futs

        try:
            pending = await send_round(rounds[0])
            topped_up = False
            half = deadline - READ_TIMEOUT / 2
            # early exit at k decodable chunks: one slow-but-up shard must
            # not stall every read for the full timeout
            while True:
                now = asyncio.get_running_loop().time()
                # top up when the minimum round can no longer decode on
                # its own: chunks of DIFFERENT versions don't combine, so
                # count the best single version, not the cross-version
                # sum; a half-spent deadline also triggers the top-up
                # (slow peer + stale local chunk could otherwise starve
                # a servable read)
                have_best = max((len(v) for v in by_version.values()),
                                default=0)
                if best() is None and not topped_up and (
                        not pending
                        or len(pending) + have_best < self.k
                        or now > half):
                    pending |= await send_round(rounds[1])
                    topped_up = True
                if not pending or best() is not None:
                    break
                wake = deadline if topped_up else min(deadline, half)
                timeout = wake - asyncio.get_running_loop().time()
                if timeout <= 0:
                    if topped_up:
                        break
                    continue    # hit the half mark: run the top-up branch
                done, pending = await asyncio.wait(
                    pending, timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED)
                for fut in done:
                    payload, data = fut.result()
                    if payload.get("found"):
                        add(payload["shard"], data, payload["ec_size"],
                            payload.get("version", (0, 0)),
                            payload.get("uattrs"))
        finally:
            for fut, tid in waits.items():
                fut.cancel()
                self._read_waiters.pop(tid, None)
        if best() is None and by_version and allow_rollback:
            # no MAIN version is decodable: a chain of partial fan-outs
            # fragmented the shard versions. Pull the shards' rollback
            # generations — every sub-write stashed its predecessor — so
            # an older consistent version can be reassembled instead of
            # wedging peering forever (the reference's rollback-extent
            # machinery serves the same purpose)
            await self._gather_prev_pass(oid, exclude_osds, chunk_off,
                                         chunk_len, add)
        ver = best()
        if ver is None:
            if not by_version:
                raise StoreError("ENOENT", f"{oid} has no shards anywhere")
            raise StoreError(
                "EIO", f"{oid}: no version has {self.k} shards "
                f"(saw {({v: sorted(s) for v, s in by_version.items()})})")
        newest = max(by_version)
        rolled_back = False
        if newest > ver:
            # a NEWER committed write exists but is currently undecodable:
            # serving the older decodable version would silently roll back
            # an acked write — answer EIO until recovery restores it
            # (ADVICE r4; the reference's rollforward machinery guarantees
            # the same by never exposing a pre-rollforward state)
            if not allow_rollback:
                raise StoreError(
                    "EIO", f"{oid}: newest version {newest} has only "
                    f"{len(by_version[newest])} of {self.k} shards; "
                    f"refusing to serve older {ver}")
            rolled_back = True
            dout("osd", 1, f"ec {oid}: rolling divergent partial write "
                           f"{newest} ({len(by_version[newest])} shards) "
                           f"back to {ver}")
        shards = by_version[ver]
        got = {shard: data for shard, (data, _) in shards.items()}
        any_shard = next(iter(shards.values()))
        return got, any_shard[1], {"version": ver,
                                   "rolled_back": rolled_back,
                                   "uattrs": uattrs_by.get(ver, {})}

    async def _gather_prev_pass(self, oid: str, exclude_osds: frozenset,
                                chunk_off: int, chunk_len: int,
                                add) -> None:
        """One round asking every live shard for its rollback
        generation; results merge into the caller's version table."""
        if self.host.whoami not in exclude_osds:
            loc = self._verified_local_extent(oid, chunk_off, chunk_len,
                                              prev=True)
            if loc is not None:
                data, shard, size, ver = loc
                add(shard, data, size, ver)
        waits: dict[asyncio.Future, int] = {}
        pending: set = set()
        for idx, osd in sorted(self._live_positions().items()):
            if osd == self.host.whoami or osd in exclude_osds:
                continue
            tid = self.new_tid()
            fut = asyncio.get_running_loop().create_future()
            self._read_waiters[tid] = fut
            waits[fut] = tid
            try:
                await self.host.send_osd(osd, MOSDECSubOpRead(
                    {"pgid": [self.pg.pgid.pool, self.pg.pgid.ps],
                     "tid": tid, "from": self.host.whoami, "oid": oid,
                     "chunk_off": chunk_off, "chunk_len": chunk_len,
                     "prev": True}))
                pending.add(fut)
            except Exception:
                fut.cancel()
        try:
            deadline = asyncio.get_running_loop().time() + READ_TIMEOUT / 2
            while pending:
                timeout = deadline - asyncio.get_running_loop().time()
                if timeout <= 0:
                    break
                done, pending = await asyncio.wait(
                    pending, timeout=timeout,
                    return_when=asyncio.ALL_COMPLETED)
                for fut in done:
                    payload, data = fut.result()
                    if payload.get("found"):
                        add(payload["shard"], data, payload["ec_size"],
                            payload.get("version", (0, 0)),
                            payload.get("uattrs"))
        finally:
            for fut, tid in waits.items():
                fut.cancel()
                self._read_waiters.pop(tid, None)

    async def execute_read(self, oid: str, offset: int,
                           length: int, snap: int | None = None) -> bytes:
        """Ranged read: fetch only the chunk extents of touched stripes
        (the reference computes the same bounds via
        offset_len_to_stripe_bounds, ECCommon.cc:281,503). With `snap`,
        the same gather runs against a snap CLONE's chunk blobs."""
        w, c = self.sinfo.stripe_width, self.sinfo.chunk_size
        first = offset // w
        if length <= 0:
            chunk_off, chunk_len = first * c, -1
        else:
            last = -(-(offset + length) // w)
            chunk_off, chunk_len = first * c, (last - first) * c
        got, ec_size, _ = await self._gather_chunks(
            oid, chunk_off=chunk_off, chunk_len=chunk_len, snap=snap)
        data = await ec_util.decode_concat_async(
            self.sinfo, self.ec_impl, got, service=self._offload_svc())
        start = offset - first * w
        end = (ec_size if length <= 0 else min(offset + length, ec_size)) \
            - first * w
        return data[start:max(start, end)]

    async def gather_snapset(self, oid: str, authoritative: bool = False):
        """The object's SnapSet. Default (read path): local snapdir
        first — clone sub-ops replicate it to every live shard and an
        ACTIVE primary processes every snap mutation, so its local copy
        is fresh — else the first live peer holding one. With
        `authoritative` (recovery pull on a possibly-stale primary):
        query local AND every live peer, adopt the highest seq (ties →
        fewest clones: a same-seq divergence means this holder missed a
        TRIM, never a clone — clones always advance seq). None = no
        snapshot state anywhere reachable."""
        from ceph_tpu.osd import snaps as snapmod
        local = snapmod.load_snapset(self.host.store, self.coll(),
                                     self.ghobject(oid))
        if local is not None and not authoritative:
            return local
        found = [local] if local is not None else []
        for idx, osd in sorted(self._live_positions().items()):
            if osd == self.host.whoami:
                continue
            tid = self.new_tid()
            fut = asyncio.get_running_loop().create_future()
            self._read_waiters[tid] = fut
            try:
                await self.host.send_osd(osd, MOSDECSubOpRead(
                    {"pgid": [self.pg.pgid.pool, self.pg.pgid.ps],
                     "tid": tid, "from": self.host.whoami, "oid": oid,
                     "want_ss": True}))
                payload, _ = await asyncio.wait_for(fut, READ_TIMEOUT / 2)
                if payload.get("ss"):
                    ss = snapmod.SnapSet.from_json(payload["ss"].encode())
                    if not authoritative:
                        return ss
                    found.append(ss)
            except Exception:
                continue
            finally:
                self._read_waiters.pop(tid, None)
        if not found:
            return None
        return max(found, key=lambda ss: (ss.seq, -len(ss.clones)))

    async def execute_stat(self, oid: str, snap: int | None = None) -> int:
        loc = self._verified_local_extent(oid, 0, 0, snap=snap)
        if loc is not None:
            return loc[2]
        _, ec_size, _ = await self._gather_chunks(oid, chunk_off=0,
                                                  chunk_len=0, snap=snap)
        return ec_size

    async def object_exists(self, oid: str) -> bool:
        if self.local_exists(oid):
            return True
        try:
            await self._gather_chunks(oid, chunk_off=0, chunk_len=0)
            return True
        except StoreError as e:
            # EIO = shards exist but are (transiently) undecodable: the
            # object exists; only authoritative absence is False
            return e.code != "ENOENT"

    def object_size(self, oid: str) -> int:
        _, attrs = self.read_for_push(oid)
        return int(attrs["ec_size"])

    # -- sub-op handlers (shard side) ----------------------------------------

    async def handle_sub_op(self, conn, msg) -> None:
        p = msg.payload
        if isinstance(msg, MOSDECSubOpWrite):
            self._apply_sub_write(p["oid"], p["shard"], p["sub"], msg.data)
            entry = LogEntry.from_dict(p["entry"])
            # out-of-order-tolerant insert: pipelined same-PG fan-outs
            # to different objects can arrive v6-before-v5 (see
            # ReplicatedBackend.handle_rep_op)
            self.pg.log.insert(entry)
            if p["sub"]["op"] in ("write_full", "delete"):
                # full-state sub-ops supersede whatever was missing;
                # an EXTENT write does not restore the base, so a
                # recovering shard stays in the missing set
                self.pg.log.mark_recovered(p["oid"])
            # coalesced: one meta persist per batch drain, not per
            # sub-op (pipelined primaries ship ~depth entries per
            # envelope; the apply above is already durable store
            # state). The reply rides the flush: the ack never outruns
            # the durable log entry
            self.pg.persist_meta_soon(ack=(conn, MOSDECSubOpWriteReply(
                {"pgid": p["pgid"], "tid": p["tid"],
                 "from": self.host.whoami})))
            return
        # sub-read: serve our chunk extent, crc-verified per chunk
        # (ECBackend.cc:1015 handle_sub_read, crc verify :1092)
        if p.get("want_ss"):
            from ceph_tpu.osd import snaps as snapmod
            ss = snapmod.load_snapset(self.host.store, self.coll(),
                                      self.ghobject(p["oid"]))
            conn.send_message(MOSDECSubOpReadReply(
                {"pgid": p["pgid"], "tid": p["tid"],
                 "from": self.host.whoami, "oid": p["oid"],
                 "found": ss is not None,
                 "ss": ss.to_json().decode() if ss else None}))
            return
        payload = {"pgid": p["pgid"], "tid": p["tid"],
                   "from": self.host.whoami, "oid": p["oid"],
                   "found": False, "shard": -1, "ec_size": -1}
        loc = self._verified_local_extent(
            p["oid"], p.get("chunk_off", 0), p.get("chunk_len", -1),
            prev=p.get("prev", False), snap=p.get("snap"))
        if loc is not None and p.get("runs"):
            # regenerating-code repair fetch: serve only the requested
            # sub-chunk byte runs of each chunk (crc-verified above on
            # the whole extent) — the d-helper fragment the CLAY plan
            # reconstructs from, ~q x less data than the full chunk
            sliced = self._slice_runs(loc[0], p["runs"])
            loc = None if sliced is None \
                else (sliced, loc[1], loc[2], loc[3])
        data = b""
        if loc is not None:
            data, shard, size, ver = loc
            payload.update({"found": True, "shard": shard,
                            "ec_size": size, "version": list(ver),
                            "uattrs": {k[2:]: v.decode("latin1")
                                       for k, v in
                                       self._local_user_attrs(
                                           p["oid"]).items()}})
            self.sub_read_bytes_served += len(data)
        conn.send_message(MOSDECSubOpReadReply(payload, data))

    def handle_sub_op_reply(self, msg) -> None:
        p = msg.payload
        if isinstance(msg, MOSDECSubOpWriteReply):
            self.sub_op_ack(p["tid"], p["from"])
            return
        fut = self._read_waiters.get(p["tid"])
        if fut is not None and not fut.done():
            fut.set_result((p, msg.data))

    # -- recovery (RecoveryOp-lite: reconstruct + push) ----------------------

    async def _rewrite_consistent(self, oid: str, got: dict[int, bytes],
                                  ec_size: int, rolled_to: tuple) -> None:
        """Converge every live shard on one consistent state by
        re-asserting the rolled-back content as a fresh full write: a
        divergent partial fan-out leaves SOME shards at the newer
        version, and reconstructing just one position would leave the
        acting set mixed (every later read would EIO)."""
        # log entries NEWER than the surviving content were rolled back:
        # their reqids must leave the dup index, or the client's retry
        # of that very write would be answered "already done" while its
        # data is gone (found by the thrashing model checker)
        self.pg.log.invalidate_reqids_for(oid, newer_than=rolled_to)
        data = (await ec_util.decode_concat_async(
            self.sinfo, self.ec_impl, got,
            service=self._offload_svc()))[:ec_size]
        # recovery-side writer: execute_write no longer locks itself,
        # so take the object's ordering lock here — a pipelined client
        # write to the same oid must not interleave with the rewrite
        async with self.obj_lock(oid):
            version = self.pg.next_version()
            entry = LogEntry(version=version, op="modify", oid=oid,
                             prior_version=self.pg._prior(oid))
            # log-intent-first, like every write (allocation + append
            # in one slice keeps the log monotonic)
            self.pg.log.append(entry, complete=False)
            self.pg.persist_meta()
            try:
                await self.execute_write(oid, "write_full", data, entry)
            finally:
                self.pg.log.mark_complete(version)

    def _slice_runs(self, data: bytes,
                    runs: list) -> bytes | None:
        """Per-chunk sub-chunk byte runs of a whole-chunk shard blob:
        for each chunk of `data`, concatenate the [off, off+len) runs.
        None when the blob is not whole-chunk aligned or a run falls
        outside the chunk (caller falls back to a full fetch)."""
        c = self.sinfo.chunk_size
        if not data or len(data) % c:
            return None
        out = bytearray()
        for base in range(0, len(data), c):
            for off, ln in runs:
                if off < 0 or ln <= 0 or off + ln > c:
                    return None
                out += data[base + off:base + off + ln]
        return bytes(out)

    def _note_repair(self, fetched: int, full_equiv: int) -> None:
        self.repair_bytes_fetched += fetched
        self.repair_bytes_full += full_equiv
        self.host.perf.inc("recovery_bytes_fetched", fetched)
        self.host.perf.inc("recovery_bytes_full_equiv", full_equiv)

    async def _maybe_repair_reconstruct(
            self, oid: str, idx: int) -> tuple[bytes, dict] | None:
        """Bandwidth-optimal single-shard reconstruction: when the
        plugin exposes a sub-chunk repair plan (CLAY regenerating
        codes), fetch only the plan's (offset, count) sub-chunk runs
        from the d helpers — repair_per_chunk = sub_chunk_no/q bytes of
        each helper chunk instead of k whole chunks — and rebuild the
        lost position through the offload service's repair job.

        Strictly an optimization with a conservative applicability
        gate: every helper must answer with ONE uniform version, every
        other live shard (the target included) is version-stat'ed in
        the same round and must not hold anything NEWER (a partial
        fan-out is the full gather's rollback business, not ours), and
        any miss, mismatch, or timeout returns None so the caller runs
        the existing full-stripe gather."""
        if not self.host.config.get("osd_ec_repair_subchunks"):
            return None
        sub = self.ec_impl.get_sub_chunk_count()
        c = self.sinfo.chunk_size
        if sub <= 1 or c % sub or self.ec_impl.get_chunk_mapping():
            return None
        live = self._live_positions()
        avail = set(live) - {idx}
        try:
            minimum = self.ec_impl.minimum_to_decode([idx], avail)
        except ErasureCodeError:
            return None
        if set(minimum) - avail:
            return None
        runs = next(iter(minimum.values()))
        per_chunk_subs = sum(cnt for _, cnt in runs)
        if per_chunk_subs >= sub:
            return None             # whole-chunk plan: nothing to save
        ssz = c // sub
        rpc = per_chunk_subs * ssz
        byte_runs = [[off * ssz, cnt * ssz] for off, cnt in runs]

        frags: dict[int, bytes] = {}
        metas: dict[int, tuple] = {}    # helper shard -> (size, version)
        others: list[tuple] = []        # non-helper shard versions
        uattrs: dict = {}
        waits: dict[asyncio.Future, tuple] = {}
        pending: set = set()
        ok = True
        for shard, osd in sorted(live.items()):
            helper = shard in minimum
            if osd == self.host.whoami:
                loc = self._verified_local_extent(oid, 0,
                                                  -1 if helper else 0)
                if loc is None:
                    if helper:
                        ok = False
                        break
                    continue
                data, lshard, size, ver = loc
                if helper:
                    frag = self._slice_runs(data, byte_runs) \
                        if lshard == shard else None
                    if frag is None:
                        ok = False
                        break
                    frags[shard] = frag
                    metas[shard] = (size, tuple(ver))
                    uattrs.update(
                        {k[2:]: v.decode("latin1") for k, v in
                         self._local_user_attrs(oid).items()})
                else:
                    others.append(tuple(ver))
                continue
            tid = self.new_tid()
            fut = asyncio.get_running_loop().create_future()
            self._read_waiters[tid] = fut
            waits[fut] = (tid, shard, helper)
            try:
                await self.host.send_osd(osd, MOSDECSubOpRead(
                    {"pgid": [self.pg.pgid.pool, self.pg.pgid.ps],
                     "tid": tid, "from": self.host.whoami, "oid": oid,
                     "chunk_off": 0,
                     "chunk_len": -1 if helper else 0,
                     "runs": byte_runs if helper else None}))
                pending.add(fut)
            except Exception:
                # an unreachable shard — helper OR version-stat — makes
                # the "no newer version anywhere" gate unverifiable:
                # the full gather (which owns divergence rollback) must
                # decide instead
                fut.cancel()
                ok = False
                break
        try:
            deadline = asyncio.get_running_loop().time() \
                + READ_TIMEOUT / 2
            while ok and pending:
                timeout = deadline - asyncio.get_running_loop().time()
                if timeout <= 0:
                    break
                done, pending = await asyncio.wait(
                    pending, timeout=timeout,
                    return_when=asyncio.ALL_COMPLETED)
                for fut in done:
                    _tid, shard, helper = waits[fut]
                    try:
                        payload, data = fut.result()
                    except Exception:
                        ok = False      # cancelled mid-gather
                        continue
                    if helper:
                        if not payload.get("found") or \
                                payload.get("shard") != shard:
                            ok = False
                            continue
                        frags[shard] = data
                        metas[shard] = (payload["ec_size"], tuple(
                            payload.get("version", (0, 0))))
                        uattrs.update(payload.get("uattrs") or {})
                    elif payload.get("found"):
                        others.append(tuple(
                            payload.get("version", (0, 0))))
        finally:
            for fut, (tid, _, _) in waits.items():
                fut.cancel()
                self._read_waiters.pop(tid, None)
        if pending:
            # an unanswered live shard — even a mere version stat —
            # leaves the newer-version check unproven
            ok = False
        if not ok or set(frags) != set(minimum):
            return None
        vers = {v for _, v in metas.values()}
        sizes = {s for s, _ in metas.values()}
        lens = {len(b) for b in frags.values()}
        if len(vers) != 1 or len(sizes) != 1 or len(lens) != 1:
            return None
        version = vers.pop()
        if any(v > version for v in others):
            return None     # newer partial state: full gather decides
        blen = lens.pop()
        if blen == 0 or blen % rpc:
            return None
        chunk = (await ec_util.decode_shards_async(
            self.sinfo, self.ec_impl, frags, [idx],
            service=get_service_or_none(), fragments=True))[idx]
        fetched = blen * len(frags)
        full_equiv = self.k * (blen // rpc) * c
        self._note_repair(fetched, full_equiv)
        attrs = self._chunk_attrs(idx, sizes.pop(), version,
                                  self._csums(chunk))
        for name, val in uattrs.items():
            attrs["u:" + name] = val.encode("latin1")
        dout("osd", 4, f"ec {oid}: sub-chunk repair of shard {idx} "
                       f"fetched {fetched}B vs {full_equiv}B full-gather")
        return chunk, attrs

    async def _reconstruct(self, oid: str, idx: int,
                           exclude: frozenset) -> tuple[bytes, dict] | None:
        """Chunk for position `idx` + its attrs, reconstructed from any k
        version-consistent survivors — INCLUDING the target itself when
        its chunk is crc-valid at the needed version (version attrs keep
        stale copies from combining; a target holding the newest version
        must count toward decodability or partial fan-outs look
        rollback-worthy when they are not). None when the acting set was
        instead converged by a divergence rewrite (the caller's push is
        already done). Transient <k availability (EIO with no rollback
        possible) propagates so peering retries instead of recording a
        deletion.

        Regenerating-code fast path first: a sub-chunk repair plan
        (CLAY) moves repair_per_chunk bytes from d helpers instead of k
        whole chunks; any applicability doubt falls back here."""
        if not exclude:
            rec = await self._maybe_repair_reconstruct(oid, idx)
            if rec is not None:
                return rec
        got, ec_size, meta = await self._gather_chunks(
            oid, exclude_osds=exclude, allow_rollback=True)
        if meta["rolled_back"]:
            await self._rewrite_consistent(oid, got, ec_size,
                                           meta["version"])
            return None
        blob = len(next(iter(got.values()))) if got else 0
        if blob:
            self._note_repair(sum(len(b) for b in got.values()),
                              self.k * blob)
        if idx in got:
            chunk = got[idx]
        else:
            chunk = (await ec_util.decode_shards_async(
                self.sinfo, self.ec_impl, got, [idx],
                service=self._offload_svc()))[idx]
        attrs = self._chunk_attrs(idx, ec_size, meta["version"],
                                  self._csums(chunk))
        for name, val in meta.get("uattrs", {}).items():
            attrs["u:" + name] = val.encode("latin1")
        return chunk, attrs

    def _log_tombstoned(self, oid: str) -> bool:
        """True when the authoritative log's newest word on `oid` is a
        delete: recovery must then push the DELETION, never a
        reconstruction — the surviving shards' rollback generations
        (stashed by _stash_prev before every apply, the delete included)
        could otherwise reassemble the pre-delete object and resurrect
        it onto the recovering peer as a lone undecodable shard, turning
        every later read into a permanent EIO (found by the thrashing
        model checker; the reference's recovery honors delete log
        entries the same way, PGLog missing `is_delete`)."""
        for ent in reversed(self.pg.log.entries):
            if ent.oid == oid:
                return ent.op == "delete"
        return False

    async def _reconstruct_clone(self, oid: str, idx: int,
                                 cloneid: int) -> tuple[bytes, dict] | None:
        """Position `idx`'s chunk of a snap clone, reconstructed from
        any k version-consistent clone holders; None when currently
        unreconstructable. Callers SKIP a None (reduced clone redundancy
        for the target, not a correctness hole: snap reads only need
        any k holders — if k were reachable, this reconstruct would
        have succeeded — and rollback re-asserts gathered content as a
        full write rather than depending on per-shard clones)."""
        try:
            got, ec_size, meta = await self._gather_chunks(
                oid, snap=cloneid)
        except StoreError:
            return None
        if idx in got:
            chunk = got[idx]
        else:
            chunk = (await ec_util.decode_shards_async(
                self.sinfo, self.ec_impl, got, [idx],
                service=self._offload_svc()))[idx]
        return chunk, self._chunk_attrs(idx, ec_size, meta["version"],
                                        self._csums(chunk))

    async def _push_snap_state(self, peer: int, idx: int,
                               oid: str) -> None:
        """Recovery of snapshot state: the peer's positional chunk of
        every clone, then the SnapSet (the replicated backend ships the
        same payload inline via snap_state; clones are chunks here).
        LOCAL snapdir only — a peer-querying gather here would cost
        every snap-less object O(peers) round trips per recovery push;
        the primary's own snapdir is restored by _pull_snap_state before
        it pushes anyone else."""
        from ceph_tpu.osd import snaps as snapmod
        ss = snapmod.load_snapset(self.host.store, self.coll(),
                                  self.ghobject(oid))
        if ss is None:
            return
        for clone in ss.clones:
            rec = await self._reconstruct_clone(oid, idx, clone["id"])
            if rec is None:
                continue
            chunk, attrs = rec
            await self.pg.send_push(peer, oid, chunk, attrs,
                                    delete=False, snap=clone["id"])
        await self.pg.send_push(peer, oid, b"", None, delete=False,
                                ss_blob=ss.to_json().decode())

    async def _pull_snap_state(self, oid: str, me: int) -> None:
        """Primary-side snapshot-state recovery: rebuild our own
        positional clone chunks + snapdir from the peers'. The gather
        is AUTHORITATIVE — a primary revived after missing clone ops
        would otherwise trust its stale local snapdir and serve wrong
        snap resolutions (found in review)."""
        ss = await self.gather_snapset(oid, authoritative=True)
        if ss is None:
            return
        for clone in ss.clones:
            rec = await self._reconstruct_clone(oid, me, clone["id"])
            if rec is None:
                continue
            chunk, attrs = rec
            self.apply_push(oid, chunk, attrs, False, snap=clone["id"])
        self.apply_push(oid, b"", None, False,
                        ss_blob=ss.to_json().decode())

    async def push_object(self, peer: int, oid: str) -> None:
        """Reconstruct `peer`'s positional chunk from k survivors and
        push it (the reference recovery reads min-to-decode and
        re-encodes the missing shard, RecoveryOp ECBackend.h:191)."""
        try:
            idx = self.pg.acting.index(peer)
        except ValueError:
            return
        await self._push_snap_state(peer, idx, oid)
        if self._log_tombstoned(oid):
            await self.pg.send_push(peer, oid, b"", None, delete=True)
            return
        try:
            # the target is NOT excluded from the gather: version attrs
            # keep a stale copy from combining with newer shards, and the
            # per-chunk crc gate keeps a corrupt one out — but a target
            # holding the newest version must still count toward its
            # decodability, or a partial fan-out looks rollback-worthy
            # when it is not (found by the thrashing model checker)
            rec = await self._reconstruct(oid, idx, exclude=frozenset())
        except StoreError as e:
            if e.code != "ENOENT":
                raise
            await self.pg.send_push(peer, oid, b"", None, delete=True)
            return
        if rec is None:
            return      # divergence rewrite already updated every shard
        chunk, attrs = rec
        await self.pg.send_push(peer, oid, chunk, attrs, delete=False)

    async def pull_object(self, auth_peer: int, oid: str, need,
                          fallbacks=()) -> None:
        """We (the primary) lack this object: reconstruct OUR positional
        chunk from the survivors instead of copying the auth peer's (its
        chunk is a different position; the gather already consults every
        live shard, so `fallbacks` is implicit here)."""
        me = self.pg.acting.index(self.host.whoami)
        await self._pull_snap_state(oid, me)
        if self._log_tombstoned(oid):
            # authoritative history deleted it (belt-and-braces: the
            # caller's ZERO-need tombstone normally catches this)
            self.local_apply(oid, "delete", b"")
            return
        try:
            rec = await self._reconstruct(oid, me, exclude=frozenset())
        except StoreError as e:
            if e.code != "ENOENT":
                raise
            self.local_apply(oid, "delete", b"")
            return
        if rec is None:
            return      # divergence rewrite already updated every shard
        chunk, attrs = rec
        self.local_apply(oid, "push", chunk, attrs=attrs)
