"""The OSD daemon: boots against the monitor quorum, subscribes to
osdmaps, hosts PGs, and serves client I/O.

Re-creation of the reference OSD's lifecycle and dispatch
(src/osd/OSD.cc): init + MOSDBoot through a MonClient (:3704 init,
_preboot), osdmap subscription and PG advance on every epoch
(handle_osd_map/activate_map), op ingest ms_fast_dispatch (:7550) ->
per-PG execution, OSD<->OSD heartbeats with failure reports to the mon
(heartbeat :6187, send_failures :7224).

Idiomatic divergences: one asyncio event loop stands in for the sharded
op threadpool (the concurrency axis the reference gets from
osd_op_tp); heartbeats ride the cluster connections instead of separate
hb_front/hb_back messengers; PG discovery scans pool pg ranges on each
epoch instead of tracking creation deltas.
"""
from __future__ import annotations

import asyncio
import json
import time

from ceph_tpu.crush.osdmap import PG, Incremental, OSDMap
from ceph_tpu.mgr.mgr_client import MgrClient
from ceph_tpu.msg.messages import (Message, MOSDOp, MOSDOpReply,
                                   MOSDOpThrottle, MOSDPGInfo,
                                   MOSDPGLog, MOSDPGPush, MOSDPGPushReply,
                                   MOSDPGQuery, MOSDRepOp, MOSDRepOpReply,
                                   MOSDRepScrub, MOSDRepScrubMap,
                                   MOSDScrubReserve, MPing, MPingReply)
from ceph_tpu.msg.messenger import Connection, Dispatcher, Messenger, Policy
from ceph_tpu.mon.mon_client import MonClient
from ceph_tpu.objectstore.memstore import MemStore
from ceph_tpu.objectstore.store import StoreError
from ceph_tpu.osd import scrub as scrub_mod
from ceph_tpu.osd.backend import IntervalChange
from ceph_tpu.osd.pg import PGInstance
from ceph_tpu.qa import faultinject
from ceph_tpu.utils import (copytrack, crash, flight, loopprof, sanitizer,
                            tracer)
from ceph_tpu.utils.admin_socket import AdminSocket
from ceph_tpu.utils.async_util import drain_all, reap_all
from ceph_tpu.utils.config import Config, Option
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.perf_counters import (TYPE_AVG, TYPE_GAUGE,
                                          TYPE_HISTOGRAM,
                                          PerfCountersCollection)
from ceph_tpu.utils.throttle import AdjustableSemaphore, HeartbeatMap
from ceph_tpu.utils.work_queue import (ClientTable, Finisher, OpTracker,
                                       ShardedOpQueue, WRITE_OP_KINDS,
                                       classify_ops, current_op,
                                       reset_current_op, set_current_op)


class OSD(Dispatcher):
    """One object-storage daemon."""

    HB_INTERVAL = 1.0
    HB_GRACE = 3.0              # osd_heartbeat_grace analog

    NUM_OP_SHARDS = 5           # osd_op_num_shards analog

    SCRUB_INTERVAL = 60.0       # osd_scrub_min_interval analog
    DEEP_SCRUB_EVERY = 4        # every Nth scrub round goes deep

    MAX_RECOVERY_IN_FLIGHT = 4  # osd_max_backfills / AsyncReserver slots

    PG_PIPELINE_DEPTH = 4       # per-PG execution window (1 = serial)

    def __init__(self, whoami: int, mon_addrs: list[tuple[str, int]],
                 store=None, crush_location: dict | None = None,
                 admin_socket_path: str | None = None,
                 config: Config | None = None,
                 auth_key: bytes | None = None):
        self.whoami = whoami
        self.store = store if store is not None else MemStore(f"osd{whoami}")
        self.crush_location = crush_location or {"host": f"host{whoami}"}
        # tunables live in the Config (defaults seeded from the class
        # attrs so test monkeypatching still works); timer loops re-read
        # every iteration, so `config set` via the admin socket takes
        # effect immediately (observer-free hot reload)
        self.config = config if config is not None else Config([
            Option("osd_heartbeat_interval", "float", self.HB_INTERVAL,
                   "seconds between peer pings", minimum=0.01),
            Option("osd_heartbeat_grace", "float", self.HB_GRACE,
                   "silence before reporting a peer failed",
                   minimum=0.05),
            Option("osd_scrub_interval", "float", self.SCRUB_INTERVAL,
                   "seconds between background scrub rounds",
                   minimum=0.05),
            Option("osd_deep_scrub_every", "int", self.DEEP_SCRUB_EVERY,
                   "every Nth scrub round re-reads data", minimum=1),
            Option("osd_scrub_chunk_max", "int", 32,
                   "objects scanned per scrub chunk; each chunk costs "
                   "one QoS grant under the scrub class, so smaller "
                   "chunks yield to client I/O more often (hot: the "
                   "next chunk re-reads it)", minimum=1),
            Option("osd_scrub_sleep", "float", 0.0,
                   "seconds slept between scrub scan chunks (throttle "
                   "on top of the QoS pacing; hot)", minimum=0.0),
            Option("osd_scrub_reserve", "bool", True,
                   "reserve one scrub slot on every acting-set member "
                   "before a round may gate client writes (the "
                   "reference's scrub reserver; hot)"),
            Option("osd_scrub_reserve_timeout", "float", 10.0,
                   "seconds a primary waits for a local or remote "
                   "scrub reservation before aborting the round — the "
                   "path that breaks crossed-reservation deadlocks "
                   "(hot)", minimum=0.1),
            Option("osd_max_scrubs", "int", 1,
                   "concurrent scrub rounds this daemon will take part "
                   "in, as primary or replica (hot: resizes the live "
                   "reservation pool)", minimum=1),
            Option("osd_op_num_shards", "int", self.NUM_OP_SHARDS,
                   "op queue shards (startup only)", minimum=1),
            Option("osd_max_recovery_in_flight", "int",
                   self.MAX_RECOVERY_IN_FLIGHT,
                   "host-wide recovery reservation slots (hot: resizes "
                   "the live pool, so recovery pressure can be tuned "
                   "mid-storm)", minimum=1),
            Option("osd_pg_pipeline_depth", "int",
                   self.PG_PIPELINE_DEPTH,
                   "max concurrent client ops in the execution slice "
                   "per PG (distinct objects only; the pg-log ordered "
                   "slice stays strictly FIFO). 1 = the legacy serial "
                   "pipeline, bit-identical. Hot: resizes the live "
                   "admission window", minimum=1),
            Option("osd_ec_repair_subchunks", "bool", True,
                   "use regenerating-code sub-chunk repair plans for "
                   "single-shard recovery (fetch repair fragments from "
                   "d helpers instead of k whole chunks)"),
            # per-client SLO engine (hot: the observer pushes changes
            # into the live ClientTable, so an operator can tighten or
            # relax the SLO mid-overload). 0 = class unguarded.
            Option("slo_read_ms", "float", 0.0,
                   "read-op SLO in ms; ops slower than this count as "
                   "per-client violations (0 disables)", minimum=0.0),
            Option("slo_write_ms", "float", 0.0,
                   "write-op SLO in ms; ops slower than this count as "
                   "per-client violations (0 disables)", minimum=0.0),
            Option("osd_max_client_entries", "int", 256,
                   "bound of the per-client accounting table; the "
                   "least-recently-active overflow folds into _other "
                   "(hot: resizes the live table)", minimum=2),
            # dmclock QoS arbiter (osd/scheduler/): every knob is hot
            # — the observer pushes changes into the live scheduler,
            # so an operator can impose a limit or flip the overload
            # policy mid-storm
            Option("osd_mclock_enabled", "bool", False,
                   "arbitrate op dequeue by per-tenant reservation/"
                   "limit/weight tag clocks instead of the legacy "
                   "class WRR (hot: queued work migrates)"),
            Option("osd_mclock_cost_per_io_bytes", "size", 65536,
                   "payload bytes worth one extra IO of scheduling "
                   "cost (byte-normalization of the tag clocks)",
                   minimum=1),
            Option("osd_mclock_client_reservation", "float", 0.0,
                   "guaranteed cost-units/sec per client tenant "
                   "(0 = no floor)", minimum=0.0),
            Option("osd_mclock_client_limit", "float", 0.0,
                   "cost-units/sec cap per client tenant (0 = "
                   "uncapped)", minimum=0.0),
            Option("osd_mclock_client_weight", "float", 1.0,
                   "proportional share of excess capacity per client "
                   "tenant", minimum=0.0),
            Option("osd_mclock_recovery_reservation", "float", 4.0,
                   "guaranteed cost-units/sec for the recovery class "
                   "pseudo-entity (nonzero keeps recovery progressing "
                   "under client floods)", minimum=0.0),
            Option("osd_mclock_recovery_limit", "float", 0.0,
                   "cost-units/sec cap for recovery (0 = uncapped)",
                   minimum=0.0),
            Option("osd_mclock_recovery_weight", "float", 0.5,
                   "recovery's proportional share of excess capacity",
                   minimum=0.0),
            Option("osd_mclock_scrub_reservation", "float", 2.0,
                   "guaranteed cost-units/sec for the scrub class "
                   "pseudo-entity (nonzero keeps integrity scanning "
                   "progressing under client floods)", minimum=0.0),
            Option("osd_mclock_scrub_limit", "float", 0.0,
                   "cost-units/sec cap for scrub (0 = uncapped)",
                   minimum=0.0),
            Option("osd_mclock_scrub_weight", "float", 0.25,
                   "scrub's proportional share of excess capacity",
                   minimum=0.0),
            Option("osd_mclock_snaptrim_reservation", "float", 1.0,
                   "guaranteed cost-units/sec for the snaptrim class "
                   "pseudo-entity", minimum=0.0),
            Option("osd_mclock_snaptrim_limit", "float", 0.0,
                   "cost-units/sec cap for snaptrim (0 = uncapped)",
                   minimum=0.0),
            Option("osd_mclock_snaptrim_weight", "float", 0.25,
                   "snaptrim's proportional share of excess capacity",
                   minimum=0.0),
            Option("osd_mclock_overload_policy", "str", "backpressure",
                   "past-saturation admission control: backpressure "
                   "defers dequeue until limit tags mature; shed "
                   "refuses enqueue with an EAGAIN-style throttle "
                   "reply once a tenant's backlog passes "
                   "osd_mclock_shed_queue_depth",
                   enum=("backpressure", "shed")),
            Option("osd_mclock_shed_queue_depth", "int", 256,
                   "per-tenant queued-op depth that triggers shedding "
                   "(shed policy only)", minimum=1),
            Option("osd_mclock_tenant_profiles", "str", "",
                   "JSON {tenant: {reservation, limit, weight}} "
                   "per-tenant overrides of the osd_mclock_client_* "
                   "defaults"),
        ])
        # op tracing rides the same config (hot-togglable: `config set
        # tracer_enabled true` over the admin socket starts collecting)
        tracer.register_config(self.config)
        # the process-wide EC offload service's knobs (ec_offload_*)
        # ride this daemon's config too: `config set
        # ec_offload_linger_ms 5` over the admin socket retunes the
        # batcher live via the config observer
        from ceph_tpu import offload
        offload.register_config(self.config)
        # per-peer message batching knobs (msgr_batch_*): hot-togglable
        # through the same observer path — `config set
        # msgr_batch_linger_us 1000` retunes the wire batcher live
        from ceph_tpu.msg import messenger as msgr_mod
        msgr_mod.register_config(self.config)
        # the msgr frame/batch counters must exist before the first
        # MgrReport so their families export from round one
        msgr_mod.msgr_perf()
        # runtime asyncio sanitizer (debug mode + slow-callback log +
        # task spawn-site tracking): `config set sanitizer_enabled
        # true` arms the running loop live
        sanitizer.register_config(self.config)
        # event-loop sampling profiler (`profile dump` over the admin
        # socket): loop-busy-fraction + top stall sites, hot-togglable
        # via `config set profiler_enabled true`
        loopprof.register_config(self.config)
        # deterministic fault injection (fault_inject_*): `config set
        # fault_inject_enabled true` over the admin socket arms the
        # process-wide injector; the `inject` command fires one-shots
        faultinject.register_config(self.config)
        # flight-recorder knobs (flight_*): `config set
        # flight_ring_capacity 2048` resizes the process-wide event
        # ring live; `config set flight_enabled false` silences it
        flight.register_config(self.config)
        # the profiler/copy-ledger/tracer counter mirrors must exist
        # before the first MgrClient report so their families export
        # from round one
        loopprof.perf()
        copytrack.perf()
        tracer.perf()
        scrub_mod.scrub_perf()
        # per-daemon perf counters, served by `perf dump` (the admin
        # socket reads the process-wide collection)
        coll = PerfCountersCollection.instance()
        coll.remove(f"osd.{whoami}")    # a restarted id re-registers
        self.perf = coll.create(f"osd.{whoami}")
        self.perf.add("op", description="client ops executed")
        self.perf.add("op_latency", type=TYPE_AVG,
                      description="client op latency (seconds)")
        self.perf.add("subop", description="replication sub-ops applied")
        self.perf.add("recovery_push",
                      description="objects pushed by recovery/backfill")
        self.perf.add("recovery_bytes_pushed",
                      description="shard bytes pushed to recovering "
                                  "peers")
        self.perf.add("recovery_bytes_fetched",
                      description="shard bytes fetched by recovery "
                                  "reconstruction gathers")
        self.perf.add("recovery_bytes_full_equiv",
                      description="bytes a full-stripe gather would "
                                  "have fetched for the same repairs "
                                  "(repair-bandwidth baseline)")
        self.perf.add("heartbeat_failures",
                      description="peers reported failed to the mon")
        # per-stage latency histograms (power-of-two µs buckets; the
        # exporter renders them as cumulative prometheus histograms)
        # per-PG pipelined execution (the PrimaryLogPG concurrency
        # window): live occupancy + admissions parked on a full window
        self.perf.add("pg_pipeline_inflight", type=TYPE_GAUGE,
                      description="ops currently in pipelined "
                                  "execution across this OSD's PGs")
        self.perf.add("pg_pipeline_window_stalls",
                      description="shard-worker waits with queued work "
                                  "blocked behind a full per-PG "
                                  "pipeline window")
        # dmclock QoS ledger (per-tenant splits ride the MgrReport
        # qos_metrics leg; these are the daemon-wide aggregates)
        self.perf.add("qos_shed",
                      description="client ops refused by shed "
                                  "admission control (throttle reply)")
        self.perf.add("qos_deferred_waits",
                      description="shard-worker sleeps with every "
                                  "queued tenant limit-blocked "
                                  "(backpressure)")
        self.perf.add("qos_dequeue_reservation",
                      description="ops dequeued by the reservation "
                                  "phase (tenant behind its floor)")
        self.perf.add("qos_dequeue_weight",
                      description="ops dequeued by the weight phase "
                                  "(proportional share)")
        self.perf.add("op_total_us", type=TYPE_HISTOGRAM,
                      description="client op total latency (µs)")
        self.perf.add("op_queue_wait_us", type=TYPE_HISTOGRAM,
                      description="op queue wait before dequeue (µs)")
        self.perf.add("ec_encode_us", type=TYPE_HISTOGRAM,
                      description="EC encode dispatch latency (µs)")
        self.perf.add("store_commit_us", type=TYPE_HISTOGRAM,
                      description="objectstore queue_transaction "
                                  "latency (µs)")
        # the store feeds its commit latency into this daemon's histogram
        self.store.commit_perf = self.perf
        # op execution substrate: sharded queue (per-PG order, cross-PG
        # concurrency) + finisher for completions + per-op tracking
        self.hb_map = HeartbeatMap()
        # the per-client accountant registers in the process collection
        # so admin-socket `perf dump`/`perf reset` cover it (reset
        # zeroes the client tables, not just the aggregate counters)
        clients = ClientTable(
            f"osd.{whoami}.clients",
            max_entries=self.config.get("osd_max_client_entries"))
        clients.set_slo(read_ms=self.config.get("slo_read_ms"),
                        write_ms=self.config.get("slo_write_ms"))
        coll.remove(clients.name)       # a restarted id re-registers
        coll.register(clients)
        self.config.add_observer(
            ("slo_read_ms", "slo_write_ms", "osd_max_client_entries"),
            self._on_client_knobs)
        self.optracker = OpTracker(clients=clients)
        self.op_queue = ShardedOpQueue(
            f"osd.{whoami}.op_tp",
            num_shards=self.config.get("osd_op_num_shards"),
            hb_map=self.hb_map,
            pipeline_depth=self.config.get("osd_pg_pipeline_depth"),
            perf=self.perf)
        self.config.add_observer(("osd_pg_pipeline_depth",),
                                 self._on_pipeline_depth)
        # dmclock arbiter wiring: seed the scheduler from the knobs,
        # then keep it live via the observer (every osd_mclock_* knob
        # is hot, including the enable toggle — queued work migrates)
        self._apply_qos_knobs()
        self.op_queue.set_mclock_enabled(
            self.config.get("osd_mclock_enabled"))
        self.config.add_observer(
            ("osd_mclock_enabled", "osd_mclock_cost_per_io_bytes",
             "osd_mclock_client_reservation",
             "osd_mclock_client_limit", "osd_mclock_client_weight",
             "osd_mclock_recovery_reservation",
             "osd_mclock_recovery_limit", "osd_mclock_recovery_weight",
             "osd_mclock_scrub_reservation",
             "osd_mclock_scrub_limit", "osd_mclock_scrub_weight",
             "osd_mclock_snaptrim_reservation",
             "osd_mclock_snaptrim_limit", "osd_mclock_snaptrim_weight",
             "osd_mclock_overload_policy",
             "osd_mclock_shed_queue_depth",
             "osd_mclock_tenant_profiles"),
            self._on_qos_knobs)
        self.finisher = Finisher(f"osd.{whoami}.finisher",
                                 hb_map=self.hb_map)
        self.asok: AdminSocket | None = None
        if admin_socket_path:
            self.asok = AdminSocket(admin_socket_path, config=self.config)
            self.asok.register_command(
                "dump_ops_in_flight",
                lambda req: self.optracker.dump_ops_in_flight(),
                "ops currently being processed")
            self.asok.register_command(
                "dump_historic_ops",
                lambda req: self.optracker.dump_historic_ops(),
                "recently completed ops with event timelines")
            self.asok.register_command(
                "dump_historic_slow_ops",
                lambda req: self.optracker.dump_historic_slow_ops(),
                "recently completed slow ops")
            self.asok.register_command(
                "dump_clients",
                lambda req: self._dump_clients(req.get("limit")),
                "per-client accounting: ops/bytes/in-flight, rolling "
                "p50/p99 per class, SLO good-vs-violating counters, "
                "live QoS tag clocks")
            self.asok.register_command(
                "qos status",
                lambda req: self.op_queue.qos_status(),
                "dmclock scheduler: per-tenant tag clocks, "
                "reservation/limit/weight in force, shed/deferred "
                "ledger")
            self.asok.register_command(
                "scrub",
                lambda req: self._trigger_scrub(req.get("deep", False)),
                "scrub all primary PGs now (deep=true for deep scrub)")
            self.asok.register_command(
                "last_scrub",
                lambda req: {f"{pgid.pool}.{pgid.ps}": pg.last_scrub
                             for pgid, pg in self.pgs.items()
                             if pg.last_scrub is not None},
                "last scrub result per PG")
            self.asok.register_command(
                "list-inconsistent-obj",
                lambda req: self._list_inconsistent(req.get("pool")),
                "per-PG inconsistent-object registry from the last "
                "scrub rounds (optionally filtered by pool id)")
            self.asok.register_command(
                "status", lambda req: self._daemon_status(),
                "daemon status")
            self.asok.register_command(
                "ec offload status",
                lambda req: self._offload_admin("status"),
                "offload service: queue/batch/fallback stats + settings")
            self.asok.register_command(
                "ec offload flush",
                lambda req: self._offload_admin("flush"),
                "force-flush every pending offload batch bucket")
            self.asok.register_command(
                "inject",
                lambda req: self._inject_admin(req),
                "fault injection: what=crash|hang|bitrot|msg|device|"
                "status (hang: seconds; bitrot: oid [offset]; msg: "
                "action/type/entity/count; device: count)")
        self.messenger = Messenger(f"osd.{whoami}", auth_key=auth_key)
        self.messenger.add_dispatcher(self)
        self.monc = MonClient(self.messenger, mon_addrs)
        self.monc.on_osdmap = self._on_osdmap
        # mgr report session: perf-counter deltas + daemon status +
        # health metrics (slow ops, pg states, store utilization) +
        # recovery progress, shipped as MMgrReport over the messenger
        self.mgr_client = MgrClient(
            self.messenger, f"osd.{whoami}", "osd",
            resolve=lambda: (self.monc.mgrmap or {}).get("active_addr"),
            status_cb=self._daemon_status,
            health_cb=self._mgr_health_metrics,
            progress_cb=self._mgr_progress,
            device_cb=self._mgr_device_metrics,
            client_cb=self._mgr_client_metrics,
            qos_cb=self._mgr_qos_metrics,
            extra_loggers=("offload", "sanitizer", "loopprof",
                           "copyflow", "msgr", "tracer", "scrub"))
        # the per-loop offload service handle (set at start(): the
        # admin-socket thread cannot resolve the running loop itself)
        self._offload_svc = None
        self.osdmap = OSDMap()
        self.pgs: dict[PG, PGInstance] = {}
        self.addr: tuple[str, int] | None = None
        self._conns: dict[int, Connection] = {}
        # ops parked until their PG finishes peering (waiting_for_active,
        # src/osd/PG.cc): preserves arrival order without wedging a
        # queue shard on a peering PG. Entries are (ingest_seq, conn,
        # msg, trk) kept sorted by ingest_seq: an op re-parked from the
        # shard queue must land BEFORE later arrivals that parked
        # directly, or a client's ops reorder across an interval change
        # (the reference requeues at the front for the same reason)
        self._waiting_for_active: dict[PG, list] = {}
        self._op_seq = 0
        # strong refs to detached notify tasks (the loop keeps only
        # weak refs; a collected task would drop the notify silently)
        self._notify_tasks: set[asyncio.Task] = set()
        # host-wide recovery throttle: background pushes across ALL PGs
        # share these slots so backfill cannot monopolize the daemon
        # (AsyncReserver, src/common/AsyncReserver.h). Resizable live
        # via the osd_max_recovery_in_flight config observer so
        # recovery pressure can be tuned mid-storm.
        self.recovery_reservations = AdjustableSemaphore(
            self.config.get("osd_max_recovery_in_flight"))
        self.config.add_observer(("osd_max_recovery_in_flight",),
                                 self._on_recovery_slots)
        # host-wide scrub slots (osd_max_scrubs): a round — primary- or
        # replica-side — holds one for its whole duration. Named, so
        # when lockdep is armed every park on the pool is a tracked
        # wait and every holder a tracked task; the entity detail rides
        # into the mgr deadlock annotations.
        self.scrub_reservations = AdjustableSemaphore(
            self.config.get("osd_max_scrubs"),
            name=f"osd.{self.whoami}:scrub_reservations")
        self.scrub_reservations.lockdep_detail = {
            "entity": f"osd.{self.whoami}"}
        # remote grants held for other primaries: (pool, ps, tid, from)
        self._scrub_remote_grants: set[tuple] = set()
        self.config.add_observer(("osd_max_scrubs",),
                                 self._on_scrub_slots)
        # fault injection: a hang deadline makes dispatch swallow
        # everything (peers see heartbeat silence -> mark-down); the
        # crash task is deliberately NOT in _bg_tasks (it runs stop(),
        # which reaps _bg_tasks — tracking it there would self-deadlock)
        self._hang_until = 0.0
        self._crash_task: asyncio.Task | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        # reactor shard index (set at start(); None = unpooled loop)
        self.shard: int | None = None
        self._booted = asyncio.Event()
        self._hb_task: asyncio.Task | None = None
        self._scrub_task: asyncio.Task | None = None
        self._bg_tasks: set[asyncio.Task] = set()
        self._reboot_task: asyncio.Task | None = None
        self._hb_last: dict[int, float] = {}      # peer -> last reply stamp
        self._hb_reported: set[int] = set()
        self._stopping = False
        # completion latch for concurrent stops (injected crash racing
        # harness teardown): the second caller WAITS for the first
        # stop to finish rather than returning mid-teardown
        self._stop_event: asyncio.Event | None = None

    # -- lifecycle -----------------------------------------------------------

    async def start(self, timeout: float = 30.0) -> tuple[str, int]:
        try:
            self.store.mount()
        except StoreError as e:
            # ONLY an uninitialized store may be formatted — any other
            # mount failure (corrupt meta, IO error) must not silently
            # wipe a durable store
            if e.code != "ENOENT":
                raise
            self.store.mkfs()
            self.store.mount()
        from ceph_tpu import offload
        self._offload_svc = offload.get_service()
        self._loop = asyncio.get_running_loop()
        # reactor placement: under the sharded runtime start() runs ON
        # the owning shard's loop, so every loop-bound resource this
        # daemon creates (messenger server, connections, op queue,
        # offload front end) lands on that shard by construction
        from ceph_tpu.utils import reactor
        self.shard = reactor.shard_index_of(self._loop)
        sanitizer.maybe_install(self.config)
        loopprof.maybe_install(self.config)
        self.op_queue.start()
        self.finisher.start()
        if self.asok is not None:
            self.asok.start()
        self.addr = await self.messenger.bind("127.0.0.1", 0)
        await self.monc.start()
        self.monc.subscribe("osdmap", 1)
        self.monc.subscribe("mgrmap", 1)
        await self.monc.send_boot(self.whoami, self.addr,
                                  crush_location=self.crush_location)
        deadline = time.monotonic() + timeout
        while not self._booted.is_set():
            if time.monotonic() > deadline:
                raise TimeoutError(f"osd.{self.whoami} never marked up")
            # boots can race leadership churn: re-send until the map shows us
            try:
                await asyncio.wait_for(self._booted.wait(), 2.0)
            except asyncio.TimeoutError:
                await self.monc.send_boot(self.whoami, self.addr,
                                          crush_location=self.crush_location)
        self._hb_task = asyncio.get_running_loop().create_task(
            self._heartbeat())
        self._scrub_task = asyncio.get_running_loop().create_task(
            self._scrub_loop())
        self.mgr_client.start()
        dout("osd", 1, f"osd.{self.whoami} up at {self.addr}")
        return self.addr

    # -- mgr reporting -------------------------------------------------------

    def _daemon_status(self) -> dict:
        return {"whoami": self.whoami,
                "osdmap_epoch": self.osdmap.epoch,
                "num_pgs": len(self.pgs),
                "hb_healthy": self.hb_map.is_healthy()[0],
                "reactor_shard": self.shard,
                "ops_processed": self.op_queue.processed,
                "pipeline": {
                    "depth": self.op_queue.pipeline_depth,
                    "in_flight": self.op_queue.total_in_flight(),
                    "window_stalls": self.op_queue.window_stalls}}

    def _mgr_health_metrics(self) -> dict:
        """Daemon health metrics for the report path: slow ops from the
        OpTracker, pending PG states, store utilization — the inputs of
        the mon's SLOW_OPS / PG_* / OSD_NEARFULL checks."""
        slow = self.optracker.get_health_metrics()
        states: dict[str, int] = {}
        degraded = undersized = 0
        for pg in self.pgs.values():
            states[pg.state] = states.get(pg.state, 0) + 1
            if not pg.is_primary():
                continue
            if len(pg.acting) < pg.pool.size:
                undersized += 1
                degraded += 1
            elif pg._pending_recovery:
                degraded += 1
        return {"slow_ops": slow["slow_ops"],
                "slow_ops_oldest_age_s": slow["oldest_age_s"],
                "pg_states": states,
                "degraded_pgs": degraded,
                "undersized_pgs": undersized,
                # unarchived crash records for this daemon: the mgr
                # digests any non-zero count into RECENT_CRASH
                "recent_crashes": len(crash.recent(f"osd.{self.whoami}")),
                # device-offload circuit-breaker state: the mgr digests
                # a degraded service into TPU_OFFLOAD_DEGRADED
                "offload": (self._offload_svc.health_metrics()
                            if self._offload_svc is not None else {}),
                # per-client SLO surface: recent violations + slow
                # clients, digested into SLO_VIOLATIONS / SLOW_CLIENT
                "clients": self.optracker.clients.health_metrics(),
                # integrity surface: registry counts digested into
                # PG_DAMAGED / OSD_SCRUB_ERRORS, per-pool table
                # aggregated into the ceph_scrub_* exporter families
                "scrub": self._scrub_health_metrics(),
                # long-parked lock/grant waits annotated with (entity,
                # resource, peer, tid): the rows the mgr assembles into
                # its cross-daemon wait-for graph (DEADLOCK_SUSPECTED)
                "deadlock": sanitizer.wait_annotations(
                    entity=f"osd.{self.whoami}"),
                "store": self.store.statfs()}

    def _mgr_device_metrics(self) -> dict:
        """Per-device offload utilization for the report path: the mgr
        stores these per daemon; the exporter renders them with a
        `ceph_device` label."""
        return (self._offload_svc.device_metrics()
                if self._offload_svc is not None else {})

    def _mgr_client_metrics(self) -> dict:
        """Per-client accounting for the report path: the mgr merges a
        client's tallies ACROSS OSDs and the exporter renders them as
        `ceph_client_*` families with a `ceph_client` label."""
        return self.optracker.clients.mgr_metrics()

    def _mgr_qos_metrics(self) -> dict:
        """Per-tenant QoS ledger (shed/deferred/dequeue-phase splits)
        for the report path: the exporter renders them as `ceph_qos_*`
        families with a `tenant` label."""
        return self.op_queue.sched.tenant_metrics()

    def _dump_clients(self, limit=None) -> dict:
        """dump_clients + the live QoS tag columns of each client's
        scheduling entity (its tenant, or itself when untenanted)."""
        dump = self.optracker.clients.dump_clients(limit)
        sched = self.op_queue.sched
        for row in dump.get("clients", []):
            row.update(sched.tag_columns(
                row.get("tenant") or row.get("client")))
        return dump

    def _on_client_knobs(self, name: str, value) -> None:
        """slo_read_ms / slo_write_ms / osd_max_client_entries observer:
        pushed straight into the live ClientTable (its own lock makes
        this safe from the admin-socket thread)."""
        clients = self.optracker.clients
        if name == "slo_read_ms":
            clients.set_slo(read_ms=float(value))
        elif name == "slo_write_ms":
            clients.set_slo(write_ms=float(value))
        elif name == "osd_max_client_entries":
            clients.resize(int(value))

    def _offload_admin(self, cmd: str) -> dict:
        if self._offload_svc is None:
            return {"error": "offload service not started"}
        if cmd == "flush":
            return self._offload_svc.flush()
        return self._offload_svc.status()

    # -- fault injection (admin `inject` + injector-driven hooks) ------------

    def _run_on_loop(self, fn, *args) -> None:
        """Run `fn(*args)` on this daemon's loop: config observers fire
        from admin-socket threads, and the targets (wake events,
        semaphores) are loop-bound — hop via call_soon_threadsafe when
        off the loop, run inline when already on it (or when the
        daemon's loop is gone)."""
        loop = self._loop
        if loop is not None and not loop.is_closed():
            try:
                on_loop = asyncio.get_running_loop() is loop
            except RuntimeError:
                on_loop = False
            if not on_loop:
                loop.call_soon_threadsafe(fn, *args)
                return
        fn(*args)

    def _on_pipeline_depth(self, name: str, value) -> None:
        """osd_pg_pipeline_depth observer: hot-resize the live per-PG
        admission window."""
        self._run_on_loop(self.op_queue.set_pipeline_depth, int(value))

    def _apply_qos_knobs(self) -> None:
        """Push every osd_mclock_* value into the live scheduler."""
        cfg = self.config
        profiles: dict = {}
        raw = cfg.get("osd_mclock_tenant_profiles")
        if raw:
            try:
                parsed = json.loads(raw)
                if isinstance(parsed, dict):
                    profiles = {str(k): v for k, v in parsed.items()
                                if isinstance(v, dict)}
            except (ValueError, TypeError):
                dout("osd", 1, f"osd.{self.whoami}: bad "
                               f"osd_mclock_tenant_profiles JSON ignored")
        self.op_queue.configure_qos(
            cost_per_io_bytes=cfg.get("osd_mclock_cost_per_io_bytes"),
            client_reservation=cfg.get("osd_mclock_client_reservation"),
            client_limit=cfg.get("osd_mclock_client_limit"),
            client_weight=cfg.get("osd_mclock_client_weight"),
            tenant_profiles=profiles,
            overload_policy=cfg.get("osd_mclock_overload_policy"),
            shed_queue_depth=cfg.get("osd_mclock_shed_queue_depth"),
            class_params={"recovery": {
                "reservation": cfg.get("osd_mclock_recovery_reservation"),
                "limit": cfg.get("osd_mclock_recovery_limit"),
                "weight": cfg.get("osd_mclock_recovery_weight")},
                "scrub": {
                "reservation": cfg.get("osd_mclock_scrub_reservation"),
                "limit": cfg.get("osd_mclock_scrub_limit"),
                "weight": cfg.get("osd_mclock_scrub_weight")},
                "snaptrim": {
                "reservation": cfg.get("osd_mclock_snaptrim_reservation"),
                "limit": cfg.get("osd_mclock_snaptrim_limit"),
                "weight": cfg.get("osd_mclock_snaptrim_weight")}})

    def _on_qos_knobs(self, name: str, value) -> None:
        """osd_mclock_* observer: the enable toggle migrates queued
        work (loop-bound); parameter knobs re-resolve every live
        entity's tags."""
        if name == "osd_mclock_enabled":
            self._run_on_loop(self.op_queue.set_mclock_enabled,
                              bool(value))
        else:
            self._run_on_loop(self._apply_qos_knobs)

    def _on_recovery_slots(self, name: str, value) -> None:
        """osd_max_recovery_in_flight observer: resize the live slot
        pool."""
        self._run_on_loop(self.recovery_reservations.resize, int(value))

    def _on_scrub_slots(self, name: str, value) -> None:
        """osd_max_scrubs observer: resize the live scrub slot pool."""
        self._run_on_loop(self.scrub_reservations.resize, int(value))

    def _inject_admin(self, req: dict) -> dict:
        """`inject` admin-socket verbs — the same injector the config
        knobs and the failure-storm bench drive."""
        what = req.get("what", "status")
        if what == "status":
            return faultinject.status()
        if what in ("msg", "device"):
            # one-shot rules are consulted behind the armed() gate:
            # arming them with the injector disabled would be a silent
            # no-op (crash/hang/bitrot fire unconditionally) — auto-arm
            # and say so, `config set fault_inject_enabled false`
            # disarms as usual
            armed_now = not faultinject.armed()
            if armed_now:
                faultinject.set_enabled(True)
            if what == "msg":
                rule = faultinject.arm_oneshot(
                    entity=req.get("entity"), msg_type=req.get("type"),
                    action=req.get("action", "drop"),
                    count=int(req.get("count", 1)),
                    delay_ms=req.get("delay_ms"))
                return {"injected": "msg", "rule": rule,
                        "armed": armed_now}
            pending = faultinject.arm_device_failures(
                int(req.get("count", 1)))
            return {"injected": "device", "pending": pending,
                    "armed": armed_now}
        loop = self._loop
        if loop is None or loop.is_closed():
            return {"error": "daemon not running"}
        if what == "crash":
            loop.call_soon_threadsafe(self._start_crash_task)
            return {"injected": "crash"}
        if what == "hang":
            seconds = float(req.get("seconds", 5.0))
            loop.call_soon_threadsafe(self._set_hang, seconds)
            return {"injected": "hang", "seconds": seconds}
        if what == "bitrot":
            import concurrent.futures
            fut = asyncio.run_coroutine_threadsafe(
                self._inject_bitrot(req["oid"], req.get("offset")), loop)
            try:
                return fut.result(timeout=5.0)
            except concurrent.futures.TimeoutError:
                fut.cancel()
                return {"error": "bitrot injection timed out"}
        return {"error": f"unknown inject target {what!r}"}

    def _start_crash_task(self) -> None:
        if self._crash_task is None or self._crash_task.done():
            self._crash_task = asyncio.get_running_loop().create_task(
                self.fault_crash())

    def _set_hang(self, seconds: float) -> None:
        self._hang_until = time.monotonic() + max(0.0, seconds)
        dout("osd", 1, f"osd.{self.whoami} injected hang for "
                       f"{seconds:.1f}s (dispatch + heartbeats muted)")

    async def fault_crash(self, reason: str = "injected crash") -> None:
        """Injected daemon death: record the crash, then tear down —
        peers find out through heartbeat silence, exactly like a kill."""
        crash.record(f"osd.{self.whoami}", RuntimeError(reason),
                     backtrace="(injected)")
        await self.stop()

    async def _inject_bitrot(self, oid: str,
                             offset=None) -> dict:
        """Flip one byte of the local shard blob of `oid` (any PG),
        bypassing csum maintenance — on the loop, so it cannot race a
        concurrent apply."""
        for pg in self.pgs.values():
            if not pg.backend.local_exists(oid):
                continue
            cid = pg.backend.coll()
            gh = pg.backend.ghobject(oid)
            size = len(self.store.read(cid, gh))
            if size == 0:
                return {"error": f"{oid!r} is empty on osd.{self.whoami}"}
            off = int(offset) if offset is not None else size // 2
            if self.store.corrupt(cid, gh, off):
                dout("osd", 1, f"osd.{self.whoami} injected bitrot in "
                               f"{oid!r} at offset {off}")
                return {"injected": "bitrot", "oid": oid, "offset": off,
                        "size": size}
        return {"error": f"no local shard of {oid!r} on "
                         f"osd.{self.whoami}"}

    def _mgr_progress(self) -> list:
        """Completion fractions for in-flight recovery/backfill (the
        reference progress module's events, fed through MMgrReport)."""
        out = []
        for pg in self.pgs.values():
            total = getattr(pg, "recovery_total", 0)
            remaining = len(pg._pending_recovery)
            if total and remaining:
                out.append({
                    "id": f"recovery-{pg.pgid.pool}.{pg.pgid.ps}",
                    "message": f"recovery of pg "
                               f"{pg.pgid.pool}.{pg.pgid.ps}",
                    "progress": round(
                        max(0.0, (total - remaining)) / total, 4)})
            prog = getattr(pg, "scrub_progress", None)
            if prog is not None and prog.state == "scrubbing" \
                    and prog.objects_total:
                out.append({
                    "id": f"scrub-{pg.pgid.pool}.{pg.pgid.ps}",
                    "message": f"{'deep-' if prog.deep else ''}scrub of "
                               f"pg {pg.pgid.pool}.{pg.pgid.ps}",
                    "progress": round(
                        min(prog.objects_scrubbed, prog.objects_total)
                        / prog.objects_total, 4)})
        return out

    def _spawn_scrubs(self, deep: bool) -> dict[str, asyncio.Task]:
        """One scrub task per primary active PG, each held in _bg_tasks
        (reaped at stop(), failures crash-recorded) AND returned by
        handle so callers can await real per-PG results."""
        tasks: dict[str, asyncio.Task] = {}
        for pgid, pg in list(self.pgs.items()):
            if pg.is_primary() and pg.state == "active":
                task = asyncio.get_running_loop().create_task(
                    pg.scrub(deep=deep))
                # hold a strong ref (the loop keeps only a weak one) and
                # surface repair failures in the log
                self._bg_tasks.add(task)
                task.add_done_callback(self._bg_task_done)
                tasks[f"{pgid.pool}.{pgid.ps}"] = task
        return tasks

    def _trigger_scrub(self, deep: bool) -> dict:
        """Kick a scrub of every primary PG. From the loop the tasks
        are spawned inline; from an admin-socket thread the spawn hops
        to the daemon's loop (tasks can only be created there) and the
        reply lists the PGs that will be scheduled."""
        try:
            on_loop = asyncio.get_running_loop() is self._loop
        except RuntimeError:
            on_loop = False
        if on_loop:
            pgs = sorted(self._spawn_scrubs(deep))
        else:
            pgs = sorted(f"{pgid.pool}.{pgid.ps}"
                         for pgid, pg in list(self.pgs.items())
                         if pg.is_primary() and pg.state == "active")
            self._run_on_loop(self._spawn_scrubs, deep)
        return {"scheduled": len(pgs), "deep": deep, "pgs": pgs}

    async def scrub_all(self, deep: bool = False) -> dict[str, dict]:
        """Scrub every primary PG and return {pg: result} — the awaited
        form of the fire-and-forget `scrub` admin verb. Waits without
        cancelling; a failed PG's slot is None (the failure is already
        crash-recorded by _bg_task_done)."""
        tasks = self._spawn_scrubs(deep)
        await drain_all(tasks.values())
        out: dict[str, dict] = {}
        for key, task in tasks.items():
            out[key] = (task.result()
                        if not task.cancelled()
                        and task.exception() is None else None)
        return out

    def _list_inconsistent(self, pool=None) -> dict:
        """Admin `list-inconsistent-obj`: the per-PG registries of every
        primary PG, newest scrub knowledge (the `rados
        list-inconsistent-obj` analog)."""
        out: dict = {}
        for pgid, pg in self.pgs.items():
            if not pg.is_primary():
                continue
            if pool is not None and pgid.pool != int(pool):
                continue
            if pg.inconsistent_objects:
                out[f"{pgid.pool}.{pgid.ps}"] = [
                    dict(e) for _, e in
                    sorted(pg.inconsistent_objects.items())]
        return {"inconsistent": out,
                "objects": sum(len(v) for v in out.values())}

    def _scrub_health_metrics(self) -> dict:
        """The scrub slice of the mgr health report: cluster health
        checks (PG_DAMAGED / OSD_SCRUB_ERRORS) key off the registry
        counts; the per-pool table feeds DaemonStateIndex
        .scrub_aggregate() -> the ceph_scrub_*{pool=} exporter
        families."""
        inconsistent = unrepaired = damaged_pgs = 0
        pools: dict[str, dict] = {}
        now = time.time()
        for pgid, pg in self.pgs.items():
            if not pg.is_primary():
                continue
            name = getattr(pg.pool, "name", None) or str(pgid.pool)
            p = pools.setdefault(name, {
                "objects_scrubbed": 0, "bytes_hashed": 0,
                "errors_found": 0, "errors_repaired": 0,
                "inconsistent": 0, "unrepaired": 0,
                "last_scrub_age_s": -1.0, "last_deep_scrub_age_s": -1.0})
            st = pg.scrub_stats
            p["objects_scrubbed"] += st["objects_scrubbed"]
            p["bytes_hashed"] += st["bytes_hashed"]
            p["errors_found"] += st["errors_found"]
            p["errors_repaired"] += st["errors_repaired"]
            reg = pg.inconsistent_objects
            n_unrep = sum(1 for e in reg.values() if not e["repaired"])
            p["inconsistent"] += len(reg)
            p["unrepaired"] += n_unrep
            inconsistent += len(reg)
            unrepaired += n_unrep
            if reg:
                damaged_pgs += 1
            for stamp, key in ((pg.last_scrub_stamp, "last_scrub_age_s"),
                               (pg.last_deep_scrub_stamp,
                                "last_deep_scrub_age_s")):
                if stamp:
                    age = round(now - stamp, 1)
                    if p[key] < 0 or age > p[key]:
                        p[key] = age
        return {"inconsistent_objects": inconsistent,
                "unrepaired_objects": unrepaired,
                "inconsistent_pgs": damaged_pgs,
                "pools": pools}

    def _bg_task_done(self, task: asyncio.Task) -> None:
        self._bg_tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            e = task.exception()
            dout("osd", 1, f"osd.{self.whoami} background task failed: "
                           f"{type(e).__name__} {e}")
            # a swallowed fatal exception leaves a crash record behind:
            # surfaced as RECENT_CRASH through the mgr report path and
            # listable via `crash ls`
            crash.record(f"osd.{self.whoami}", e)

    async def _scrub_loop(self) -> None:
        """Background scrub scheduler: every SCRUB_INTERVAL, scrub each
        PG this OSD is primary of (the reference's OSD::sched_scrub);
        every DEEP_SCRUB_EVERY-th round re-reads data (deep)."""
        rounds = 0
        last = time.monotonic()
        while True:
            # sleep in short slices so a runtime `config set
            # osd_scrub_interval` takes effect without waiting out the
            # previous interval
            interval = self.config.get("osd_scrub_interval")
            await asyncio.sleep(min(1.0, interval / 4))
            if time.monotonic() - last < interval:
                continue
            last = time.monotonic()
            rounds += 1
            deep = rounds % self.config.get("osd_deep_scrub_every") == 0
            # per-PG tasks with real handles: failures are crash-
            # recorded by _bg_task_done, stragglers are reaped at
            # stop() via _bg_tasks — nothing fire-and-forget
            await self.scrub_all(deep=deep)

    async def _reboot_until_up(self) -> None:
        """Resend MOSDBoot until the map shows us up again (mirrors the
        resend loop in start(); survives mon churn mid-send)."""
        while not self._stopping:
            if self._hang_until and time.monotonic() < self._hang_until:
                # injected hang: a wedged daemon cannot re-boot either —
                # the mark-down must stick until the hang lifts
                await asyncio.sleep(0.2)
                continue
            me = self.osdmap.osds.get(self.whoami)
            if me is not None and me.up and self._same_addr(me.addr):
                return
            try:
                await self.monc.send_boot(self.whoami, self.addr,
                                          crush_location=self.crush_location)
            except Exception as e:
                dout("osd", 5, f"osd.{self.whoami} re-boot send failed: "
                               f"{type(e).__name__} {e}")
            await asyncio.sleep(2.0)

    async def stop(self) -> None:
        if self._stop_event is not None:
            # a stop is already running (or done): wait it out so the
            # caller never proceeds while teardown is mid-flight
            await self._stop_event.wait()
            return
        self._stop_event = asyncio.Event()
        self._stopping = True
        try:
            bg = [t for t in (self._hb_task, self._scrub_task,
                              self._reboot_task) if t is not None]
            # background + detached-notify tasks too: anything left
            # pending when the loop closes is destroyed (messenger
            # leak's sibling)
            bg += list(self._bg_tasks) + list(self._notify_tasks)
            await reap_all(bg)
            self._bg_tasks.clear()
            self._notify_tasks.clear()
            for pg in self.pgs.values():
                pg._cancel_peering()
                pg.backend.fail_inflight("osd stopping")
            for waiting in self._waiting_for_active.values():
                for _, _, _, trk in waiting:
                    trk.finish()
            self._waiting_for_active.clear()
            await self.op_queue.stop()
            await self.finisher.stop()
            if self.asok is not None:
                self.asok.stop()
            await self.mgr_client.stop()
            await self.monc.close()
            await self.messenger.shutdown()
            # coalesced persist flush LAST, after the messenger is down:
            # a sub-op dispatched mid-teardown re-arms the call_soon
            # flush, and an earlier flush would leave that dirty delta
            # to fire after umount (applied data without its log entry)
            for pg in self.pgs.values():
                pg.flush_persist()
            self.store.umount()
        finally:
            self._stop_event.set()

    # -- osdmap plane --------------------------------------------------------

    async def _on_osdmap(self, payload: dict) -> None:
        changed = False
        if payload.get("full") is not None:
            full = payload["full"]
            if full["epoch"] > self.osdmap.epoch:
                self.osdmap.load_dict(full)
                changed = True
        for raw in payload.get("incrementals", []):
            inc_dict = json.loads(raw) if isinstance(raw, str) else raw
            inc = Incremental.from_dict(inc_dict)
            if inc.epoch <= self.osdmap.epoch:
                continue
            if inc.epoch != self.osdmap.epoch + 1:
                # gap: ask the mon for the full map instead
                self.monc.subscribe("osdmap", self.osdmap.epoch + 1)
                break
            self.osdmap.apply_incremental(inc)
            changed = True
        if not changed:
            return
        self.monc.sub_got("osdmap", self.osdmap.epoch)
        me = self.osdmap.osds.get(self.whoami)
        if me is not None and me.up and self._same_addr(me.addr):
            self._booted.set()
        elif self._booted.is_set() and me is not None and not me.up \
                and not self._stopping:
            # we are alive but the map says down (wrongly marked):
            # re-boot, as the reference OSD does on a spurious mark-down
            if self._reboot_task is None or self._reboot_task.done():
                dout("osd", 1, f"osd.{self.whoami} wrongly marked down; "
                               f"re-booting")
                self._reboot_task = asyncio.get_running_loop().create_task(
                    self._reboot_until_up())
                t = asyncio.get_running_loop().create_task(
                    self.monc.send_log(
                        "WRN", f"osd.{self.whoami}",
                        "map wrongly marked me down; re-booting"))
                self._bg_tasks.add(t)
                t.add_done_callback(self._bg_task_done)
        for peer in list(self._conns):
            if not self.osdmap.is_up(peer):
                self._drop_conn(peer)
        self._advance_pgs()

    def _same_addr(self, addr) -> bool:
        if self.addr is None:
            return False
        return tuple(addr) == tuple(self.addr) if addr else False

    def _advance_pgs(self) -> None:
        """Scan every pool's PGs; host the ones whose acting set includes
        us, advance intervals on the rest (OSD::activate_map)."""
        for pool in self.osdmap.pools.values():
            for ps in range(pool.pg_num):
                pgid = PG(pool.id, ps)
                up, acting = self.osdmap.pg_to_up_acting_osds(pgid)
                mine = self.whoami in acting
                inst = self.pgs.get(pgid)
                if inst is None:
                    if not mine:
                        continue
                    inst = PGInstance(self, pgid, pool)
                    self.pgs[pgid] = inst
                # pool records mutate across epochs (snap create/rm):
                # the PG must see the current one, then react to newly
                # removed snaps
                inst.pool = pool
                inst.advance_map(up, acting)
                inst.maybe_snaptrim()
        # parked ops whose PG lost primacy (or went straight to active)
        # must not wait forever
        for pgid in list(self._waiting_for_active):
            pg = self.pgs.get(pgid)
            if pg is not None:
                if pg.state == "active" or not pg.is_primary():
                    self.requeue_waiting(pg)
            else:
                for seq, conn, msg, trk in self._waiting_for_active.pop(
                        pgid, []):
                    trk.finish()
                    try:
                        conn.send_message(MOSDOpReply(
                            {"tid": msg.payload.get("tid", 0), "rc": -11,
                             "epoch": self.osdmap.epoch,
                             "error": "pg gone"}))
                    except Exception:
                        pass

    # -- cluster connections -------------------------------------------------

    def _osd_addr(self, osd: int) -> tuple[str, int]:
        a = self.osdmap.get_addr(osd)
        return (a[0], int(a[1]))

    async def send_osd(self, peer: int, msg: Message) -> None:
        addr = self._osd_addr(peer)
        conn = self._conns.get(peer)
        if conn is not None and (conn._closed
                                 or tuple(conn.peer_addr or ()) != addr):
            # the peer re-bound (restart => new port): a cached lossless
            # conn would replay into the void forever
            self._drop_conn(peer)
            conn = None
        if conn is None:
            conn = await self.messenger.connect(addr, Policy.lossless_peer())
            self._conns[peer] = conn
        conn.send_message(msg)

    def _drop_conn(self, peer: int) -> None:
        conn = self._conns.pop(peer, None)
        if conn is not None:
            # tracked: stop() reaps these, so a close racing daemon
            # teardown can't be destroyed while pending
            t = asyncio.get_running_loop().create_task(conn.close())
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_task_done)

    # -- heartbeats / failure reporting (OSD::heartbeat) ---------------------

    def _hb_peers(self) -> set[int]:
        peers: set[int] = set()
        for pg in self.pgs.values():
            if pg.state != "stray":
                peers |= pg.acting_peers()
        return peers

    async def _heartbeat(self) -> None:
        while True:
            await asyncio.sleep(self.config.get("osd_heartbeat_interval"))
            now = time.monotonic()
            if self._hang_until:
                if now < self._hang_until:
                    continue    # injected hang: no pings, no reports
                # hang lifted: the map pushes announcing our mark-down
                # were swallowed (the mon thinks it delivered them) —
                # re-request the map so the wrongly-marked-down re-boot
                # path sees the mark-down and recovers. The liveness
                # stamps also froze (ping replies were swallowed): left
                # stale, the very next tick would report EVERY healthy
                # peer failed — re-seed them instead
                self._hang_until = 0.0
                self._hb_last.clear()
                self._hb_reported.clear()
                dout("osd", 1, f"osd.{self.whoami} injected hang "
                               f"lifted; re-requesting osdmap")
                try:
                    await self.monc.request_osdmap(0)
                except Exception as e:
                    dout("osd", 3, f"osd.{self.whoami} post-hang map "
                                   f"request failed: "
                                   f"{type(e).__name__} {e}")
            for peer in self._hb_peers():
                if not self.osdmap.is_up(peer):
                    self._hb_last.pop(peer, None)
                    self._hb_reported.discard(peer)
                    continue
                last = self._hb_last.setdefault(peer, now)
                if now - last > self.config.get("osd_heartbeat_grace"):
                    if peer not in self._hb_reported:
                        self._hb_reported.add(peer)
                        try:
                            await self.monc.report_failure(peer, self.whoami)
                            self.perf.inc("heartbeat_failures")
                            dout("osd", 2, f"osd.{self.whoami} reported "
                                           f"osd.{peer} down")
                            flight.record(
                                "heartbeat_failure", f"osd.{peer}",
                                reporter=self.whoami,
                                silent_s=round(now - last, 2))
                        except Exception:
                            self._hb_reported.discard(peer)
                        else:
                            # best-effort: a failed clog line must not
                            # un-record the (delivered) failure report
                            try:
                                await self.monc.send_log(
                                    "WRN", f"osd.{self.whoami}",
                                    f"no heartbeat reply from osd.{peer} "
                                    f"for {now - last:.1f}s; reported "
                                    f"failed")
                            except Exception:
                                pass
                    continue
                try:
                    await self.send_osd(peer, MPing(
                        {"stamp": now, "from": self.whoami}))
                except Exception:
                    self._drop_conn(peer)

    # -- dispatch ------------------------------------------------------------

    def ms_handle_reset(self, conn: Connection) -> None:
        """A client connection died: its watches die with it (watchers
        linger-re-register over a fresh connection)."""
        for pg in self.pgs.values():
            pg.drop_watchers_for_conn(conn)

    async def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if self._hang_until and time.monotonic() < self._hang_until:
            # injected hang: swallow everything (pings AND the map
            # pushes the MonClient would otherwise consume after us in
            # the chain) so peers see heartbeat silence, report us
            # failed, and the mon marks us down
            return True
        if isinstance(msg, MPing):
            # the reply must name the RESPONDER: the pinger keys its
            # liveness table by who answered, not by who asked
            conn.send_message(MPingReply(
                {"stamp": msg.payload.get("stamp"), "from": self.whoami}))
            return True
        if isinstance(msg, MPingReply):
            peer = msg.payload.get("from")
            if peer is not None:
                self._hb_last[peer] = time.monotonic()
                self._hb_reported.discard(peer)
            return True
        if isinstance(msg, MOSDOp):
            self._ingest_op(conn, msg)
            return True
        if isinstance(msg, MOSDRepOp):
            pg = self._pg_of(msg)
            if pg is not None:
                await pg.backend.handle_rep_op(conn, msg)
                self.perf.inc("subop")
            return True
        if isinstance(msg, MOSDRepOpReply):
            pg = self._pg_of(msg)
            if pg is not None:
                pg.backend.sub_op_ack(msg.payload["tid"],
                                      msg.payload["from"])
            return True
        if isinstance(msg, MOSDPGQuery):
            pg = self._pg_of(msg)
            if pg is not None:
                await pg.handle_query(conn, msg)
            return True
        if isinstance(msg, MOSDPGLog):
            pg = self._pg_of(msg)
            if pg is not None:
                pg.handle_log(msg)
            return True
        if isinstance(msg, MOSDPGPush):
            pg = self._pg_of(msg, create=True)
            if pg is not None:
                await pg.handle_push(conn, msg)
            return True
        if isinstance(msg, MOSDPGPushReply):
            return True
        if isinstance(msg, MOSDPGInfo):
            pg = self._pg_of(msg, create=True)
            if pg is not None:
                if msg.payload.get("op") == "activate":
                    pg.handle_activate(msg)
                elif msg.payload.get("op") == "recovering":
                    pg.handle_recovering(msg)
            return True
        if isinstance(msg, MOSDRepScrub):
            pg = self._pg_of(msg)
            if pg is not None:
                await pg.handle_scrub_request(conn, msg)
            return True
        if isinstance(msg, MOSDRepScrubMap):
            pg = self._pg_of(msg)
            if pg is not None:
                pg.handle_scrub_map(msg)
            return True
        if isinstance(msg, MOSDScrubReserve):
            pg = self._pg_of(msg, create=True)
            if pg is not None:
                if msg.payload.get("op") == "reserve":
                    # a reserve can park on the slot pool for seconds:
                    # never on the dispatch loop, or every other
                    # message from this peer (replication sub-ops,
                    # heartbeats on shared conns) stalls behind it
                    t = asyncio.get_running_loop().create_task(
                        scrub_mod.handle_scrub_reserve(self, pg, msg))
                    self._notify_tasks.add(t)
                    t.add_done_callback(self._notify_tasks.discard)
                else:
                    await scrub_mod.handle_scrub_reserve(self, pg, msg)
            return True
        from ceph_tpu.msg.messages import MWatchNotifyAck
        if isinstance(msg, MWatchNotifyAck):
            pg = self._pg_of(msg)
            if pg is not None:
                pg.handle_notify_ack(msg)
            return True
        return await self._dispatch_backend(conn, msg)

    async def _dispatch_backend(self, conn: Connection,
                                msg: Message) -> bool:
        """EC sub-op messages are routed to the PG's ECBackend."""
        from ceph_tpu.msg.messages import (MOSDECSubOpRead,
                                           MOSDECSubOpReadReply,
                                           MOSDECSubOpWrite,
                                           MOSDECSubOpWriteReply)
        if isinstance(msg, (MOSDECSubOpWrite, MOSDECSubOpRead)):
            pg = self._pg_of(msg, create=True)
            if pg is not None:
                await pg.backend.handle_sub_op(conn, msg)
                if isinstance(msg, MOSDECSubOpWrite):
                    self.perf.inc("subop")
            return True
        if isinstance(msg, (MOSDECSubOpWriteReply, MOSDECSubOpReadReply)):
            pg = self._pg_of(msg)
            if pg is not None:
                pg.backend.handle_sub_op_reply(msg)
            return True
        return False

    def _pg_of(self, msg: Message, create: bool = False) -> PGInstance | None:
        pool_id, ps = msg.payload["pgid"]
        pgid = PG(pool_id, ps)
        inst = self.pgs.get(pgid)
        if inst is None and create:
            pool = self.osdmap.pools.get(pool_id)
            if pool is None:
                return None
            inst = PGInstance(self, pgid, pool)
            up, acting = self.osdmap.pg_to_up_acting_osds(pgid)
            self.pgs[pgid] = inst
            inst.advance_map(up, acting)
        return inst

    # -- op ingest: enqueue_op -> sharded queue -> dequeue_op ---------------
    # (src/osd/OSD.cc:9683 enqueue_op, :9742 dequeue_op; per-PG hashing
    # keeps same-PG ops FIFO while shards run concurrently)

    @staticmethod
    def _op_identity(conn: Connection,
                     p: dict) -> tuple[str | None, str | None]:
        """Client identity of an op: the session's handshake entity is
        authoritative (it was negotiated before any op flowed); the
        MOSDOp stamp is the fallback for paths where the originating
        session is gone (requeues after a reset). Non-client peers
        (OSD-to-OSD MOSDOp never happens, but belt-and-braces) are not
        accounted."""
        name = conn.peer_name if conn.peer_name.startswith("client") \
            else p.get("client")
        if not name or not str(name).startswith("client"):
            return None, None
        tenant = getattr(conn, "peer_tenant", None) or p.get("tenant")
        return str(name), (str(tenant) if tenant else None)

    def _ingest_op(self, conn: Connection, msg: MOSDOp) -> None:
        p = msg.payload
        pool_id, ps = p["pgid"]
        pgid = PG(pool_id, ps)
        pg = self.pgs.get(pgid)
        if pg is None or not pg.is_primary():
            conn.send_message(MOSDOpReply(
                {"tid": p.get("tid", 0), "rc": -11,
                 "epoch": self.osdmap.epoch, "error": "not primary"}))
            return
        ops = p.get("ops", [])
        client, tenant = self._op_identity(conn, p)
        desc = (f"osd_op({'+'.join(o.get('op', '?') for o in ops)} "
                f"{ops[0].get('oid', '') if ops else ''} "
                f"pg={pgid.pool}.{pgid.ps} tid={p.get('tid', 0)})")
        if any(o.get("op") == "notify" for o in ops):
            # notify gathers watcher acks for seconds: it must NOT hold
            # an op-queue shard, or a watcher callback touching the same
            # PG (the RBD header-watch pattern) deadlocks behind it —
            # the reference routes notifies outside the write pipeline.
            # Still tracked + counted like any other op.
            trk = self.optracker.create(desc, client=client,
                                        tenant=tenant)
            trk.trace = tracer.current_context()
            trk.mark_event("detached_notify")
            t = asyncio.get_running_loop().create_task(
                self._execute_op(conn, msg, trk))
            self._notify_tasks.add(t)
            t.add_done_callback(self._notify_tasks.discard)
            return
        trk = self.optracker.create(desc, client=client, tenant=tenant)
        # the trace context (the connection's ms_dispatch span) rides the
        # TrackedOp: the queued closure runs in a shard worker task where
        # the dispatch context is gone
        trk.trace = tracer.current_context()
        trk.mark_event("queued")
        self._op_seq += 1
        seq = self._op_seq
        if pg.state != "active" or self._waiting_for_active.get(pgid):
            self._park_op(pgid, seq, conn, msg, trk)
            return
        self._enqueue_op(pgid, seq, conn, msg, trk)

    def _park_op(self, pgid: PG, seq: int, conn, msg, trk) -> None:
        import bisect
        trk.mark_event("waiting_for_active")
        waiting = self._waiting_for_active.setdefault(pgid, [])
        bisect.insort(waiting, (seq, conn, msg, trk), key=lambda e: e[0])

    async def _execute_op(self, conn: Connection, msg: MOSDOp, trk,
                          queue_wait_us: float | None = None) -> None:
        """Run one tracked client op with its span + perf accounting —
        the single site for op latency bookkeeping (detached notifies
        and queued ops both land here)."""
        token = set_current_op(trk)
        t0 = time.monotonic()
        try:
            with tracer.span("osd_op", f"osd.{self.whoami}",
                             parent=trk.trace) as sp:
                if sp is not None:
                    sp.set_tag("desc", trk.description)
                    if queue_wait_us is not None:
                        sp.set_tag("queue_wait_us", queue_wait_us)
                await self._handle_op(conn, msg)
        finally:
            reset_current_op(token)
            trk.finish()
            self.perf.inc("op")
            lat = time.monotonic() - t0
            self.perf.avg_add("op_latency", lat)
            self.perf.hist_add("op_total_us", lat * 1e6)

    @staticmethod
    def _op_object(msg: MOSDOp) -> str | None:
        """The object stream a client op belongs to, for the pipelined
        window's per-object FIFO. None (an exclusive whole-PG barrier)
        when the op vector names no single object — multi-object
        messages and listings keep the legacy serial semantics."""
        oids = {o.get("oid") for o in msg.payload.get("ops", [])}
        if len(oids) == 1:
            oid = oids.pop()
            if oid is not None:
                return oid
        return None

    def _enqueue_op(self, pgid: PG, seq: int, conn: Connection,
                    msg: MOSDOp, trk) -> None:
        t_enq = time.monotonic()

        async def work():
            # the PG may have left 'active' while this op sat in the
            # queue: re-park instead of wedging the shard worker on a
            # peering PG (the reference requeues into waiting_for_active)
            pg = self.pgs.get(pgid)
            if pg is not None and pg.is_primary() and pg.state != "active":
                self._park_op(pgid, seq, conn, msg, trk)
                return
            trk.mark_event("dequeued")
            wait_us = (time.monotonic() - t_enq) * 1e6
            self.perf.hist_add("op_queue_wait_us", wait_us)
            await self._execute_op(conn, msg, trk,
                                   queue_wait_us=round(wait_us, 1))
        p = msg.payload
        nbytes = len(msg.data) or sum(int(o.get("len") or 0)
                                      for o in p.get("ops", []))
        admitted = self.op_queue.enqueue(
            (pgid.pool, pgid.ps), work, obj=self._op_object(msg),
            entity=trk.tenant or trk.client, nbytes=nbytes)
        if not admitted:
            # shed admission control: the tenant's backlog is past the
            # depth cap — refuse with a pacing hint instead of letting
            # queue depth and p99 run away. The client resends the
            # same tid after the backoff; no map refresh (the map is
            # fine, the tenant is over its share).
            trk.mark_event("qos_shed")
            trk.finish()
            try:
                conn.send_message(MOSDOpThrottle(
                    {"tid": p.get("tid", 0), "rc": -11,
                     "retry_after_ms": 50,
                     "epoch": self.osdmap.epoch}))
            except Exception:
                pass

    def requeue_waiting(self, pg: PGInstance) -> None:
        """PG activation (or loss of primacy) drains its parked ops in
        ingest order (the reference requeues waiting_for_active)."""
        waiting = self._waiting_for_active.pop(pg.pgid, None)
        if not waiting:
            return
        for seq, conn, msg, trk in waiting:
            if pg.is_primary() and pg.state == "active":
                trk.mark_event("requeued_after_activation")
                self._enqueue_op(pg.pgid, seq, conn, msg, trk)
            else:
                trk.mark_event("dropped_not_primary")
                trk.finish()
                try:
                    conn.send_message(MOSDOpReply(
                        {"tid": msg.payload.get("tid", 0), "rc": -11,
                         "epoch": self.osdmap.epoch,
                         "error": "not primary"}))
                except Exception:
                    pass

    async def _handle_op(self, conn: Connection, msg: MOSDOp) -> None:
        p = msg.payload
        tid = p.get("tid", 0)
        pool_id, ps = p["pgid"]
        pgid = PG(pool_id, ps)
        pg = self.pgs.get(pgid)
        if pg is None or not pg.is_primary():
            # wrong (or stale) target: tell the client to refresh its map
            conn.send_message(MOSDOpReply(
                {"tid": tid, "rc": -11, "epoch": self.osdmap.epoch,
                 "error": "not primary"}))
            return
        trk = current_op()
        if trk is not None and trk.client:
            # kind is known before execution so even an errored op's
            # latency lands in the right per-client histogram
            trk.kind = classify_ops(p.get("ops", []))
        try:
            results = []
            outdata = b""
            for i, op in enumerate(p.get("ops", [])):
                if p.get("reqid"):
                    # one dedup key per op within the message: multi-op
                    # messages must not collide in the dup index
                    op = dict(op, reqid=[*p["reqid"], i])
                rc, out, opdata = await pg.do_op(op, msg.data, conn=conn)
                results.append({"rc": rc, "out": out})
                outdata += opdata
                if rc < 0:
                    break
            final_rc = results[-1]["rc"] if results else 0
            if trk is not None and trk.client:
                # byte attribution: reads are charged what they
                # returned; writes what they shipped — but a dup-op
                # replay (answered from the pg log, never re-executed)
                # charges NOTHING, so a client's resends can't inflate
                # its written-bytes ledger
                if trk.kind == "read":
                    trk.rd_bytes = len(outdata)
                elif trk.kind == "write" and any(
                        o.get("op") in WRITE_OP_KINDS
                        and r["rc"] == 0
                        and not (r.get("out") or {}).get("dup")
                        for o, r in zip(p.get("ops", []), results)):
                    trk.wr_bytes = len(msg.data)
            conn.send_message(MOSDOpReply(
                {"tid": tid, "rc": final_rc, "results": results,
                 "epoch": self.osdmap.epoch}, outdata))
        except asyncio.TimeoutError:
            conn.send_message(MOSDOpReply(
                {"tid": tid, "rc": -110, "epoch": self.osdmap.epoch,
                 "error": "sub-op timeout"}))
        except IntervalChange as e:
            # don't fail the client: it refreshes the map and resends,
            # landing on whoever is primary in the new interval
            conn.send_message(MOSDOpReply(
                {"tid": tid, "rc": -11, "epoch": self.osdmap.epoch,
                 "error": f"interval change: {e}"}))
        except Exception as e:
            conn.send_message(MOSDOpReply(
                {"tid": tid, "rc": -5, "epoch": self.osdmap.epoch,
                 "error": f"{type(e).__name__}: {e}"}))
