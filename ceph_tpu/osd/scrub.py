"""PG scrub: background verification + repair of replica/shard state.

Re-creation of the reference scrub machinery (src/osd/scrubber/
pg_scrubber.h:177 state machine, scrub_backend.h:101 per-shard map
compare, ECBackend.cc:1092-1120 deep shard verify):

  * the primary asks every acting peer for a SCRUB MAP — per object:
    size, attrs digest, and (deep) content digests; it builds its own
    map the same way;
  * client writes are gated out for the duration of a scrub round (the
    reference's scrub range write blocking) so repairs never race an
    acknowledged write;
  * maps are compared per object: corrupt shards are self-certified by
    the stored per-chunk crc on EC pools (or the store's blob crc on
    FileStore); replicated copies vote — ABSENCE VOTES TOO, so a stale
    holder cannot resurrect a deleted object — and only a strict
    majority is repaired toward (no majority = inconsistency reported,
    never guessed, matching the reference's refusal to auto-repair
    ambiguous objects);
  * repairs ride the existing recovery machinery: EC shards are
    reconstructed from k survivors and pushed; replicated copies
    converge on the majority fingerprint, pulled first if the primary
    itself is wrong.

Observability (the continuous-integrity layer):

  * deep-scrub content digests are BATCHED through the offload
    service's CrcJob path (`OffloadService.crc32c_blocks`) — one
    coalesced hash job per scan chunk instead of a per-chunk host loop,
    bit-identical to the `ec_native.crc32c` host fallback because both
    run the same slice-by-8 kernel with the same seed;
  * scans are CHUNKED (`osd_scrub_chunk_max` objects per grant, an
    optional `osd_scrub_sleep` pause between chunks) and each chunk
    pre-pays a zero-work grant token through the op queue under the
    declared background `scrub` class, so dmclock arbitration paces
    scrub against client I/O while its reservation guarantees forward
    progress;
  * every round updates per-PG progress (`pg.scrub_progress`), stamps
    (`last_scrub_stamp` / `last_deep_scrub_stamp`), cumulative
    `pg.scrub_stats`, and the per-PG inconsistent-object registry
    (`pg.inconsistent_objects`, the `list-inconsistent-obj` source);
    mismatches/repairs/aborts drop flight-recorder crumbs and the
    process-wide "scrub" perf logger rides the mgr report leg.

Idiomatic divergences: one round-trip map exchange instead of chunked
scrub reservations/ranges (PGs here are small); light scrub compares
size+attrs digests, deep scrub re-reads and re-hashes everything — same
split as the reference's shallow/deep modes.
"""
from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import TYPE_CHECKING

import numpy as np

from ceph_tpu.msg.messages import (MOSDRepScrub, MOSDRepScrubMap,
                                   MOSDScrubReserve)
from ceph_tpu.objectstore.store import StoreError
from ceph_tpu.utils import flight, sanitizer
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.perf_counters import (TYPE_HISTOGRAM,
                                          PerfCountersCollection)

if TYPE_CHECKING:
    from ceph_tpu.osd.pg import PGInstance

SCRUB_PEER_TIMEOUT = 10.0
#: bound on one range's wait for its QoS grant. Grants are taken with
#: the PG write gate OPEN (client writes flow while scrub waits its
#: turn), so there is no gate/queue deadlock — the bound is pure
#: robustness: the scheduler shapes scrub, it must never wedge it.
#: On timeout the range proceeds ungranted (counted + crumbed).
SCRUB_GRANT_TIMEOUT = 5.0
_SCAN_YIELD_EVERY = 32      # objects hashed between event-loop yields
_DIGEST_BLOCK = 4096        # replicated-pool digest batch block size

# fingerprint sentinel: the object does not exist on that OSD. A real
# value (not exclusion) so deletions can win the majority vote.
ABSENT = "__absent__"

_perf_lock = threading.Lock()


def scrub_perf():
    """The process-wide "scrub" perf logger, created on first use.
    Rides `perf dump` and the MgrClient report leg via extra_loggers
    (exported with the `scrub_` prefix: `scrub_bytes_hashed`, ...)."""
    coll = PerfCountersCollection.instance()
    with _perf_lock:
        pc = coll.get("scrub")
        if pc is not None:
            return pc
        pc = coll.create("scrub")
        pc.add("bytes_hashed",
               description="content bytes digested by deep scrub")
        pc.add("objects_hashed",
               description="objects whose content digests were computed")
        pc.add("rounds",
               description="scrub rounds completed on this node's "
                           "primary PGs")
        pc.add("deep_rounds",
               description="deep rounds among the completed rounds")
        pc.add("chunks",
               description="scan chunks processed (each chunk = one "
                           "QoS grant under the scrub class)")
        pc.add("errors_found",
               description="inconsistent copies/shards detected by "
                           "map compare")
        pc.add("errors_repaired",
               description="copies/shards repaired through the "
                           "recovery machinery")
        pc.add("errors_unrepaired",
               description="objects left unrepaired (no majority to "
                           "repair toward)")
        pc.add("aborts",
               description="scrub rounds that died on an exception or "
                           "cancellation")
        pc.add("grant_timeouts",
               description="scan chunks that proceeded after their QoS "
                           "grant timed out (forward-progress escape "
                           "hatch)")
        pc.add("reserve_failures",
               description="scrub rounds aborted because an acting-set "
                           "reservation timed out or was rejected (the "
                           "crossed-reservation deadlock breaker)")
        pc.add("digest_batch_blocks", type=TYPE_HISTOGRAM,
               description="blocks per offloaded digest batch")
        pc.add("digest_batch_us", type=TYPE_HISTOGRAM,
               description="wall microseconds per digest batch")
        return pc


class ScrubProgress:
    """Live progress of one scrub round, published at `pg.scrub_progress`
    while the round runs (mgr progress events + admin `last_scrub`)."""

    __slots__ = ("pgid", "deep", "state", "objects_total",
                 "objects_scrubbed", "bytes_hashed", "started_mono")

    def __init__(self, pgid, deep: bool):
        self.pgid = str(pgid)
        self.deep = deep
        self.state = "scrubbing"
        self.objects_total = 0
        self.objects_scrubbed = 0
        self.bytes_hashed = 0
        self.started_mono = time.monotonic()

    def finish(self, state: str = "done") -> None:
        self.state = state

    def to_dict(self) -> dict:
        dt = max(1e-9, time.monotonic() - self.started_mono)
        return {"pgid": self.pgid, "deep": self.deep, "state": self.state,
                "objects_scrubbed": self.objects_scrubbed,
                "objects_total": self.objects_total,
                "bytes_hashed": self.bytes_hashed,
                "bytes_per_s": round(self.bytes_hashed / dt, 1),
                "elapsed_s": round(dt, 3)}


def _cfg(pg: "PGInstance", name: str, default):
    try:
        v = pg.host.config.get(name)
        return default if v is None else v
    except Exception:
        return default


async def _qos_grant(pg: "PGInstance") -> None:
    """Pre-pay one scan chunk through the op queue under the declared
    background `scrub` class: the grant is a zero-work token billed at
    one IO cost unit, so dmclock paces scrub against client load and
    the class reservation guarantees it keeps moving. Bounded wait —
    see SCRUB_GRANT_TIMEOUT."""
    q = getattr(pg.host, "op_queue", None)
    if q is None:
        return
    done = asyncio.get_running_loop().create_future()

    async def work():
        if not done.done():
            done.set_result(None)

    # distinct key: the grant must not ride (and stall behind) this
    # PG's own client-write pipeline window
    if not q.enqueue(("scrub", pg.pgid.pool, pg.pgid.ps), work,
                     klass="scrub", nbytes=q.sched.cost_per_io_bytes):
        return
    try:
        await asyncio.wait_for(done, SCRUB_GRANT_TIMEOUT)
    except asyncio.TimeoutError:
        scrub_perf().inc("grant_timeouts")
        flight.record("scrub_grant_timeout", f"pg.{pg.pgid}",
                      waited_s=SCRUB_GRANT_TIMEOUT)


def _in_range(oid: str, oid_range) -> bool:
    """Membership in a half-open name range `(lo, hi]` (None = open
    end). Exclusive lo / inclusive hi so consecutive ranges sharing a
    boundary partition the namespace with no gap and no overlap."""
    lo, hi = oid_range
    return (lo is None or oid > lo) and (hi is None or oid <= hi)


async def build_scrub_map(pg: "PGInstance", deep: bool,
                          progress: "ScrubProgress | None" = None,
                          oid_range=None, paced: bool = True) -> dict:
    """Per-object scrub entries for the local store (the reference's
    build_scrub_map_chunk / be_scan_list). With `oid_range=(lo, hi]`
    only names inside the range are scanned — the primary drives the
    round range-by-range and peers answer for exactly the requested
    slice, so absence within a range map is authoritative. Chunked:
    every `osd_scrub_chunk_max` objects cost one QoS grant when
    `paced` (standalone/full builds; range scans are paced by the
    primary at the range level and run here with paced=False), deep
    content digests for a chunk are hashed as ONE offload batch, and
    an optional `osd_scrub_sleep` pause between chunks yields the disk
    to client I/O. Yields to the event loop periodically: a large deep
    scan must not stall heartbeats."""
    if pg.pool.type == "erasure" and (oid_range is None
                                      or oid_range[0] is None):
        # once per round, on the first range
        _gc_rollback_generations(pg)
    oids = sorted(pg.list_objects())
    if oid_range is not None:
        oids = [o for o in oids if _in_range(o, oid_range)]
    elif progress is not None:
        progress.objects_total = len(oids)
    chunk_max = max(1, int(_cfg(pg, "osd_scrub_chunk_max", 32)))
    sleep_s = float(_cfg(pg, "osd_scrub_sleep", 0.0))
    out: dict[str, dict] = {}
    for start in range(0, len(oids), chunk_max):
        chunk = oids[start:start + chunk_max]
        if paced:
            await _qos_grant(pg)
        await _scan_chunk(pg, chunk, deep, out, progress)
        scrub_perf().inc("chunks")
        if progress is not None:
            progress.objects_scrubbed += len(chunk)
        if paced and sleep_s > 0 and start + chunk_max < len(oids):
            await asyncio.sleep(sleep_s)
    return out


async def _scan_chunk(pg: "PGInstance", oids: list, deep: bool,
                      out: dict, progress: "ScrubProgress | None") -> None:
    """Scan one chunk of objects: metadata host-side, deep content
    digests deferred into one `_digest_batch` offload job."""
    from ceph_tpu.native import ec_native
    store = pg.host.store
    cid = pg.backend.coll()
    pend: list = []         # (oid, ent, data, csum-or-None)
    for i, oid in enumerate(oids):
        if i % _SCAN_YIELD_EVERY == _SCAN_YIELD_EVERY - 1:
            await asyncio.sleep(0)
        gh = pg.backend.ghobject(oid)
        ent: dict = {"corrupt": False}
        try:
            attrs = store.getattrs(cid, gh)
            st = store.stat(cid, gh)
            ent["size"] = st["size"]
            ent["attr_digest"] = ec_native.crc32c(
                b"\x00".join(k.encode() + b"=" + v
                             for k, v in sorted(attrs.items())))
            if pg.pool.type == "erasure":
                ent["shard"] = int(attrs.get("shard", b"-1"))
                ent["version"] = list(
                    json.loads(attrs.get("version", b"[0,0]")))
                csum = json.loads(attrs.get("csum", b"[]"))
                if deep:
                    data = store.read(cid, gh)
                    c = pg.backend.sinfo.chunk_size
                    if len(data) != len(csum) * c:
                        ent["corrupt"] = True
                    else:
                        pend.append((oid, ent, data, csum))
            elif deep:
                data = store.read(cid, gh)
                omap = store.omap_get(cid, gh)
                ent["omap_digest"] = ec_native.crc32c(
                    b"\x00".join(k.encode() + b"=" + v
                                 for k, v in sorted(omap.items())))
                pend.append((oid, ent, data, None))
        except StoreError as e:
            # a FileStore blob whose crc gate refuses the read is a
            # corrupt local copy — exactly what scrub exists to find
            dout("scrub", 1, f"scrub read {oid}: {e}")
            ent["corrupt"] = True
        out[oid] = ent
    if pend:
        await _digest_batch(pg, pend, progress)


async def _digest_batch(pg: "PGInstance", pend: list,
                        progress: "ScrubProgress | None") -> None:
    """Hash one chunk's content as a single crc32c block batch through
    the offload service (host fallback: the same `ec_native`
    slice-by-8 kernel — bit-identical either way). EC shards check the
    per-block crcs against the stored csum vector; replicated copies
    fold the block crcs into one whole-object digest."""
    from ceph_tpu.native import ec_native
    from ceph_tpu.offload.service import get_service_or_none
    perf = scrub_perf()
    t0 = time.perf_counter()
    ec = pg.pool.type == "erasure"
    block = pg.backend.sinfo.chunk_size if ec else _DIGEST_BLOCK
    batch: list[np.ndarray] = []
    counts: list[int] = []
    total_bytes = 0
    for oid, ent, data, csum in pend:
        n, tail = divmod(len(data), block)
        if tail:
            n += 1
            buf = np.zeros(n * block, dtype=np.uint8)
            buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        else:
            buf = np.frombuffer(data, dtype=np.uint8)
        if n:
            batch.append(buf.reshape(n, block))
        counts.append(n)
        total_bytes += len(data)
    nblocks = sum(counts)
    if nblocks:
        svc = get_service_or_none()
        if svc is not None:
            crcs = await svc.crc32c_blocks(batch, block)
        else:
            flat = np.concatenate([b.reshape(-1) for b in batch])
            crcs = ec_native.crc32c_blocks(flat, block)
        crcs = np.asarray(crcs, dtype=np.uint32)
    else:
        crcs = np.zeros(0, dtype=np.uint32)
    pos = 0
    for (oid, ent, data, csum), n in zip(pend, counts):
        mine = crcs[pos:pos + n]
        pos += n
        if ec:
            # the length check already ran; every stored csum entry has
            # a freshly hashed counterpart
            for s in range(len(csum)):
                if int(mine[s]) != int(csum[s]):
                    ent["corrupt"] = True
                    break
        else:
            ent["digest"] = _fold_digest(mine, len(data))
    perf.inc("bytes_hashed", total_bytes)
    perf.inc("objects_hashed", len(pend))
    perf.hist_add("digest_batch_blocks", nblocks)
    perf.hist_add("digest_batch_us",
                  (time.perf_counter() - t0) * 1e6)
    if progress is not None:
        progress.bytes_hashed += total_bytes


def _fold_digest(crcs: np.ndarray, total_len: int) -> int:
    """Whole-object digest from per-block crcs + true length (the tail
    block is zero-padded, so the length disambiguates). Deterministic
    pure function of (content, length): every OSD recomputes it per
    round, nothing is stored, so all replicas agree by construction."""
    from ceph_tpu.native import ec_native
    return ec_native.crc32c(
        np.asarray(crcs, dtype="<u4").tobytes()
        + int(total_len).to_bytes(8, "little"))


def _gc_rollback_generations(pg: "PGInstance") -> None:
    """Drop EC rollback generations (<oid>\\x00prev clones) whose main
    object is gone: scrub only runs on a healthy active PG with writes
    gated, so any divergence that could have needed them has already
    been resolved by peering. (Prevents deleted objects from leaking a
    prev clone forever.)"""
    from ceph_tpu.objectstore.store import Transaction
    from ceph_tpu.osd.ec_backend import PREV_SUFFIX
    store = pg.host.store
    cid = pg.backend.coll()
    live = set(pg.list_objects())
    for gh in list(store.collection_list(cid)):
        if not gh.name.endswith(PREV_SUFFIX):
            continue
        if gh.name[:-len(PREV_SUFFIX)] not in live:
            store.queue_transaction(Transaction().remove(cid, gh))


def _note_inconsistent(pg: "PGInstance", oid: str, bad_osds: list,
                       kind: str, deep: bool) -> None:
    """Register a detected inconsistency (the `list-inconsistent-obj`
    registry) and drop the flight crumb. Entries persist until a clean
    same-or-deeper round retires them, so PG_DAMAGED raises at
    detection and clears only on a verified-clean rescan."""
    flight.record("scrub_mismatch", f"pg.{pg.pgid}", oid=oid,
                  osds=list(bad_osds), kind=kind, deep=deep)
    pg.inconsistent_objects[oid] = {
        "oid": oid, "osds": sorted(bad_osds), "kind": kind,
        "deep": deep, "repaired": False,
        "pending": sorted(bad_osds), "stamp": time.time()}


def _note_repaired(pg: "PGInstance", oid: str, osd: int, ok: bool,
                   kind: str) -> None:
    flight.record("scrub_repair", f"pg.{pg.pgid}", oid=oid, osd=osd,
                  ok=ok, kind=kind)
    entry = pg.inconsistent_objects.get(oid)
    if entry is None or not ok:
        return
    entry["pending"] = [o for o in entry["pending"] if o != osd]
    if not entry["pending"]:
        entry["repaired"] = True


async def _reserve_acting_set(pg: "PGInstance",
                              tid: int) -> tuple[bool, list[int]]:
    """Claim one `osd_max_scrubs` slot on self and every up acting
    peer before the round may gate client writes (the reference's
    scrub reserver: OSD::sched_scrub + MOSDScrubReserve). Local slot
    first, then peers in ascending id, every wait bounded by
    `osd_scrub_reserve_timeout`: crossed reservations between two
    primaries therefore stall only until one side's timeout fires,
    releases everything it holds, and retries later — the abort path
    that breaks the cycle. While a remote wait is parked it is
    registered with lockdep under the PEER's slot name, which is the
    inter-OSD edge the in-process watchdog and the mgr's cross-daemon
    wait-for graph report."""
    host = pg.host
    sem = getattr(host, "scrub_reservations", None)
    if sem is None:
        return True, []
    timeout = float(_cfg(pg, "osd_scrub_reserve_timeout", 10.0))
    me = f"osd.{host.whoami}"
    try:
        await sem.acquire_timeout(timeout)
    except asyncio.TimeoutError:
        scrub_perf().inc("reserve_failures")
        flight.record("scrub_reserve_fail", f"pg.{pg.pgid}", tid=tid,
                      stage="local", waited_s=timeout)
        return False, []
    granted: list[int] = []
    released = False
    try:
        for peer in sorted(pg.acting_peers()):
            if not host.osdmap.is_up(peer):
                continue
            fut = asyncio.get_running_loop().create_future()
            pg._reserve_waiters[(tid, peer)] = fut
            token = sanitizer.lockdep_wait_start(
                f"osd.{peer}:scrub_reservations", kind="remote_reserve",
                entity=me, peer=peer, tid=tid, pgid=str(pg.pgid))
            ok, reason = False, "rejected"
            try:
                await host.send_osd(peer, MOSDScrubReserve(
                    {"pgid": [pg.pgid.pool, pg.pgid.ps], "tid": tid,
                     "from": host.whoami, "op": "reserve"}))
                ok = bool(await asyncio.wait_for(fut, timeout))
            except asyncio.TimeoutError:
                reason = "timeout"
            except Exception as e:
                reason = f"{type(e).__name__}: {e}"
            finally:
                sanitizer.lockdep_wait_end(token)
                pg._reserve_waiters.pop((tid, peer), None)
            if ok:
                granted.append(peer)
                continue
            scrub_perf().inc("reserve_failures")
            flight.record("scrub_reserve_fail", f"pg.{pg.pgid}", tid=tid,
                          stage=f"osd.{peer}", reason=reason,
                          waited_s=timeout)
            dout("scrub", 2, f"pg {pg.pgid} scrub reservation on "
                             f"osd.{peer} failed ({reason}): aborting "
                             f"round")
            released = True
            await _release_acting_set(pg, tid, granted)
            return False, []
    except BaseException:
        # a CancelledError (round reaped at daemon stop, drained round
        # interrupted) is not an Exception: without this the local slot
        # acquired above — and any grants already collected — would
        # leak, wedging every later round on this daemon's semaphore
        if not released:
            await _release_acting_set(pg, tid, granted)
        raise
    return True, granted


async def _release_acting_set(pg: "PGInstance", tid: int,
                              granted: list[int]) -> None:
    """Return the local slot and every remote grant of this round.
    Releasing local FIRST unparks any peer's reserve handler queued on
    our slot — in the crossed-primaries deadlock this is the edge that
    must break before the other side can make progress."""
    host = pg.host
    sem = getattr(host, "scrub_reservations", None)
    if sem is not None:
        sem.release()
    interrupted: asyncio.CancelledError | None = None
    for peer in granted:
        try:
            await host.send_osd(peer, MOSDScrubReserve(
                {"pgid": [pg.pgid.pool, pg.pgid.ps], "tid": tid,
                 "from": host.whoami, "op": "release"}))
        # deferred re-raise below: every granted peer must get its
        # release even when this round is being cancelled, or the
        # peer's slot stays taken until its own stale-grant churn
        # radoslint: disable-next=cancellation-swallow
        except asyncio.CancelledError as e:
            interrupted = e
        except Exception as e:
            dout("scrub", 2,
                 f"scrub reserve release to osd.{peer} failed: {e}")
    if interrupted is not None:
        raise interrupted


async def handle_scrub_reserve(host, pg: "PGInstance", msg) -> None:
    """Both halves of the reservation wire protocol.

    Replica (`op=reserve`): park — bounded — on the local slot on the
    requesting primary's behalf, then grant; a timeout rejects. The
    park is a real AdjustableSemaphore acquire, so it shows up in this
    daemon's lockdep waits/holders and in its mgr deadlock
    annotations.

    Primary (`op=grant|reject`): resolve the round's waiter. A grant
    with no waiter means the round already aborted; the slot is handed
    straight back (`op=release`) so a slow peer never leaks it.

    Anyone (`op=release`): free a slot previously granted to this
    requester."""
    p = msg.payload
    op, tid, frm = p.get("op"), p.get("tid"), p.get("from")
    key = (pg.pgid.pool, pg.pgid.ps, tid, frm)
    sem = getattr(host, "scrub_reservations", None)
    if op == "reserve":
        granted = True
        if sem is not None:
            # wait longer than the requester will: the reject path is
            # for a genuinely wedged slot, not a normally-busy one —
            # the primary's own timeout aborts first and the grant
            # that eventually lands is bounced back as stale
            timeout = 4.0 * float(_cfg(pg, "osd_scrub_reserve_timeout",
                                       10.0))
            try:
                await sem.acquire_timeout(timeout)
                host._scrub_remote_grants.add(key)
            except asyncio.TimeoutError:
                granted = False
        try:
            await host.send_osd(frm, MOSDScrubReserve(
                {"pgid": [pg.pgid.pool, pg.pgid.ps], "tid": tid,
                 "from": host.whoami,
                 "op": "grant" if granted else "reject"}))
        except asyncio.CancelledError:
            # handler reaped mid-reply (daemon stop): the grant never
            # reached the requester, so nobody will ever release it —
            # hand the slot back before unwinding
            if granted and sem is not None:
                host._scrub_remote_grants.discard(key)
                sem.release()
            raise
        except Exception as e:
            dout("scrub", 2, f"scrub reserve reply to osd.{frm} "
                             f"failed: {e}")
            if granted and sem is not None:
                host._scrub_remote_grants.discard(key)
                sem.release()
    elif op in ("grant", "reject"):
        fut = pg._reserve_waiters.get((tid, frm))
        if fut is not None and not fut.done():
            fut.set_result(op == "grant")
        elif op == "grant":
            try:
                await host.send_osd(frm, MOSDScrubReserve(
                    {"pgid": [pg.pgid.pool, pg.pgid.ps], "tid": tid,
                     "from": host.whoami, "op": "release"}))
            except Exception:
                pass
    elif op == "release":
        if sem is not None and key in host._scrub_remote_grants:
            host._scrub_remote_grants.discard(key)
            sem.release()


async def scrub_pg(pg: "PGInstance", deep: bool) -> dict:
    """Primary-side scrub round, range-gated like the reference's
    chunky scrub: the namespace is walked in sorted-name ranges and
    client writes are blocked only while ONE range is being scanned,
    compared and repaired on all OSDs — between ranges the gate is
    open, so a colliding write waits out a small chunk, not the whole
    round. Publishes live progress at `pg.scrub_progress` and crumbs
    aborted rounds."""
    async with pg._scrub_lock:           # one scrub per PG at a time
        progress = ScrubProgress(pg.pgid, deep)
        pg.scrub_progress = progress
        try:
            return await _scrub_locked(pg, deep, progress)
        except BaseException as e:
            progress.finish("aborted")
            scrub_perf().inc("aborts")
            flight.record("scrub_abort", f"pg.{pg.pgid}", deep=deep,
                          reason=f"{type(e).__name__}: {e}")
            raise
        finally:
            if progress.state == "scrubbing":
                progress.finish()


def _plan_ranges(oids: list, chunk_max: int) -> list:
    """Partition the whole name space into `(lo, hi]` ranges with a
    boundary every `chunk_max` names of the primary's sorted listing.
    First range starts at None and last ends at None: peer-only names
    (strays the primary never listed) sort into SOME range and are
    still compared, which is what majority-delete detection needs."""
    bounds = [oids[i] for i in range(chunk_max - 1, len(oids), chunk_max)]
    if bounds and bounds[-1] == oids[-1]:
        bounds.pop()                     # tail range is open-ended anyway
    ranges, lo = [], None
    for b in bounds:
        ranges.append((lo, b))
        lo = b
    ranges.append((lo, None))
    return ranges


async def _scrub_range(pg: "PGInstance", deep: bool, oid_range,
                       progress: "ScrubProgress") -> dict:
    """Gather this range's maps from self + up acting peers and
    compare/repair it. Caller holds the write gate, so the slice is
    frozen across all OSDs while it is judged."""
    host = pg.host
    maps: dict[int, dict] = {
        host.whoami: await build_scrub_map(pg, deep, progress,
                                           oid_range=oid_range,
                                           paced=False)}
    tid = pg.backend.new_tid()
    waits = []
    for peer in sorted(pg.acting_peers()):
        if not host.osdmap.is_up(peer):
            continue
        fut = asyncio.get_running_loop().create_future()
        pg._scrub_waiters[(tid, peer)] = fut
        try:
            await host.send_osd(peer, MOSDRepScrub(
                {"pgid": [pg.pgid.pool, pg.pgid.ps], "tid": tid,
                 "from": host.whoami, "deep": deep,
                 "range": list(oid_range)}))
            waits.append((peer, fut))
        except Exception as e:
            dout("scrub", 2, f"scrub request to osd.{peer} failed: {e}")
            fut.cancel()
            pg._scrub_waiters.pop((tid, peer), None)
    for peer, fut in waits:
        try:
            maps[peer] = await asyncio.wait_for(fut, SCRUB_PEER_TIMEOUT)
        except asyncio.TimeoutError:
            dout("scrub", 2, f"osd.{peer} never sent a scrub map")
            flight.record("scrub_abort", f"pg.{pg.pgid}", deep=deep,
                          reason="peer_timeout", peer=peer)
        finally:
            pg._scrub_waiters.pop((tid, peer), None)

    if pg.pool.type == "erasure":
        res = await _compare_repair_ec(pg, maps, deep)
    else:
        res = await _compare_repair_replicated(pg, maps, deep)
    res["osds"] = sorted(maps)
    return res


async def _scrub_locked(pg: "PGInstance", deep: bool,
                        progress: "ScrubProgress") -> dict:
    host = pg.host
    t0 = time.monotonic()
    oids = sorted(pg.list_objects())
    progress.objects_total = len(oids)
    chunk_max = max(1, int(_cfg(pg, "osd_scrub_chunk_max", 32)))
    sleep_s = float(_cfg(pg, "osd_scrub_sleep", 0.0))
    ranges = _plan_ranges(oids, chunk_max)

    result: dict = {"errors": 0, "repaired": 0,
                    "inconsistent": [], "unrepaired": []}
    seen_osds = {host.whoami}

    # reserve one scrub slot per acting-set member for the WHOLE round
    # (sched_scrub's reserver): osd_max_scrubs bounds concurrent rounds
    # per daemon cluster-wide, and a failed/timed-out reservation
    # aborts cleanly before any write gate was ever taken
    reserve_tid = pg.backend.new_tid()
    reserved, reserved_peers = False, []
    if bool(_cfg(pg, "osd_scrub_reserve", True)):
        ok, reserved_peers = await _reserve_acting_set(pg, reserve_tid)
        reserved = ok and getattr(host, "scrub_reservations",
                                  None) is not None
        if not ok:
            progress.finish("reserve_failed")
            result.update({"reserve_failed": True, "deep": deep,
                           "osds": sorted(seen_osds), "objects": 0,
                           "bytes_hashed": 0, "duration_s": round(
                               time.monotonic() - t0, 3), "mb_s": 0.0})
            return result
    try:
        for i, rng in enumerate(ranges):
            # pace UNGATED: while scrub waits for its dmclock turn (and
            # between ranges) client writes flow freely — this is where
            # the QoS class actually shapes scrub against foreground
            # load
            await _qos_grant(pg)
            await pg.block_writes()
            try:
                r = await _scrub_range(pg, deep, rng, progress)
            finally:
                pg.unblock_writes()
            result["errors"] += r["errors"]
            result["repaired"] += r["repaired"]
            result["inconsistent"].extend(r["inconsistent"])
            result["unrepaired"].extend(r.get("unrepaired", []))
            seen_osds.update(r["osds"])
            if sleep_s > 0 and i + 1 < len(ranges):
                await asyncio.sleep(sleep_s)
    finally:
        if reserved:
            await _release_acting_set(pg, reserve_tid, reserved_peers)

    result["deep"] = deep
    result["osds"] = sorted(seen_osds)
    result["objects"] = progress.objects_total
    result["bytes_hashed"] = progress.bytes_hashed
    dt = max(1e-9, time.monotonic() - t0)
    result["duration_s"] = round(dt, 3)
    result["mb_s"] = round(progress.bytes_hashed / dt / 2**20, 2)
    pg.last_scrub = result
    now = time.time()
    pg.last_scrub_stamp = now
    if deep:
        pg.last_deep_scrub_stamp = now

    # a clean same-or-deeper round retires registry entries: the
    # damage is VERIFIED gone, so the mgr health checks can clear
    found = set(result["inconsistent"])
    for oid in list(pg.inconsistent_objects):
        entry = pg.inconsistent_objects[oid]
        if oid not in found and (deep or not entry.get("deep")):
            del pg.inconsistent_objects[oid]

    perf = scrub_perf()
    perf.inc("rounds")
    if deep:
        perf.inc("deep_rounds")
    if result["errors"]:
        perf.inc("errors_found", result["errors"])
    if result["repaired"]:
        perf.inc("errors_repaired", result["repaired"])
    if result.get("unrepaired"):
        perf.inc("errors_unrepaired", len(result["unrepaired"]))
    st = pg.scrub_stats
    st["objects_scrubbed"] += progress.objects_total
    st["bytes_hashed"] += progress.bytes_hashed
    st["errors_found"] += result["errors"]
    st["errors_repaired"] += result["repaired"]

    dout("scrub", 2 if result["errors"] else 4,
         f"pg {pg.pgid} {'deep-' if deep else ''}scrub: "
         f"{result['errors']} errors, {result['repaired']} repaired, "
         f"{result['objects']} objects, {result['mb_s']} MB/s hashed")
    return result


async def _compare_repair_ec(pg: "PGInstance", maps: dict,
                             deep: bool) -> dict:
    """Each EC shard self-certifies via its stored per-chunk crc; a
    corrupt or stale shard is reconstructed from the survivors
    (ECBackend.cc:1092 deep verify; repair via RecoveryOp). Presence
    votes: when a majority of the acting set lacks the object, the
    straggler shards are a half-deleted object and are removed."""
    errors = repaired = 0
    inconsistent: list[str] = []
    me = pg.host.whoami
    oids = sorted({o for m in maps.values() for o in m})
    for oid in oids:
        holders = [osd for osd, m in maps.items() if oid in m]
        absent = [osd for osd in maps if oid not in maps[osd]]
        if len(absent) > len(maps) / 2:
            # majority says the object is gone: finish the deletion
            errors += len(holders)
            inconsistent.append(oid)
            _note_inconsistent(pg, oid, holders, "stray", deep)
            for osd in holders:
                try:
                    if osd == me:
                        pg.backend.local_apply(oid, "delete", b"")
                    else:
                        await pg.send_push(osd, oid, b"", None,
                                           delete=True)
                    repaired += 1
                    _note_repaired(pg, oid, osd, True, "stray")
                except Exception as e:
                    _note_repaired(pg, oid, osd, False, "stray")
                    dout("scrub", 1, f"stray delete of {oid} on "
                                     f"osd.{osd} failed: {e}")
            continue
        newest = max((tuple(maps[osd][oid]["version"]) for osd in holders
                      if not maps[osd][oid]["corrupt"]), default=None)
        bad: list[int] = []
        for osd, m in maps.items():
            ent = m.get(oid)
            if ent is None or ent["corrupt"] or (
                    newest is not None
                    and tuple(ent["version"]) != newest):
                bad.append(osd)
        if not bad:
            continue
        errors += len(bad)
        inconsistent.append(oid)
        _note_inconsistent(pg, oid, bad, "shard", deep)
        for osd in bad:
            try:
                if osd == me:
                    await pg.backend.pull_object(None, oid, None)
                else:
                    await pg.backend.push_object(osd, oid)
                repaired += 1
                _note_repaired(pg, oid, osd, True, "shard")
            except Exception as e:
                _note_repaired(pg, oid, osd, False, "shard")
                dout("scrub", 1, f"repair of {oid} shard on osd.{osd} "
                                 f"failed: {type(e).__name__} {e}")
    return {"errors": errors, "repaired": repaired,
            "inconsistent": inconsistent}


async def _compare_repair_replicated(pg: "PGInstance", maps: dict,
                                     deep: bool) -> dict:
    """Strict-majority authoritative selection (be_select_auth_object):
    copies disagreeing with the majority fingerprint — including absent
    copies, which vote — are overwritten (or deleted) toward it. No
    strict majority means the inconsistency is reported but NOT
    repaired: guessing could propagate rot (the reference leaves
    ambiguous objects to `ceph pg repair` policy for the same reason)."""
    errors = repaired = 0
    inconsistent: list[str] = []
    unrepaired: list[str] = []
    me = pg.host.whoami
    oids = sorted({o for m in maps.values() for o in m})
    for oid in oids:
        def fingerprint(ent):
            if ent is None:
                return ABSENT
            if ent["corrupt"]:
                return None         # self-certified bad: no vote
            key = [ent["size"], ent["attr_digest"]]
            if deep:
                key += [ent.get("digest"), ent.get("omap_digest")]
            return tuple(key)

        prints = {osd: fingerprint(m.get(oid)) for osd, m in maps.items()}
        tally: dict = {}
        for osd, fp in prints.items():
            if fp is not None:
                tally.setdefault(fp, []).append(osd)
        bad_by_corruption = [osd for osd, fp in prints.items()
                             if fp is None]
        if not tally:
            unrepaired.append(oid)      # unreadable everywhere
            errors += len(prints)
            _note_inconsistent(pg, oid, list(prints), "unreadable", deep)
            continue
        auth_fp, auth_osds = max(tally.items(), key=lambda kv: len(kv[1]))
        majority = len(auth_osds) > len(prints) / 2
        bad = [osd for osd, fp in prints.items() if fp != auth_fp]
        if not bad:
            continue
        errors += len(bad)
        inconsistent.append(oid)
        _note_inconsistent(pg, oid, bad, "copy", deep)
        if not majority and not (len(tally) == 1 and bad_by_corruption):
            # a corrupt copy may be repaired toward the only candidate
            # even without strict majority; a tie between two VALID
            # fingerprints is never guessed at
            unrepaired.append(oid)
            dout("scrub", 1, f"pg {pg.pgid} {oid}: no majority "
                             f"fingerprint ({prints}); NOT auto-repairing")
            continue
        try:
            if auth_fp == ABSENT:
                # the delete is authoritative: finish it on the holders.
                # The push carries the primary's snapshot state so a
                # delete-repair can't wipe legitimate clones the target
                # replica holds (head deletes preserve clones)
                snap_state = pg.backend.snap_state_for_push(oid)
                for osd in bad:
                    if osd == me:
                        pg.backend.local_apply(oid, "delete", b"")
                    else:
                        await pg.send_push(osd, oid, b"", None,
                                           delete=True,
                                           snap_state=snap_state)
                    repaired += 1
                    _note_repaired(pg, oid, osd, True, "copy")
                continue
            if me in bad:
                # the primary's own copy is wrong: adopt an authoritative
                # peer's before pushing
                await pg.pull_transport(auth_osds[0], oid)
                repaired += 1
                _note_repaired(pg, oid, me, True, "copy")
                bad.remove(me)
            for osd in bad:
                await pg.backend.push_object(osd, oid)
                repaired += 1
                _note_repaired(pg, oid, osd, True, "copy")
        except Exception as e:
            dout("scrub", 1, f"repair of {oid} failed: "
                             f"{type(e).__name__} {e}")
    return {"errors": errors, "repaired": repaired,
            "inconsistent": inconsistent, "unrepaired": unrepaired}
