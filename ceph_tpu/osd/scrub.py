"""PG scrub: background verification + repair of replica/shard state.

Re-creation of the reference scrub machinery (src/osd/scrubber/
pg_scrubber.h:177 state machine, scrub_backend.h:101 per-shard map
compare, ECBackend.cc:1092-1120 deep shard verify):

  * the primary asks every acting peer for a SCRUB MAP — per object:
    size, attrs digest, and (deep) content digests; it builds its own
    map the same way;
  * client writes are gated out for the duration of a scrub round (the
    reference's scrub range write blocking) so repairs never race an
    acknowledged write;
  * maps are compared per object: corrupt shards are self-certified by
    the stored per-chunk crc on EC pools (or the store's blob crc on
    FileStore); replicated copies vote — ABSENCE VOTES TOO, so a stale
    holder cannot resurrect a deleted object — and only a strict
    majority is repaired toward (no majority = inconsistency reported,
    never guessed, matching the reference's refusal to auto-repair
    ambiguous objects);
  * repairs ride the existing recovery machinery: EC shards are
    reconstructed from k survivors and pushed; replicated copies
    converge on the majority fingerprint, pulled first if the primary
    itself is wrong.

Idiomatic divergences: one round-trip map exchange instead of chunked
scrub reservations/ranges (PGs here are small); light scrub compares
size+attrs digests, deep scrub re-reads and re-hashes everything — same
split as the reference's shallow/deep modes.
"""
from __future__ import annotations

import asyncio
import json
from typing import TYPE_CHECKING

from ceph_tpu.msg.messages import MOSDRepScrub, MOSDRepScrubMap
from ceph_tpu.objectstore.store import StoreError
from ceph_tpu.utils.dout import dout

if TYPE_CHECKING:
    from ceph_tpu.osd.pg import PGInstance

SCRUB_PEER_TIMEOUT = 10.0
_SCAN_YIELD_EVERY = 32      # objects hashed between event-loop yields

# fingerprint sentinel: the object does not exist on that OSD. A real
# value (not exclusion) so deletions can win the majority vote.
ABSENT = "__absent__"


async def build_scrub_map(pg: "PGInstance", deep: bool) -> dict:
    """Per-object scrub entries for the local store (the reference's
    build_scrub_map_chunk / be_scan_list). Yields to the event loop
    periodically: a large deep scan must not stall heartbeats."""
    from ceph_tpu.native import ec_native
    store = pg.host.store
    cid = pg.backend.coll()
    if pg.pool.type == "erasure":
        _gc_rollback_generations(pg)
    out: dict[str, dict] = {}
    for i, oid in enumerate(pg.list_objects()):
        if i % _SCAN_YIELD_EVERY == _SCAN_YIELD_EVERY - 1:
            await asyncio.sleep(0)
        gh = pg.backend.ghobject(oid)
        ent: dict = {"corrupt": False}
        try:
            attrs = store.getattrs(cid, gh)
            st = store.stat(cid, gh)
            ent["size"] = st["size"]
            ent["attr_digest"] = ec_native.crc32c(
                b"\x00".join(k.encode() + b"=" + v
                             for k, v in sorted(attrs.items())))
            if pg.pool.type == "erasure":
                ent["shard"] = int(attrs.get("shard", b"-1"))
                ent["version"] = list(
                    json.loads(attrs.get("version", b"[0,0]")))
                csum = json.loads(attrs.get("csum", b"[]"))
                if deep:
                    data = store.read(cid, gh)
                    c = pg.backend.sinfo.chunk_size
                    for s in range(len(csum)):
                        have = ec_native.crc32c(data[s * c:(s + 1) * c])
                        if have != csum[s]:
                            ent["corrupt"] = True
                            break
                    if len(data) != len(csum) * c:
                        ent["corrupt"] = True
            elif deep:
                data = store.read(cid, gh)
                ent["digest"] = ec_native.crc32c(data)
                omap = store.omap_get(cid, gh)
                ent["omap_digest"] = ec_native.crc32c(
                    b"\x00".join(k.encode() + b"=" + v
                                 for k, v in sorted(omap.items())))
        except StoreError as e:
            # a FileStore blob whose crc gate refuses the read is a
            # corrupt local copy — exactly what scrub exists to find
            dout("scrub", 1, f"scrub read {oid}: {e}")
            ent["corrupt"] = True
        out[oid] = ent
    return out


def _gc_rollback_generations(pg: "PGInstance") -> None:
    """Drop EC rollback generations (<oid>\\x00prev clones) whose main
    object is gone: scrub only runs on a healthy active PG with writes
    gated, so any divergence that could have needed them has already
    been resolved by peering. (Prevents deleted objects from leaking a
    prev clone forever.)"""
    from ceph_tpu.objectstore.store import Transaction
    from ceph_tpu.osd.ec_backend import PREV_SUFFIX
    store = pg.host.store
    cid = pg.backend.coll()
    live = set(pg.list_objects())
    for gh in list(store.collection_list(cid)):
        if not gh.name.endswith(PREV_SUFFIX):
            continue
        if gh.name[:-len(PREV_SUFFIX)] not in live:
            store.queue_transaction(Transaction().remove(cid, gh))


async def scrub_pg(pg: "PGInstance", deep: bool) -> dict:
    """Primary-side scrub round: block writes, gather maps, compare,
    repair, unblock."""
    async with pg._scrub_lock:           # one scrub per PG at a time
        await pg.block_writes()
        try:
            return await _scrub_locked(pg, deep)
        finally:
            pg.unblock_writes()


async def _scrub_locked(pg: "PGInstance", deep: bool) -> dict:
    host = pg.host
    tid = pg.backend.new_tid()
    maps: dict[int, dict] = {host.whoami: await build_scrub_map(pg, deep)}
    waits = []
    for peer in sorted(pg.acting_peers()):
        if not host.osdmap.is_up(peer):
            continue
        fut = asyncio.get_running_loop().create_future()
        pg._scrub_waiters[(tid, peer)] = fut
        try:
            await host.send_osd(peer, MOSDRepScrub(
                {"pgid": [pg.pgid.pool, pg.pgid.ps], "tid": tid,
                 "from": host.whoami, "deep": deep}))
            waits.append((peer, fut))
        except Exception as e:
            dout("scrub", 2, f"scrub request to osd.{peer} failed: {e}")
            fut.cancel()
            pg._scrub_waiters.pop((tid, peer), None)
    for peer, fut in waits:
        try:
            maps[peer] = await asyncio.wait_for(fut, SCRUB_PEER_TIMEOUT)
        except asyncio.TimeoutError:
            dout("scrub", 2, f"osd.{peer} never sent a scrub map")
        finally:
            pg._scrub_waiters.pop((tid, peer), None)

    if pg.pool.type == "erasure":
        result = await _compare_repair_ec(pg, maps, deep)
    else:
        result = await _compare_repair_replicated(pg, maps, deep)
    result["deep"] = deep
    result["osds"] = sorted(maps)
    pg.last_scrub = result
    dout("scrub", 2 if result["errors"] else 4,
         f"pg {pg.pgid} {'deep-' if deep else ''}scrub: "
         f"{result['errors']} errors, {result['repaired']} repaired")
    return result


async def _compare_repair_ec(pg: "PGInstance", maps: dict,
                             deep: bool) -> dict:
    """Each EC shard self-certifies via its stored per-chunk crc; a
    corrupt or stale shard is reconstructed from the survivors
    (ECBackend.cc:1092 deep verify; repair via RecoveryOp). Presence
    votes: when a majority of the acting set lacks the object, the
    straggler shards are a half-deleted object and are removed."""
    errors = repaired = 0
    inconsistent: list[str] = []
    me = pg.host.whoami
    oids = sorted({o for m in maps.values() for o in m})
    for oid in oids:
        holders = [osd for osd, m in maps.items() if oid in m]
        absent = [osd for osd in maps if oid not in maps[osd]]
        if len(absent) > len(maps) / 2:
            # majority says the object is gone: finish the deletion
            errors += len(holders)
            inconsistent.append(oid)
            for osd in holders:
                try:
                    if osd == me:
                        pg.backend.local_apply(oid, "delete", b"")
                    else:
                        await pg.send_push(osd, oid, b"", None,
                                           delete=True)
                    repaired += 1
                except Exception as e:
                    dout("scrub", 1, f"stray delete of {oid} on "
                                     f"osd.{osd} failed: {e}")
            continue
        newest = max((tuple(maps[osd][oid]["version"]) for osd in holders
                      if not maps[osd][oid]["corrupt"]), default=None)
        bad: list[int] = []
        for osd, m in maps.items():
            ent = m.get(oid)
            if ent is None or ent["corrupt"] or (
                    newest is not None
                    and tuple(ent["version"]) != newest):
                bad.append(osd)
        if not bad:
            continue
        errors += len(bad)
        inconsistent.append(oid)
        for osd in bad:
            try:
                if osd == me:
                    await pg.backend.pull_object(None, oid, None)
                else:
                    await pg.backend.push_object(osd, oid)
                repaired += 1
            except Exception as e:
                dout("scrub", 1, f"repair of {oid} shard on osd.{osd} "
                                 f"failed: {type(e).__name__} {e}")
    return {"errors": errors, "repaired": repaired,
            "inconsistent": inconsistent}


async def _compare_repair_replicated(pg: "PGInstance", maps: dict,
                                     deep: bool) -> dict:
    """Strict-majority authoritative selection (be_select_auth_object):
    copies disagreeing with the majority fingerprint — including absent
    copies, which vote — are overwritten (or deleted) toward it. No
    strict majority means the inconsistency is reported but NOT
    repaired: guessing could propagate rot (the reference leaves
    ambiguous objects to `ceph pg repair` policy for the same reason)."""
    errors = repaired = 0
    inconsistent: list[str] = []
    unrepaired: list[str] = []
    me = pg.host.whoami
    oids = sorted({o for m in maps.values() for o in m})
    for oid in oids:
        def fingerprint(ent):
            if ent is None:
                return ABSENT
            if ent["corrupt"]:
                return None         # self-certified bad: no vote
            key = [ent["size"], ent["attr_digest"]]
            if deep:
                key += [ent.get("digest"), ent.get("omap_digest")]
            return tuple(key)

        prints = {osd: fingerprint(m.get(oid)) for osd, m in maps.items()}
        tally: dict = {}
        for osd, fp in prints.items():
            if fp is not None:
                tally.setdefault(fp, []).append(osd)
        bad_by_corruption = [osd for osd, fp in prints.items()
                             if fp is None]
        if not tally:
            unrepaired.append(oid)      # unreadable everywhere
            errors += len(prints)
            continue
        auth_fp, auth_osds = max(tally.items(), key=lambda kv: len(kv[1]))
        majority = len(auth_osds) > len(prints) / 2
        bad = [osd for osd, fp in prints.items() if fp != auth_fp]
        if not bad:
            continue
        errors += len(bad)
        inconsistent.append(oid)
        if not majority and not (len(tally) == 1 and bad_by_corruption):
            # a corrupt copy may be repaired toward the only candidate
            # even without strict majority; a tie between two VALID
            # fingerprints is never guessed at
            unrepaired.append(oid)
            dout("scrub", 1, f"pg {pg.pgid} {oid}: no majority "
                             f"fingerprint ({prints}); NOT auto-repairing")
            continue
        try:
            if auth_fp == ABSENT:
                # the delete is authoritative: finish it on the holders.
                # The push carries the primary's snapshot state so a
                # delete-repair can't wipe legitimate clones the target
                # replica holds (head deletes preserve clones)
                snap_state = pg.backend.snap_state_for_push(oid)
                for osd in bad:
                    if osd == me:
                        pg.backend.local_apply(oid, "delete", b"")
                    else:
                        await pg.send_push(osd, oid, b"", None,
                                           delete=True,
                                           snap_state=snap_state)
                    repaired += 1
                continue
            if me in bad:
                # the primary's own copy is wrong: adopt an authoritative
                # peer's before pushing
                await pg.pull_transport(auth_osds[0], oid)
                repaired += 1
                bad.remove(me)
            for osd in bad:
                await pg.backend.push_object(osd, oid)
                repaired += 1
        except Exception as e:
            dout("scrub", 1, f"repair of {oid} failed: "
                             f"{type(e).__name__} {e}")
    return {"errors": errors, "repaired": repaired,
            "inconsistent": inconsistent, "unrepaired": unrepaired}
