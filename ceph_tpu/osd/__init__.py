"""OSD data plane: daemon (daemon.py), PG + peering (pg.py), backends
(backend.py replicated, ec_backend.py erasure), PGLog (pglog.py), EC
stripe driver (ec_util.py)."""
