"""OSD data-plane components. Currently: EC stripe driver (ec_util)."""
