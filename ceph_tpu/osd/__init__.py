"""OSD data-plane components (EC stripe driver, transactions, backends)."""
