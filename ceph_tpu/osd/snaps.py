"""RADOS snapshots: SnapSet, clone-on-write, snap reads, SnapMapper.

Re-creation of the reference's snapshot machinery essentials:

  * clone-on-write (src/osd/PrimaryLogPG.cc make_writeable): the first
    mutation after a new snap appears in the client's SnapContext clones
    the head into a read-only clone object covering the new snaps;
  * SnapSet (src/osd/osd_types.h SnapSet): per-object record of the
    newest snap observed (seq) and the clone list with the exact snap
    ids each clone covers;
  * snap-directed reads (PrimaryLogPG::find_object_context): a read at
    snap s serves head when s is newer than every mutation, the covering
    clone when one exists, and ENOENT when the object did not exist at s;
  * SnapMapper (src/osd/SnapMapper.h): an omap index snap -> object
    names on the PG meta object so snaptrim can find the affected
    objects without scanning the collection;
  * snaptrim (PrimaryLogPG::trim_object): when the monitor marks a snap
    removed, the primary strips it from covering clones and deletes
    clones left covering nothing.

Idiomatic divergences: the SnapSet lives on a per-object "snapdir"
companion (snap=SNAPDIR_SNAP) instead of head-attr-with-migration, so
head delete/recreate never moves it; clones are full copies (no overlap
extents); all helpers are deterministic pure store operations so
replicas replay the same clone/trim ops the primary logged.
"""
from __future__ import annotations

import dataclasses
import json

from ceph_tpu.objectstore.store import ObjectStore, StoreError, Transaction
from ceph_tpu.objectstore.types import CEPH_NOSNAP, CollectionId, Ghobject

# companion object holding the SnapSet (reference: CEPH_SNAPDIR head
# stand-in); distinct from NOSNAP and NO_GEN sentinels
SNAPDIR_SNAP = 2 ** 64 - 3

SS_ATTR = "ss"
SM_PREFIX = "sm_"


def snapdir_gh(head: Ghobject) -> Ghobject:
    return dataclasses.replace(head, snap=SNAPDIR_SNAP)


def clone_gh(head: Ghobject, cloneid: int) -> Ghobject:
    return dataclasses.replace(head, snap=cloneid)


def sm_key(snapid: int, name: str) -> str:
    return f"{SM_PREFIX}{snapid:016x}|{name}"


@dataclasses.dataclass
class SnapSet:
    """seq + clone list, ascending by clone id; each clone records the
    exact snap ids whose object state it preserves."""

    seq: int = 0
    # [{"id": int, "snaps": [int,...] ascending, "size": int}, ...]
    clones: list[dict] = dataclasses.field(default_factory=list)

    def to_json(self) -> bytes:
        return json.dumps({"seq": self.seq, "clones": self.clones}).encode()

    @classmethod
    def from_json(cls, blob: bytes) -> "SnapSet":
        d = json.loads(blob)
        return cls(seq=d["seq"], clones=list(d["clones"]))


def load_snapset(store: ObjectStore, cid: CollectionId,
                 head: Ghobject) -> SnapSet | None:
    sd = snapdir_gh(head)
    try:
        return SnapSet.from_json(store.getattr(cid, sd, SS_ATTR))
    except StoreError:
        return None


def save_snapset(txn: Transaction, cid: CollectionId, head: Ghobject,
                 ss: SnapSet, store: ObjectStore) -> None:
    """Persist even a clone-less SnapSet while seq > 0: the seq is what
    lets resolve_read answer ENOENT for snaps that predate the object."""
    sd = snapdir_gh(head)
    if not ss.clones and ss.seq == 0:
        if store.exists(cid, sd):
            txn.remove(cid, sd)
        return
    if not store.exists(cid, sd):
        txn.touch(cid, sd)
    txn.setattr(cid, sd, SS_ATTR, ss.to_json())


def resolve_read(ss: SnapSet | None, snapid: int,
                 head_exists: bool):
    """Which object serves a read at `snapid`: "head", a clone id, or
    None for ENOENT (the object did not exist at that snap)."""
    if ss is None:
        return "head" if head_exists else None
    if snapid > ss.seq:
        return "head" if head_exists else None
    for clone in ss.clones:                      # ascending clone id
        if snapid in clone["snaps"]:
            return clone["id"]
    return None


# -- deterministic store-level ops (replayed identically on replicas) ------

def apply_clone(store: ObjectStore, cid: CollectionId, head: Ghobject,
                pgmeta: Ghobject, cloneid: int, snaps: list[int],
                seq_only: bool, size: int | None = None) -> None:
    """make_writeable's clone step: preserve the current head state as
    clone `cloneid` covering `snaps`, and advance SnapSet.seq. With
    seq_only (head absent at clone time: nothing to preserve) only the
    seq advances, so a later clone cannot claim to cover snaps that
    predate the object. `size` overrides the recorded clone size (EC
    shards pass the LOGICAL object size; their local blob is a padded
    chunk stack)."""
    ss = load_snapset(store, cid, head) or SnapSet()
    if cloneid <= ss.seq:
        return                               # replayed / stale clone op
    txn = Transaction()
    if not seq_only and store.exists(cid, head):
        cgh = clone_gh(head, cloneid)
        if store.exists(cid, cgh):
            txn.remove(cid, cgh)
        txn.clone(cid, head, cgh)
        if size is None:
            size = store.stat(cid, head)["size"]
        ss.clones.append({"id": cloneid, "snaps": sorted(snaps),
                          "size": size})
        txn.omap_setkeys(cid, pgmeta,
                         {sm_key(s, head.name): b"1" for s in snaps})
    ss.seq = cloneid
    save_snapset(txn, cid, head, ss, store)
    store.queue_transaction(txn)


def apply_rollback(store: ObjectStore, cid: CollectionId, head: Ghobject,
                   snapid: int,
                   extra_attrs: dict[str, bytes] | None = None) -> None:
    """Copy the clone covering `snapid` back over head (rollback op,
    PrimaryLogPG::_rollback_to). The primary rejects ENOENT resolutions
    before logging, so an unresolvable replay is a no-op. `extra_attrs`
    are stamped onto the restored head (the EC backend re-stamps the
    shard's version attr so the rolled-back chunks carry the rollback
    entry's eversion, not the clone-time one)."""
    ss = load_snapset(store, cid, head)
    src = resolve_read(ss, snapid, store.exists(cid, head))
    if src is None or src == "head":
        return
    cgh = clone_gh(head, src)
    if not store.exists(cid, cgh):
        return
    txn = Transaction()
    if store.exists(cid, head):
        txn.remove(cid, head)
    txn.clone(cid, cgh, head)
    if extra_attrs:
        txn.setattrs(cid, head, extra_attrs)
    store.queue_transaction(txn)


def apply_snaptrim(store: ObjectStore, cid: CollectionId, head: Ghobject,
                   pgmeta: Ghobject, snapid: int) -> None:
    """Strip a removed snap from this object: drop it from the covering
    clone's snap list, delete the clone once it covers nothing, clear
    the SnapMapper key (PrimaryLogPG::trim_object)."""
    txn = Transaction()
    txn.omap_rmkeys(cid, pgmeta, [sm_key(snapid, head.name)])
    ss = load_snapset(store, cid, head)
    if ss is not None:
        kept = []
        for clone in ss.clones:
            if snapid in clone["snaps"]:
                clone = dict(clone, snaps=[s for s in clone["snaps"]
                                           if s != snapid])
            if clone["snaps"]:
                kept.append(clone)
            else:
                cgh = clone_gh(head, clone["id"])
                if store.exists(cid, cgh):
                    txn.remove(cid, cgh)
        ss.clones = kept
        save_snapset(txn, cid, head, ss, store)
    store.queue_transaction(txn)


def purge_object(store: ObjectStore, cid: CollectionId, head: Ghobject,
                 pgmeta: Ghobject) -> None:
    """Remove head AND every clone + the snapdir + SnapMapper keys: the
    stray-deletion path during backfill (a stray's snapshots are strays
    too, unlike a client delete which preserves clones)."""
    txn = Transaction()
    ss = load_snapset(store, cid, head)
    if ss is not None:
        rm_keys = []
        for clone in ss.clones:
            cgh = clone_gh(head, clone["id"])
            if store.exists(cid, cgh):
                txn.remove(cid, cgh)
            rm_keys.extend(sm_key(s, head.name) for s in clone["snaps"])
        if rm_keys:
            txn.omap_rmkeys(cid, pgmeta, rm_keys)
        txn.remove(cid, snapdir_gh(head))
    if store.exists(cid, head):
        txn.remove(cid, head)
    if len(txn):
        store.queue_transaction(txn)


# -- recovery payload helpers ----------------------------------------------

def snap_state_for_push(store: ObjectStore, cid: CollectionId,
                        head: Ghobject) -> dict | None:
    """Clones + SnapSet for a recovery push payload (None when the
    object has no snapshot state)."""
    ss = load_snapset(store, cid, head)
    if ss is None:
        return None
    clones = {}
    for clone in ss.clones:
        cgh = clone_gh(head, clone["id"])
        try:
            clones[str(clone["id"])] = {
                "data": store.read(cid, cgh).decode("latin1"),
                "attrs": {k: v.decode("latin1")
                          for k, v in store.getattrs(cid, cgh).items()}}
        except StoreError:
            pass
    return {"ss": ss.to_json().decode(), "clones": clones}


def apply_snap_push(store: ObjectStore, cid: CollectionId, head: Ghobject,
                    pgmeta: Ghobject, state: dict | None) -> None:
    """Replace local snapshot state with a pushed one (or clear it)."""
    old = load_snapset(store, cid, head)
    txn = Transaction()
    if old is not None:
        rm = []
        for clone in old.clones:
            cgh = clone_gh(head, clone["id"])
            if store.exists(cid, cgh):
                txn.remove(cid, cgh)
            rm.extend(sm_key(s, head.name) for s in clone["snaps"])
        if rm:
            txn.omap_rmkeys(cid, pgmeta, rm)
        txn.remove(cid, snapdir_gh(head))
    if state is not None:
        ss = SnapSet.from_json(state["ss"].encode())
        sd = snapdir_gh(head)
        txn.touch(cid, sd)
        txn.setattr(cid, sd, SS_ATTR, ss.to_json())
        sm = {}
        for clone in ss.clones:
            blob = state["clones"].get(str(clone["id"]))
            if blob is None:
                continue
            cgh = clone_gh(head, clone["id"])
            txn.touch(cid, cgh)
            txn.write(cid, cgh, 0, blob["data"].encode("latin1"))
            if blob["attrs"]:
                txn.setattrs(cid, cgh,
                             {k: v.encode("latin1")
                              for k, v in blob["attrs"].items()})
            for s in clone["snaps"]:
                sm[sm_key(s, head.name)] = b"1"
        if sm:
            txn.omap_setkeys(cid, pgmeta, sm)
    if len(txn):
        store.queue_transaction(txn)


def snapmapper_objects(store: ObjectStore, cid: CollectionId,
                       pgmeta: Ghobject, snapid: int) -> list[str]:
    """Object names with a clone covering `snapid` (SnapMapper
    get_next_objects_to_trim): a prefix scan of the pgmeta omap."""
    prefix = f"{SM_PREFIX}{snapid:016x}|"
    try:
        omap = store.omap_get(cid, pgmeta)
    except StoreError:
        return []
    return sorted(k[len(prefix):] for k in omap if k.startswith(prefix))


def headless_snap_objects(store: ObjectStore,
                          cid: CollectionId) -> set[str]:
    """Names whose head is gone but snapshot state survives (these must
    still be recovered/backfilled and must not be swept as strays)."""
    heads, snapdirs = set(), set()
    for gh in store.collection_list(cid):
        if gh.snap == CEPH_NOSNAP:
            heads.add(gh.name)
        elif gh.snap == SNAPDIR_SNAP:
            snapdirs.add(gh.name)
    return snapdirs - heads
