"""PGLog: per-PG ordered op log with missing-set tracking.

Re-creation of the reference's PGLog essentials (src/osd/PGLog.{h,cc},
pg_log_entry_t at src/osd/osd_types.h:4325): every write appends an
entry stamped with an eversion (map epoch, per-PG sequence); peers
compare logs during peering, divergent entries are rewound, and the
objects whose entries one side lacks become its *missing set*, repaired
by log-driven recovery (push of the authoritative object) instead of a
full resync (PGLog::merge_log, src/osd/PGLog.h:1254).

Idiomatic divergences: entries are JSON-able dataclasses; rollback is
whole-object re-push (the reference's per-op rollback info is an
optimization on top of the same authority rules); the log is bounded by
entry count, with a fallthrough to backfill when a peer is behind the
tail.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Iterable

Eversion = tuple[int, int]      # (epoch, seq) — totally ordered
ZERO: Eversion = (0, 0)


@dataclasses.dataclass
class LogEntry:
    """pg_log_entry_t-lite: what happened to which object, when.

    `reqid` is the client's stable request id (nonce, seq) — the dup-op
    index key (osd_reqid_t in pg_log_entry_t): a client retry whose
    first attempt actually committed must NOT re-execute (appends would
    double-apply, deletes would answer ENOENT for a success)."""

    version: Eversion
    op: str                     # "modify" | "delete"
    oid: str                    # object name within the PG
    prior_version: Eversion = ZERO
    reqid: tuple | None = None

    def to_dict(self) -> dict:
        d = {"version": list(self.version), "op": self.op,
             "oid": self.oid, "prior_version": list(self.prior_version)}
        if self.reqid is not None:
            d["reqid"] = list(self.reqid)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "LogEntry":
        return cls(version=tuple(d["version"]), op=d["op"], oid=d["oid"],
                   prior_version=tuple(d.get("prior_version", [0, 0])),
                   reqid=tuple(d["reqid"]) if d.get("reqid") else None)


class PGLog:
    """Bounded ordered log + missing set (PGLog.h)."""

    MAX_ENTRIES = 1000          # osd_max_pg_log_entries analog

    def __init__(self):
        self.entries: list[LogEntry] = []
        self.tail: Eversion = ZERO      # everything <= tail is implicit
        self.head: Eversion = ZERO      # last_update
        # oid -> (need version, have prior) — objects this replica must
        # recover before it can serve them (pg_missing_t)
        self.missing: dict[str, Eversion] = {}
        # dup-op index: reqid -> version of the entry that executed it
        # (PGLog dups; horizon = the retained entry window)
        self._reqids: dict[tuple, Eversion] = {}
        # pipelined-execution completion tracking: versions whose
        # log-intent is appended but whose execution slice has not yet
        # settled (the primary marks them complete in ANY order;
        # `last_complete` advances only over the contiguous settled
        # prefix — the reference's pg_info_t.last_complete)
        self._incomplete: set[Eversion] = set()
        # newest retained entry per object (prior_version lookups ran a
        # reverse scan of the whole window PER WRITE — profiled on the
        # pipelined hot path); kept in sync with `entries` by
        # append/insert/trim and rebuilt with the reqid index
        self._last_by_oid: dict[str, Eversion] = {}
        # incremental-persistence dirty state: persist_meta writes ONE
        # omap key per changed entry instead of re-serializing the whole
        # window per op (the reference stores pg log entries as
        # individual omap keys the same way, src/osd/PGLog.cc
        # _write_log_and_missing). A fresh/replaced log starts
        # dirty_full so wholesale adoption rewrites everything.
        self._dirty_full = True
        self._dirty: dict[str, LogEntry | None] = {}    # key -> entry|del

    # omap key namespace: the pgmeta object's omap is shared with the
    # SnapMapper ("sm_..." keys) — log keys carry their own prefix
    KEY_PREFIX = "log."

    @classmethod
    def entry_key(cls, v: Eversion) -> str:
        """Lexically-sortable omap key of one entry."""
        return f"{cls.KEY_PREFIX}{v[0]:012d}.{v[1]:012d}"

    def take_dirty(self) -> tuple[bool, dict[str, "LogEntry | None"]]:
        """Consume the pending persistence delta: (full_rewrite, {key ->
        entry or None=deleted}). The caller must durably apply it (or a
        full rewrite) in the same transaction as the static meta — and
        hand it back via restore_dirty() if that transaction fails, or
        the entries silently vanish from the persisted omap forever."""
        full, dirty = self._dirty_full, self._dirty
        self._dirty_full, self._dirty = False, {}
        return full, dirty

    def restore_dirty(self, full: bool,
                      dirty: dict[str, "LogEntry | None"]) -> None:
        """Re-merge a delta whose transaction failed; dirt recorded
        since the failed take wins on key collisions."""
        self._dirty_full = self._dirty_full or full
        merged = dict(dirty)
        merged.update(self._dirty)
        self._dirty = merged

    @classmethod
    def from_omap(cls, meta: dict, omap: dict[str, bytes]) -> "PGLog":
        """Rebuild from the persisted form written by the incremental
        path: static fields from the pgmeta blob, entries from the
        log-prefixed omap keys (lexicographic key order IS version
        order). The loaded instance starts clean — disk already
        matches."""
        log = cls()
        log.entries = [LogEntry.from_dict(json.loads(v))
                       for k, v in sorted(omap.items())
                       if k.startswith(cls.KEY_PREFIX)]
        log.head = tuple(meta.get("head", [0, 0]))
        log.tail = tuple(meta.get("tail", [0, 0]))
        log.missing = {o: tuple(v)
                       for o, v in meta.get("missing", {}).items()}
        log._rebuild_reqids()
        log._dirty_full = False
        return log

    # -- append path ---------------------------------------------------------

    def append(self, entry: LogEntry, complete: bool = True) -> None:
        assert entry.version > self.head, (entry, self.head)
        self.entries.append(entry)
        self._dirty[self.entry_key(entry.version)] = entry
        self.head = entry.version
        self._last_by_oid[entry.oid] = entry.version
        if entry.reqid is not None:
            self._reqids[entry.reqid] = entry.version
        if not complete:
            # a pipelined primary appends the log INTENT before the
            # execution slice runs; mark_complete settles it later
            self._incomplete.add(entry.version)
        if len(self.entries) > self.MAX_ENTRIES:
            drop = len(self.entries) - self.MAX_ENTRIES
            self.tail = self.entries[drop - 1].version
            for e in self.entries[:drop]:
                if e.reqid is not None:
                    self._reqids.pop(e.reqid, None)
                self._dirty[self.entry_key(e.version)] = None
                self._incomplete.discard(e.version)
                # only when the dropped entry IS the object's newest:
                # a later retained entry keeps the mapping alive
                if self._last_by_oid.get(e.oid) == e.version:
                    del self._last_by_oid[e.oid]
            del self.entries[:drop]

    def insert(self, entry: LogEntry) -> None:
        """Adopt an entry that may arrive OUT OF ORDER: a pipelined
        primary fans sub-ops for different objects out concurrently, so
        a replica can see v6 before v5. In-order entries append; an
        out-of-order entry splices into version position (the old
        `version > head` guard silently DROPPED it, leaving the replica
        log with a hole a failover would promote — its dup index would
        re-execute the lost entry's request)."""
        if entry.version > self.head:
            self.append(entry)
            return
        if entry.version <= self.tail:
            return              # trimmed past: implicit
        lo, hi = 0, len(self.entries)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.entries[mid].version < entry.version:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(self.entries) and \
                self.entries[lo].version == entry.version:
            return              # duplicate delivery
        self.entries.insert(lo, entry)
        self._dirty[self.entry_key(entry.version)] = entry
        if entry.version > self._last_by_oid.get(entry.oid, ZERO):
            self._last_by_oid[entry.oid] = entry.version
        if entry.reqid is not None:
            self._reqids[entry.reqid] = entry.version

    def mark_complete(self, version: Eversion) -> None:
        """The execution slice of `version` settled (committed or
        failed out to the client for resend) — completions arrive in
        any order under pipelining."""
        self._incomplete.discard(tuple(version))

    @property
    def last_complete(self) -> Eversion:
        """Newest version with no unsettled predecessor: advances
        CONTIGUOUSLY no matter what order executions complete in."""
        if not self._incomplete:
            return self.head
        lo = min(self._incomplete)
        best = self.tail
        for e in self.entries:
            if e.version >= lo:
                break
            best = e.version
        return best

    def lookup_reqid(self, reqid: tuple) -> Eversion | None:
        """Version recorded for a client request id, if it already
        executed within the retained log window (dup-op detection)."""
        return self._reqids.get(reqid)

    def _rebuild_reqids(self) -> None:
        """Rebuild the derived per-entry indexes (reqid dup table AND
        the per-object newest-version map) after wholesale entry-list
        surgery: load, authoritative merge, backfill adoption."""
        self._reqids = {e.reqid: e.version for e in self.entries
                        if e.reqid is not None}
        self._last_by_oid = {e.oid: e.version for e in self.entries}

    def last_version_of(self, oid: str) -> Eversion:
        """Newest retained entry version for `oid` (ZERO if none) —
        the O(1) prior_version lookup."""
        return self._last_by_oid.get(oid, ZERO)

    def invalidate_reqids_for(self, oid: str, newer_than: Eversion) -> None:
        """Divergence rollback rewound this object past these entries:
        their requests did NOT survive, so retries must re-execute
        rather than be answered from the dup index. The reqid is
        stripped from the ENTRY too — _rebuild_reqids (log reload,
        authoritative merge) would otherwise resurrect the stale dup
        answer."""
        for e in self.entries:
            if e.oid == oid and e.version > newer_than \
                    and e.reqid is not None:
                self._reqids.pop(e.reqid, None)
                e.reqid = None
                self._dirty[self.entry_key(e.version)] = e

    # -- peering -------------------------------------------------------------

    def entries_since(self, since: Eversion) -> list[LogEntry] | None:
        """Entries with version > since, or None if `since` predates the
        tail (log too short -> caller must backfill)."""
        if since < self.tail:
            return None
        return [e for e in self.entries if e.version > since]

    def merge_log(self, auth_entries: Iterable[LogEntry],
                  auth_head: Eversion) -> dict[str, Eversion]:
        """Adopt the authoritative log (PGLog::merge_log semantics):

        * entries we lack (version > our head) are applied to the log and
          their objects become missing (to be pushed);
        * our entries PAST the authoritative head are divergent (we
          accepted writes the quorum never finished): the touched objects
          must be rewound to the authoritative version -> also missing.

        Returns the resulting missing map (oid -> need version).
        """
        auth_entries = list(auth_entries)
        # divergent suffix: anything we have beyond the auth head
        divergent = [e for e in self.entries if e.version > auth_head]
        if divergent:
            self.entries = [e for e in self.entries
                            if e.version <= auth_head]
            self.head = self.entries[-1].version if self.entries \
                else self.tail
            # a rewind invalidates persisted suffix keys: rewrite whole
            self._dirty_full = True
            self._incomplete = {v for v in self._incomplete
                                if v <= auth_head}
            self._last_by_oid = {e.oid: e.version for e in self.entries}
        for e in divergent:
            # latest authoritative version of that object, if any
            auth_v = ZERO
            for a in reversed(auth_entries):
                if a.oid == e.oid:
                    auth_v = a.version
                    break
            if auth_v == ZERO:
                for mine in reversed(self.entries):
                    if mine.oid == e.oid:
                        auth_v = mine.version
                        break
            self.missing[e.oid] = auth_v    # ZERO = delete/rewind to none
        for e in auth_entries:
            if e.version <= self.head:
                continue
            self.append(e)
            if e.op == "delete":
                self.missing.pop(e.oid, None)
                self.missing[e.oid] = ZERO
            else:
                self.missing[e.oid] = e.version
        return dict(self.missing)

    def mark_recovered(self, oid: str) -> None:
        self.missing.pop(oid, None)

    def clear_missing(self) -> None:
        self.missing.clear()

    # -- persistence ---------------------------------------------------------

    def to_dict(self) -> dict:
        return {"entries": [e.to_dict() for e in self.entries],
                "tail": list(self.tail), "head": list(self.head),
                "missing": {o: list(v) for o, v in self.missing.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "PGLog":
        log = cls()
        log.entries = [LogEntry.from_dict(e) for e in d.get("entries", [])]
        log.tail = tuple(d.get("tail", [0, 0]))
        log.head = tuple(d.get("head", [0, 0]))
        log.missing = {o: tuple(v)
                       for o, v in d.get("missing", {}).items()}
        log._rebuild_reqids()
        return log
