"""dmclock-analog tag clocks (src/osd/scheduler/mClockScheduler.h,
after Gulati's mClock / the dmClock distributed variant).

Every scheduling ENTITY — a client tenant, or a background class's
pseudo-entity — carries three virtual-time tags:

  r_tag  reservation clock: advances by cost/reservation per service.
         While r_tag <= now the entity is BEHIND its guaranteed rate
         and is served in the reservation phase (strict priority,
         earliest r_tag first).
  l_tag  limit clock: advances by cost/limit. While l_tag > now the
         entity is at its cap and is ineligible for weight-phase
         service (reservation phase ignores the limit: reservation <=
         limit is the operator's contract, and a guarantee that a cap
         could veto would be no guarantee).
  p_tag  proportional clock: advances by cost/weight. The weight phase
         serves the earliest p_tag among eligible entities — weighted
         fair queueing over the capacity reservations leave behind.

Tags advance as max(tag + cost/rate, now): an idle entity re-anchors
to `now` instead of banking credit, and EVERY service advances ALL
clocks — weight-phase service counts toward the reservation (the
dmclock R-tag adjustment), so a reservation is a floor, not a bonus.

Cost is byte-normalized: cost_of(nbytes) = 1 + nbytes/cost_per_io_bytes,
so a 256 KiB streamer pays ~5x a 4 KiB writer per op and cannot hide
behind op counts.

Overload admission (past saturation, chosen by osd_mclock_overload_policy):

  backpressure  entities at their limit are simply ineligible; when
                every queued entity is limit-blocked the queue sleeps
                until the earliest l_tag matures (deferred dequeue) —
                queue depth is bounded by arrival throttling upstream.
  shed          enqueue refuses (EAGAIN-style throttle reply) once an
                entity's queued depth passes osd_mclock_shed_queue_depth
                — the client's existing backoff path absorbs the retry.

The clock is injectable (`clock=`) so the interleave tier can drive the
arbitration with a deterministic counter and assert same seed => same
dequeue digest.
"""
from __future__ import annotations

import time

from .profile import QosProfile, default_profile

#: entity-table cap: past this, idle zero-queue entities are culled
#: oldest-active-first (a 100k-client storm must not grow an unbounded
#: tag table; an evicted tenant just re-anchors at `now` on return)
MAX_ENTITIES = 1024


class _Entity:
    """One tenant's (or background class's) tag clocks + QoS ledger."""

    __slots__ = ("name", "klass", "reservation", "limit", "weight",
                 "r_tag", "l_tag", "p_tag", "queued", "shed",
                 "deferred", "deq_reservation", "deq_weight",
                 "cost_total", "last_active")

    def __init__(self, name: str, klass: str, now: float,
                 reservation: float, limit: float, weight: float):
        self.name = name
        self.klass = klass
        self.reservation = reservation
        self.limit = limit
        self.weight = weight
        self.r_tag = now
        self.l_tag = now
        self.p_tag = now
        self.queued = 0             # ops waiting in the shard queues
        self.shed = 0               # enqueues refused (shed policy)
        self.deferred = 0           # times this entity's limit deferred
        self.deq_reservation = 0    # dequeues served by reservation
        self.deq_weight = 0         # dequeues served by weight phase
        self.cost_total = 0.0       # cost units served
        self.last_active = now

    def to_dict(self) -> dict:
        return {"klass": self.klass,
                "reservation": self.reservation, "limit": self.limit,
                "weight": self.weight,
                "r_tag": round(self.r_tag, 6),
                "l_tag": round(self.l_tag, 6),
                "p_tag": round(self.p_tag, 6),
                "queued": self.queued, "shed": self.shed,
                "deferred": self.deferred,
                "dequeue_reservation": self.deq_reservation,
                "dequeue_weight": self.deq_weight,
                "cost": round(self.cost_total, 3)}


class MClockScheduler:
    """Tag-clock arbiter. Owns NO queues — ShardedOpQueue keeps the
    per-shard per-entity deques and the ordering windows; this object
    answers "in what order should entities be tried" (schedule), "may
    this op even enter" (note_enqueue / shed) and advances the clocks
    on each admission (charge)."""

    def __init__(self, profile: QosProfile | None = None,
                 clock=time.monotonic):
        self.profile = profile if profile is not None \
            else default_profile()
        self.clock = clock
        self._ents: dict[str, _Entity] = {}
        # client-entity defaults + per-tenant overrides (knobs)
        self.cost_per_io_bytes = 65536
        self.client_reservation = 0.0
        self.client_limit = 0.0
        self.client_weight = 1.0
        self.tenant_profiles: dict[str, dict] = {}
        self.overload_policy = "backpressure"
        self.shed_queue_depth = 256
        # global ledger (the daemon mirrors these into qos_* perf
        # counters; per-entity splits live on the entities)
        self.total_shed = 0
        self.total_deferred = 0

    # -- knobs ---------------------------------------------------------------

    def configure(self, *, cost_per_io_bytes=None,
                  client_reservation=None, client_limit=None,
                  client_weight=None, tenant_profiles=None,
                  overload_policy=None, shed_queue_depth=None,
                  class_params=None) -> None:
        """Apply knob values (config observer path) and re-resolve the
        parameters of every live entity — a hot limit change must bite
        on the next schedule() without waiting for entity churn."""
        if cost_per_io_bytes is not None:
            self.cost_per_io_bytes = max(1, int(cost_per_io_bytes))
        if client_reservation is not None:
            self.client_reservation = max(0.0, float(client_reservation))
        if client_limit is not None:
            self.client_limit = max(0.0, float(client_limit))
        if client_weight is not None:
            self.client_weight = max(0.0, float(client_weight))
        if tenant_profiles is not None:
            self.tenant_profiles = dict(tenant_profiles)
        if overload_policy in ("backpressure", "shed"):
            self.overload_policy = overload_policy
        if shed_queue_depth is not None:
            self.shed_queue_depth = max(1, int(shed_queue_depth))
        if class_params:
            for name, p in class_params.items():
                spec = self.profile.ensure(name)
                if "reservation" in p:
                    spec.reservation = max(0.0, float(p["reservation"]))
                if "limit" in p:
                    spec.limit = max(0.0, float(p["limit"]))
                if "weight" in p:
                    spec.weight = max(0.0, float(p["weight"]))
        for e in self._ents.values():
            e.reservation, e.limit, e.weight = \
                self._params_for(e.name, e.klass)

    def _params_for(self, entity: str,
                    klass: str) -> tuple[float, float, float]:
        if klass != "client":
            spec = self.profile.ensure(klass)
            return spec.reservation, spec.limit, spec.weight
        p = self.tenant_profiles.get(entity)
        if p:
            return (max(0.0, float(p.get("reservation",
                                         self.client_reservation))),
                    max(0.0, float(p.get("limit", self.client_limit))),
                    max(0.0, float(p.get("weight",
                                         self.client_weight))))
        return (self.client_reservation, self.client_limit,
                self.client_weight)

    def cost_of(self, nbytes: int) -> float:
        """Byte-normalized op cost: 1 IOP plus the payload's share of
        the per-IO byte budget."""
        return 1.0 + max(0, int(nbytes)) / self.cost_per_io_bytes

    # -- entity table --------------------------------------------------------

    def entity(self, name: str, klass: str) -> _Entity:
        e = self._ents.get(name)
        if e is None:
            if len(self._ents) >= MAX_ENTITIES:
                self._cull()
            now = self.clock()
            res, lim, wgt = self._params_for(name, klass)
            e = self._ents[name] = _Entity(name, klass, now,
                                           res, lim, wgt)
        return e

    def _cull(self) -> None:
        idle = sorted((e for e in self._ents.values() if e.queued == 0),
                      key=lambda e: e.last_active)
        for e in idle[:max(1, len(idle) // 2)]:
            del self._ents[e.name]

    # -- admission-side ------------------------------------------------------

    def note_enqueue(self, entity: str, klass: str) -> bool:
        """Called before an op enters a shard queue. Returns False to
        SHED it (policy `shed`, entity backlog past the depth cap) —
        background classes are never shed; their producers self-pace
        on completion and a refused recovery push would just stall
        recovery silently."""
        e = self.entity(entity, klass)
        if (self.overload_policy == "shed" and klass == "client"
                and e.queued >= self.shed_queue_depth):
            e.shed += 1
            self.total_shed += 1
            return False
        e.queued += 1
        return True

    def note_drop(self, entity: str) -> None:
        """An enqueued op left the queues without service (migration
        loss paths); keeps the shed depth gauge honest."""
        e = self._ents.get(entity)
        if e is not None and e.queued > 0:
            e.queued -= 1

    # -- scheduling ----------------------------------------------------------

    def schedule(self, ready) -> tuple[list, float | None, str | None]:
        """Arbitrate over `ready` (entity names with queued work the
        queue could try). Returns (order, defer_s, defer_entity):

        order: (entity, phase) pairs to try in sequence — reservation
        phase first (entities behind their guarantee, earliest r_tag),
        then weight phase (limit-eligible entities, earliest p_tag).
        The queue tries each in turn because an entity's head may be
        window-blocked; ties break on entity name so the arbitration
        is schedule-deterministic under an injected clock.

        defer_s/defer_entity: set only when order is empty but work is
        queued — every entity is limit-blocked; defer_s is the time
        until the earliest l_tag matures (the backpressure sleep)."""
        now = self.clock()
        ents = [self.entity(name, "client") if name not in self._ents
                else self._ents[name] for name in ready]
        order: list[tuple[str, str]] = []
        seen: set[str] = set()
        rphase = sorted((e for e in ents
                         if e.reservation > 0.0 and e.r_tag <= now),
                        key=lambda e: (e.r_tag, e.name))
        for e in rphase:
            order.append((e.name, "reservation"))
            seen.add(e.name)
        wphase = sorted((e for e in ents if e.name not in seen
                         and (e.limit <= 0.0 or e.l_tag <= now)),
                        key=lambda e: (e.p_tag, e.name))
        for e in wphase:
            order.append((e.name, "weight"))
        if order or not ents:
            return order, None, None
        blocker = min(ents, key=lambda e: (e.l_tag, e.name))
        blocker.deferred += 1
        self.total_deferred += 1
        return [], max(0.001, blocker.l_tag - now), blocker.name

    def charge(self, entity: str, cost: float,
               phase: str = "weight") -> None:
        """One op of `entity` admitted for execution: advance all three
        clocks by its cost (service counts toward reservation AND
        limit AND proportional share regardless of which phase won)."""
        e = self._ents.get(entity)
        if e is None:
            return
        now = self.clock()
        if e.reservation > 0.0:
            e.r_tag = max(e.r_tag + cost / e.reservation, now)
        if e.limit > 0.0:
            e.l_tag = max(e.l_tag + cost / e.limit, now)
        if e.weight > 0.0:
            e.p_tag = max(e.p_tag + cost / e.weight, now)
        if e.queued > 0:
            e.queued -= 1
        if phase == "reservation":
            e.deq_reservation += 1
        else:
            e.deq_weight += 1
        e.cost_total += cost
        e.last_active = now

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        """Admin-socket `qos status` body: knobs in force + every live
        entity's tag clocks and ledger."""
        return {"cost_per_io_bytes": self.cost_per_io_bytes,
                "client_reservation": self.client_reservation,
                "client_limit": self.client_limit,
                "client_weight": self.client_weight,
                "tenant_profiles": dict(self.tenant_profiles),
                "overload_policy": self.overload_policy,
                "shed_queue_depth": self.shed_queue_depth,
                "total_shed": self.total_shed,
                "total_deferred": self.total_deferred,
                "now": round(self.clock(), 6),
                "classes": self.profile.to_dict(),
                "entities": {name: e.to_dict() for name, e
                             in sorted(self._ents.items())}}

    def tenant_metrics(self) -> dict:
        """Per-entity qos ledger for the MgrReport leg (absolute
        counters; the mgr stores latest-wins per daemon)."""
        return {name: {"shed": e.shed, "deferred": e.deferred,
                       "dequeue_reservation": e.deq_reservation,
                       "dequeue_weight": e.deq_weight,
                       "queued": e.queued,
                       "cost": round(e.cost_total, 3)}
                for name, e in self._ents.items()
                if e.cost_total > 0 or e.shed or e.queued}

    def tag_columns(self, entity: str) -> dict:
        """dump_clients merge: the live QoS tag columns of one tenant
        (empty when the tenant has no tag state yet)."""
        e = self._ents.get(entity)
        if e is None:
            return {}
        return {"qos_r_tag": round(e.r_tag, 6),
                "qos_l_tag": round(e.l_tag, 6),
                "qos_p_tag": round(e.p_tag, 6),
                "qos_queued": e.queued, "qos_shed": e.shed}
