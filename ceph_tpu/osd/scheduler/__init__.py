"""OSD op scheduling (src/osd/scheduler/): class registration profile
and the dmclock-analog tag-clock arbiter.

The package mirrors the reference's scheduler split: `profile` declares
the op classes (what used to be the hardcoded `ShardedOpQueue.WEIGHTS`)
and their default QoS parameters; `dmclock` holds the tag math —
per-entity reservation/limit/weight clocks plus overload admission
(shed / backpressure). `ShardedOpQueue` stays the owner of queues,
ordering windows and workers; it consults the scheduler only for
"which entity next" and "may this op even enter".
"""
from .profile import ClassSpec, QosProfile, default_profile
from .dmclock import MClockScheduler

__all__ = ["ClassSpec", "QosProfile", "default_profile",
           "MClockScheduler"]
