"""Op-class registration profile (src/osd/scheduler/OpSchedulerItem.h
op_scheduler_class + mClock profile defaults).

Classes are DECLARED here, not hardcoded in the queue: each ClassSpec
carries both the legacy WRR weight (the scheduler-off arbitration) and
the dmclock parameters its pseudo-entity runs with when the scheduler
is on. Background classes (recovery, scrub, snaptrim) are queue-side
entities — they arbitrate against client tenants under the same tag
clocks, which is exactly how a reservation guarantees background
progress without letting it starve clients.
"""
from __future__ import annotations


class ClassSpec:
    """One declared op class.

    wrr: dequeues per round under the legacy weighted-round-robin path
    (scheduler off). reservation/limit/weight: dmclock parameters of
    the class pseudo-entity (background classes) — client-class ops are
    tagged per TENANT instead, from the osd_mclock_client_* knobs, so
    the client spec's QoS fields are only the fallback defaults.
    Rates are in cost units/second where one cost unit is a small op
    (byte-normalized; see MClockScheduler.cost_of)."""

    __slots__ = ("name", "wrr", "reservation", "limit", "weight",
                 "background")

    def __init__(self, name: str, wrr: int = 1,
                 reservation: float = 0.0, limit: float = 0.0,
                 weight: float = 1.0, background: bool = False):
        self.name = name
        self.wrr = max(1, int(wrr))
        self.reservation = float(reservation)
        self.limit = float(limit)
        self.weight = float(weight)
        self.background = background

    def to_dict(self) -> dict:
        return {"name": self.name, "wrr": self.wrr,
                "reservation": self.reservation, "limit": self.limit,
                "weight": self.weight, "background": self.background}


class QosProfile:
    """Ordered registry of op classes. Declaration order IS the legacy
    WRR scan order (dict insertion order), so the default profile must
    list `client` first to keep the historical interleave."""

    def __init__(self, classes):
        self.classes: dict[str, ClassSpec] = {}
        for c in classes:
            self.classes[c.name] = c

    def spec(self, name: str) -> ClassSpec:
        return self.classes[name]

    def ensure(self, name: str) -> ClassSpec:
        """Late registration for a class no profile declared: it gets
        wrr=1 best-effort background defaults rather than a KeyError —
        producers declare intent by enqueueing, the profile only
        refuses to hardcode."""
        c = self.classes.get(name)
        if c is None:
            c = self.classes[name] = ClassSpec(name, wrr=1,
                                               background=True)
        return c

    def wrr_weights(self) -> dict[str, int]:
        return {c.name: c.wrr for c in self.classes.values()}

    def to_dict(self) -> dict:
        return {name: c.to_dict() for name, c in self.classes.items()}


def default_profile() -> QosProfile:
    """The stock OSD profile: client traffic at the historical 4:1 WRR
    edge over the background classes; under dmclock, recovery's
    pseudo-entity gets a small reservation (guaranteed progress while
    degraded) but only half a client tenant's weight (yields excess
    bandwidth). Scrub and snaptrim are DECLARED background customers —
    scrub's scan-chunk grant tokens and snaptrim's per-object trims
    enqueue under these specs, so they pace against client I/O with a
    guaranteed trickle instead of late-registering at best-effort
    wrr=1 defaults. Their reservations are deliberately small: integrity
    scanning and snap GC must keep moving, never compete."""
    return QosProfile([
        ClassSpec("client", wrr=4,
                  reservation=0.0, limit=0.0, weight=1.0),
        ClassSpec("recovery", wrr=1, background=True,
                  reservation=4.0, limit=0.0, weight=0.5),
        ClassSpec("scrub", wrr=1, background=True,
                  reservation=2.0, limit=0.0, weight=0.25),
        ClassSpec("snaptrim", wrr=1, background=True,
                  reservation=1.0, limit=0.0, weight=0.25),
    ])
