"""PGBackend: how a PG applies ops to its acting set.

Re-creation of the reference's backend split (src/osd/PGBackend.cc:570
build_pg_backend: replicated vs erasure by pool type):

  * ReplicatedBackend (src/osd/ReplicatedBackend.cc): the primary applies
    the transaction locally and sends the whole logical op to every
    replica (MOSDRepOp); the client is acked when ALL live replicas
    commit.
  * ECBackend lives in ec_backend.py.

Idiomatic divergences: replicas re-execute the logical op (write_full /
remove are full-state, so re-execution == transaction shipping);
sub-op acks resolve asyncio futures instead of Context callbacks.
"""
from __future__ import annotations

import asyncio
import contextlib
import json
from typing import TYPE_CHECKING

from ceph_tpu.crush.crush import CRUSH_NONE
from ceph_tpu.msg.messages import MOSDRepOp, MOSDRepOpReply
from ceph_tpu.objectstore.store import StoreError, Transaction
from ceph_tpu.objectstore.types import CollectionId, Ghobject
from ceph_tpu.osd.pglog import LogEntry
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.work_queue import mark_op_event

if TYPE_CHECKING:
    from ceph_tpu.osd.pg import PGInstance

SUBOP_TIMEOUT = 10.0


class IntervalChange(Exception):
    """The peering interval changed under an in-flight op; the op is not
    failed to the client — the primary (possibly a new one) re-runs it
    (the reference re-queues ops across intervals instead of erroring)."""


class PGBackend:
    """Common plumbing; subclasses implement the write/read fan-out."""

    def __init__(self, pg: "PGInstance"):
        self.pg = pg
        self._tid = 0
        # tid -> (pending peer set, future)
        self._inflight: dict[int, tuple[set[int], asyncio.Future]] = {}
        # per-object write ordering (the reference's ObjectContext rw
        # locks): pipelined PG execution runs ops to DIFFERENT objects
        # concurrently; the commit section of same-object mutations —
        # log intent + apply/fan-out — must serialize or interleave
        # into lost updates. oid -> [lock, users]; refcounted so churn
        # workloads don't grow the dict unboundedly.
        self._obj_locks: dict[str, list] = {}

    @contextlib.asynccontextmanager
    async def obj_lock(self, oid: str):
        """Acquire this object's write-ordering lock (FIFO-fair:
        asyncio.Lock wakes waiters in acquisition order, so same-object
        ops commit in arrival order). NOT reentrant — a holder must not
        re-enter the modify path for the same oid."""
        ent = self._obj_locks.get(oid)
        if ent is None:
            ent = self._obj_locks[oid] = [asyncio.Lock(), 0]
        ent[1] += 1
        try:
            async with ent[0]:
                yield
        finally:
            ent[1] -= 1
            if ent[1] == 0 and self._obj_locks.get(oid) is ent:
                del self._obj_locks[oid]

    # -- identity ------------------------------------------------------------

    @property
    def host(self):
        return self.pg.host

    def coll(self, shard: int = -1) -> CollectionId:
        return CollectionId.make_pg(self.pg.pgid.pool, self.pg.pgid.ps,
                                    shard)

    def ghobject(self, oid: str, shard: int = -1) -> Ghobject:
        return Ghobject(pool=self.pg.pgid.pool, name=oid, shard=shard)

    def new_tid(self) -> int:
        self._tid += 1
        return self._tid

    # -- sub-op ack plumbing -------------------------------------------------

    def _start_waiting(self, tid: int, peers: set[int]) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        if peers:
            self._inflight[tid] = (set(peers), fut)
        else:
            fut.set_result(None)
        return fut

    def sub_op_ack(self, tid: int, from_osd: int) -> None:
        ent = self._inflight.get(tid)
        if ent is None:
            return
        pending, fut = ent
        pending.discard(from_osd)
        if not pending:
            del self._inflight[tid]
            if not fut.done():
                fut.set_result(None)

    def fail_inflight(self, why: str) -> None:
        for pending, fut in self._inflight.values():
            if not fut.done():
                fut.set_exception(IntervalChange(why))
        self._inflight.clear()

    # -- local store helpers -------------------------------------------------

    def ensure_collections(self) -> None:
        cid = self.coll()
        if not self.host.store.collection_exists(cid):
            txn = Transaction().create_collection(cid)
            self.host.store.queue_transaction(txn)

    def local_apply(self, oid: str, op: str, data: bytes,
                    attrs: dict[str, bytes] | None = None,
                    shard: int = -1, off: int = 0,
                    omap: dict[str, bytes] | None = None) -> None:
        cid = self.coll(shard)
        gh = self.ghobject(oid, shard)
        if not isinstance(data, (bytes, bytearray)) and \
                op not in ("write_full", "push", "write"):
            # control-kind payloads (json / decimal-coded op args)
            # arrive as zero-copy memoryviews off the wire and their
            # decoders below need bytes semantics; the BULK kinds above
            # keep the view — the store writes straight from it
            data = bytes(data)
        txn = Transaction()
        if op == "write_full":
            # WRITEFULL replaces the DATA only — xattrs and omap survive
            # (the reference's CEPH_OSD_OP_WRITEFULL; an RBD header
            # rewrite must not wipe its cls-lock omap state)
            if self.host.store.exists(cid, gh):
                txn.truncate(cid, gh, 0)
            else:
                txn.touch(cid, gh)
            txn.write(cid, gh, 0, data)
        elif op == "push":
            # recovery push IS full-state: replace everything
            if self.host.store.exists(cid, gh):
                txn.remove(cid, gh)
            txn.touch(cid, gh)
            txn.write(cid, gh, 0, data)
            if attrs:
                txn.setattrs(cid, gh, attrs)
            if omap:
                txn.omap_setkeys(cid, gh, omap)
        elif op == "write":
            if not self.host.store.exists(cid, gh):
                txn.touch(cid, gh)
            txn.write(cid, gh, off, data)
        elif op == "truncate":
            if not self.host.store.exists(cid, gh):
                txn.touch(cid, gh)
            txn.truncate(cid, gh, off)
        elif op == "zero":
            # data carries the length as decimal bytes (ops re-execute on
            # replicas; zero has no payload of its own)
            if not self.host.store.exists(cid, gh):
                txn.touch(cid, gh)
            txn.zero(cid, gh, off, int(data))
        elif op == "create":
            txn.touch(cid, gh)
        elif op == "setxattr":
            kv = json.loads(data)
            if not self.host.store.exists(cid, gh):
                txn.touch(cid, gh)
            txn.setattrs(cid, gh,
                         {"u:" + kv["name"]:
                          kv["value"].encode("latin1")})
        elif op == "rmxattr":
            name = "u:" + bytes(data).decode()
            try:
                self.host.store.getattr(cid, gh, name)
            except StoreError:
                pass        # absent attr (or object): rm is a no-op
            else:
                txn.rmattr(cid, gh, name)
        elif op == "omap_set":
            kv = json.loads(data)
            if not self.host.store.exists(cid, gh):
                txn.touch(cid, gh)
            txn.omap_setkeys(cid, gh, {k: v.encode("latin1")
                                       for k, v in kv.items()})
        elif op == "omap_rm":
            if self.host.store.exists(cid, gh):
                txn.omap_rmkeys(cid, gh, json.loads(data))
        elif op in ("delete", "remove"):
            # client delete removes HEAD only; clones/snapdir survive
            # (make_writeable has already cloned when a snapc required)
            if self.host.store.exists(cid, gh):
                txn.remove(cid, gh)
        elif op == "clone":
            from ceph_tpu.osd import snaps
            p = json.loads(data)
            snaps.apply_clone(self.host.store, cid, gh, self.pg._meta_gh(),
                              p["cloneid"], p["snaps"], p["seq_only"],
                              size=p.get("size"))
            return
        elif op == "rollback":
            from ceph_tpu.osd import snaps
            snaps.apply_rollback(self.host.store, cid, gh, int(data))
            return
        elif op == "snaptrim":
            from ceph_tpu.osd import snaps
            snaps.apply_snaptrim(self.host.store, cid, gh,
                                 self.pg._meta_gh(), int(data))
            return
        elif op == "purge":
            from ceph_tpu.osd import snaps
            snaps.purge_object(self.host.store, cid, gh, self.pg._meta_gh())
            return
        else:
            raise StoreError("EINVAL", f"unknown backend op {op!r}")
        self.host.store.queue_transaction(txn)

    def local_read(self, oid: str, shard: int = -1) -> bytes:
        return self.host.store.read(self.coll(shard),
                                    self.ghobject(oid, shard))

    def local_exists(self, oid: str, shard: int = -1) -> bool:
        return self.host.store.exists(self.coll(shard),
                                      self.ghobject(oid, shard))

    # -- interface subclasses implement --------------------------------------

    async def execute_write(self, oid: str, op: str, data: bytes,
                            entry: LogEntry, off: int = 0) -> None:
        raise NotImplementedError

    async def execute_read(self, oid: str, offset: int, length: int) -> bytes:
        raise NotImplementedError

    async def object_exists(self, oid: str) -> bool:
        """Whether the object logically exists in this PG. The EC backend
        overrides: the primary's own positional chunk can be missing or
        corrupt while >= k shards exist on peers (ADVICE r4)."""
        return self.local_exists(oid)

    def object_size(self, oid: str) -> int:
        raise NotImplementedError

    async def execute_stat(self, oid: str) -> int:
        return self.object_size(oid)

    async def verify_dup_committed(self, oid: str, version) -> bool:
        """Whether a dup-index hit may be answered as done. The
        replicated primary applies locally in the same event-loop slice
        as the log append, so a logged entry is always applied here and
        recovery rolls it forward — always answerable."""
        return True

    # -- recovery hooks (PG peering calls these) -----------------------------

    def read_for_push(self, oid: str, shard: int = -1) -> tuple[bytes, dict]:
        """Object payload + attrs for a recovery push."""
        cid, gh = self.coll(shard), self.ghobject(oid, shard)
        return (self.host.store.read(cid, gh),
                self.host.store.getattrs(cid, gh))

    def omap_for_push(self, oid: str, shard: int = -1) -> dict[str, bytes]:
        return self.host.store.omap_get(self.coll(shard),
                                        self.ghobject(oid, shard))

    def apply_push(self, oid: str, data: bytes, attrs: dict,
                   delete: bool, shard: int = -1,
                   omap: dict[str, bytes] | None = None,
                   snap_state: dict | None = None,
                   snap: int | None = None,
                   ss_blob: str | None = None) -> None:
        if snap is not None or ss_blob is not None:
            # EC snapshot-state push: a reconstructed CLONE chunk for
            # this position, or the replicated SnapSet for the snapdir
            # (clones ride recovery one push per clone, like head chunks)
            from ceph_tpu.osd import snaps
            cid = self.coll(shard)
            head = self.ghobject(oid, shard)
            txn = Transaction()
            if snap is not None:
                cgh = snaps.clone_gh(head, snap)
                if self.host.store.exists(cid, cgh):
                    txn.remove(cid, cgh)
                txn.touch(cid, cgh)
                if data:
                    txn.write(cid, cgh, 0, data)
                if attrs:
                    txn.setattrs(cid, cgh, attrs)
            if ss_blob is not None:
                ss = snaps.SnapSet.from_json(ss_blob.encode())
                # the pushed SnapSet REPLACES local snapshot state:
                # stale clone blobs (e.g. a trim that ran while this
                # peer was down) and this object's SnapMapper keys must
                # go, or they leak forever and re-trigger trims
                old = snaps.load_snapset(self.host.store, cid, head)
                keep = {c["id"] for c in ss.clones}
                if old is not None:
                    rm = []
                    for clone in old.clones:
                        rm.extend(snaps.sm_key(s, oid)
                                  for s in clone["snaps"])
                        if clone["id"] in keep:
                            continue
                        cgh = snaps.clone_gh(head, clone["id"])
                        if self.host.store.exists(cid, cgh):
                            txn.remove(cid, cgh)
                    if rm:
                        txn.omap_rmkeys(cid, self.pg._meta_gh(), rm)
                sd = snaps.snapdir_gh(head)
                if not self.host.store.exists(cid, sd):
                    txn.touch(cid, sd)
                txn.setattr(cid, sd, snaps.SS_ATTR, ss.to_json())
                sm = {snaps.sm_key(s, oid): b"1"
                      for clone in ss.clones for s in clone["snaps"]}
                if sm:
                    txn.omap_setkeys(cid, self.pg._meta_gh(), sm)
            self.host.store.queue_transaction(txn)
            return
        if delete:
            self.local_apply(oid, "delete", b"", shard=shard)
        else:
            self.local_apply(oid, "push", data, attrs=attrs, shard=shard,
                             omap=omap)
        if self.pg.pool.type == "replicated":
            # full-state push replaces snapshot state too (clears stale
            # clones when the authoritative object has none)
            from ceph_tpu.osd import snaps
            snaps.apply_snap_push(self.host.store, self.coll(shard),
                                  self.ghobject(oid, shard),
                                  self.pg._meta_gh(), snap_state)

    def snap_state_for_push(self, oid: str) -> dict | None:
        if self.pg.pool.type != "replicated":
            return None
        from ceph_tpu.osd import snaps
        return snaps.snap_state_for_push(self.host.store, self.coll(),
                                         self.ghobject(oid))

    async def push_object(self, peer: int, oid: str) -> None:
        """Push this object's local state (or its absence) to `peer`.
        The EC backend overrides this to reconstruct the peer's
        positional chunk instead."""
        snap_state = self.snap_state_for_push(oid)
        if self.local_exists(oid):
            data, attrs = self.read_for_push(oid)
            await self.pg.send_push(peer, oid, data, attrs, delete=False,
                                    omap=self.omap_for_push(oid),
                                    snap_state=snap_state)
        else:
            await self.pg.send_push(peer, oid, b"", None, delete=True,
                                    snap_state=snap_state)

    async def pull_object(self, auth_peer: int, oid: str, need,
                          fallbacks=()) -> None:
        """Fetch this object's authoritative state from `auth_peer`,
        trying `fallbacks` before accepting absence: a single source
        that happens to lack the object must not tombstone a copy
        another peer still holds."""
        for peer in [auth_peer, *fallbacks]:
            await self.pg.pull_transport(peer, oid)
            if self.local_exists(oid):
                return


class ReplicatedBackend(PGBackend):
    """Primary fans the logical op to all live replicas and waits for
    every commit (src/osd/ReplicatedBackend.cc submit_transaction)."""

    async def execute_write(self, oid: str, op: str, data: bytes,
                            entry: LogEntry, off: int = 0) -> None:
        pg = self.pg
        if op == "append":
            # resolve the append offset at the primary so every replica
            # splices at the same position regardless of its local state
            op = "write"
            off = self.object_size(oid) if self.local_exists(oid) else 0
        peers = {o for o in pg.acting
                 if o not in (CRUSH_NONE, self.host.whoami)}
        tid = self.new_tid()
        fut = self._start_waiting(tid, peers)
        # local first (the primary is always a replica of itself). The
        # caller logged the entry synchronously before this call, so a
        # retry after ANY mid-fan-out failure dup-detects instead of
        # re-executing against polluted local state (an unlogged
        # applied APPEND made a retry resolve its offset one payload
        # too far — found by the thrashing model checker). The
        # reference writes pg log entries in the same ObjectStore
        # transaction as the data for the same reason; here entry
        # append + local apply run in one event-loop slice.
        self.local_apply(oid, op, data, off=off)
        msg_payload = {
            "pgid": [pg.pgid.pool, pg.pgid.ps],
            "tid": tid,
            "epoch": self.host.osdmap.epoch,
            "from": self.host.whoami,
            "oid": oid,
            "op": op,
            "off": off,
            "entry": entry.to_dict(),
        }
        for peer in peers:
            await self.host.send_osd(peer, MOSDRepOp(dict(msg_payload),
                                                     data))
        mark_op_event("sub_ops_sent")
        await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        mark_op_event("commit")

    async def execute_read(self, oid: str, offset: int,
                           length: int) -> bytes:
        data = self.local_read(oid)
        if length <= 0:
            return data[offset:]
        return data[offset:offset + length]

    def object_size(self, oid: str) -> int:
        return self.host.store.stat(self.coll(), self.ghobject(oid))["size"]

    # -- replica side --------------------------------------------------------

    async def handle_rep_op(self, conn, msg: MOSDRepOp) -> None:
        p = msg.payload
        entry = LogEntry.from_dict(p["entry"])
        self.local_apply(p["oid"], p["op"], msg.data, off=p.get("off", 0))
        # insert, not append: a pipelined primary's concurrent fan-outs
        # can deliver v6 before v5 — the old `> head` guard dropped the
        # late entry, leaving this replica's log (and dup index) with a
        # hole a failover would promote
        self.pg.log.insert(entry)
        if p["op"] in ("push", "delete", "create"):
            # only FULL-state ops supersede a missing base; an extent
            # write — and now write_full too, since it preserves
            # xattrs/omap it cannot supply — leaves a missing object
            # missing until recovery pushes the whole state
            self.pg.log.mark_recovered(p["oid"])
        # coalesced with any other sub-ops landing this loop slice; the
        # ack rides the flush so rc=0 never outruns the durable entry
        self.pg.persist_meta_soon(ack=(conn, MOSDRepOpReply(
            {"pgid": p["pgid"], "tid": p["tid"],
             "from": self.host.whoami, "rc": 0})))
