"""MonClient: the daemon/client side of the monitor plane.

Re-creation of src/mon/MonClient.{h,cc} essentials: hunt for a live
monitor, bootstrap the monmap, subscribe to map updates, and run
commands with retry — commands bounce off peons with a leader hint
(rc=-11) and the client re-targets, like the reference's command retry
on EAGAIN/leader change. Auth is the `none` method (matching the
messenger this round).

The MonClient shares its daemon's Messenger (the reference wires
MonClient into the daemon's client messenger the same way) and speaks
over a lossy client connection: a transport fault drops the session and
the hunt loop picks another monitor.
"""
from __future__ import annotations

import asyncio
import time

from ceph_tpu.msg.messages import (MLog, Message, MMgrMap, MMonCommand,
                                   MMonCommandAck, MMonGetMap, MMonMap,
                                   MMonMgrReport, MMonSubscribe, MOSDBoot,
                                   MOSDFailure, MOSDMapMsg)
from ceph_tpu.msg.messenger import Connection, Dispatcher, Messenger, Policy
from ceph_tpu.utils.dout import dout


class MonClient(Dispatcher):
    COMMAND_TIMEOUT = 10.0      # per-attempt ack wait
    HUNT_BACKOFF = 0.1

    def __init__(self, messenger: Messenger,
                 mon_addrs: list[tuple[str, int]]):
        self.messenger = messenger
        self.messenger.add_dispatcher(self)
        self.mon_addrs = [tuple(a) for a in mon_addrs]
        self.monmap: dict | None = None
        # latest pushed mgrmap (subscribe "mgrmap"): daemons resolve the
        # active mgr from this cache, never by polling commands
        self.mgrmap: dict | None = None
        self._conn: Connection | None = None
        self._cur_addr: tuple[str, int] | None = None
        self._tid = 0
        self._waiters: dict[int, asyncio.Future] = {}
        # subscriptions: what -> start epoch; re-sent after re-hunt
        self._sub_want: dict[str, int] = {}
        self.on_osdmap = None       # callback(payload dict)
        self._closed = False

    # -- connection hunt -----------------------------------------------------

    async def _ensure_conn(self) -> Connection:
        if self._conn is not None and not self._conn._closed \
                and self._conn.connected:
            return self._conn
        last_err: Exception | None = None
        for _ in range(3):
            for addr in self.mon_addrs:
                if self._closed:
                    raise ConnectionError("monclient closed")
                try:
                    conn = await self.messenger.connect(
                        addr, Policy.lossy_client())
                    self._conn = conn
                    self._cur_addr = addr
                    self._resubscribe()
                    return conn
                except Exception as e:
                    last_err = e
            await asyncio.sleep(self.HUNT_BACKOFF)
        raise ConnectionError(f"no monitor reachable: {last_err}")

    async def _retarget(self, addr: tuple[str, int] | None) -> None:
        """Drop the current session; optionally pin the next hunt to the
        leader address a peon handed us."""
        self._conn = None
        if addr is not None:
            addr = tuple(addr)
            if addr in self.mon_addrs:
                # rotate so the hunt tries the leader first
                i = self.mon_addrs.index(addr)
                self.mon_addrs = self.mon_addrs[i:] + self.mon_addrs[:i]
            else:
                self.mon_addrs.insert(0, addr)

    def _resubscribe(self) -> None:
        if self._sub_want and self._conn is not None:
            self._conn.send_message(MMonSubscribe(
                {"what": dict(self._sub_want)}))

    # -- public API ----------------------------------------------------------

    async def start(self) -> None:
        """Bootstrap: fetch the monmap from whichever mon answers."""
        conn = await self._ensure_conn()
        conn.send_message(MMonGetMap({"what": "monmap"}))

    async def command(self, cmd: dict, timeout: float = 30.0) -> dict:
        """Run a command against the leader; retries through leader hints
        and transport faults until it lands or the deadline passes."""
        deadline = time.monotonic() + timeout
        last = "no attempt"
        while time.monotonic() < deadline:
            try:
                conn = await self._ensure_conn()
            except ConnectionError as e:
                last = str(e)
                await asyncio.sleep(self.HUNT_BACKOFF)
                continue
            self._tid += 1
            tid = self._tid
            fut = asyncio.get_running_loop().create_future()
            self._waiters[tid] = fut
            conn.send_message(MMonCommand({"tid": tid, "cmd": cmd}))
            try:
                ack = await asyncio.wait_for(
                    fut, min(self.COMMAND_TIMEOUT,
                             max(0.1, deadline - time.monotonic())))
            except asyncio.TimeoutError:
                last = f"ack timeout from {self._cur_addr}"
                await self._retarget(None)
                continue
            finally:
                self._waiters.pop(tid, None)
            rc = ack.get("rc", 0)
            if rc == 0:
                return ack.get("out", {})
            if rc == -11:          # not leader: follow the hint
                last = ack.get("error", "not leader")
                await self._retarget(ack.get("leader_addr"))
                await asyncio.sleep(self.HUNT_BACKOFF)
                continue
            raise RuntimeError(ack.get("error", f"command failed rc={rc}"))
        raise TimeoutError(f"mon command {cmd.get('prefix')!r} timed out "
                           f"({last})")

    def subscribe(self, what: str, start: int) -> None:
        """Subscribe to map updates (MMonSubscribe); push survives
        re-hunts. osdmap payloads land on self.on_osdmap."""
        self._sub_want[what] = start
        if self._conn is not None and self._conn.connected:
            self._resubscribe()

    def sub_got(self, what: str, epoch: int) -> None:
        """Advance the subscription cursor after consuming an epoch."""
        if what in self._sub_want:
            self._sub_want[what] = max(self._sub_want[what], epoch + 1)

    async def request_osdmap(self, have: int = 0) -> None:
        """Ask for the current osdmap (reply lands on on_osdmap)."""
        conn = await self._ensure_conn()
        conn.send_message(MMonGetMap({"what": "osdmap", "have": have}))

    async def send_boot(self, osd: int, addr: tuple[str, int],
                        crush_location: dict | None = None,
                        weight: float = 1.0) -> None:
        conn = await self._ensure_conn()
        conn.send_message(MOSDBoot(
            {"osd": osd, "addr": list(addr),
             "crush_location": crush_location or {}, "weight": weight}))

    async def report_failure(self, failed: int, reporter: int) -> None:
        conn = await self._ensure_conn()
        conn.send_message(MOSDFailure({"failed": failed, "from": reporter}))

    _LOG_LEVELS = ("WRN", "ERR")

    async def send_log(self, level: str, who: str, message: str) -> None:
        """Ship one cluster-log line to the mon (LogClient-lite). Only
        WARN+ levels travel — the channel is for health events, not
        debug chatter (mon_cluster_log_level analog)."""
        if level not in self._LOG_LEVELS:
            return
        conn = await self._ensure_conn()
        conn.send_message(MLog({"level": level, "who": who,
                                "message": message, "stamp": time.time()}))

    async def send_mgr_report(self, payload: dict) -> None:
        """Ship the mgr's aggregated health digest to the mon
        (MMonMgrReport; fire-and-forget like the osd plane — the next
        tick re-sends a fresher digest anyway)."""
        conn = await self._ensure_conn()
        conn.send_message(MMonMgrReport(payload))

    async def close(self) -> None:
        self._closed = True
        for fut in self._waiters.values():
            if not fut.done():
                fut.cancelled() or fut.cancel()
        self._waiters.clear()

    # -- dispatch ------------------------------------------------------------

    async def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, MMonCommandAck):
            fut = self._waiters.get(msg.payload.get("tid", 0))
            if fut is not None and not fut.done():
                fut.set_result(msg.payload)
            return True
        if isinstance(msg, MMonMap):
            self.monmap = msg.payload.get("monmap")
            return True
        if isinstance(msg, MOSDMapMsg):
            if self.on_osdmap is not None:
                res = self.on_osdmap(msg.payload)
                if asyncio.iscoroutine(res):
                    await res
            return True
        if isinstance(msg, MMgrMap):
            m = msg.payload.get("mgrmap")
            if m and (self.mgrmap is None or m.get("epoch", 0)
                      >= self.mgrmap.get("epoch", 0)):
                self.mgrmap = m
                self.sub_got("mgrmap", m.get("epoch", 0))
            return True
        return False

    def ms_handle_reset(self, conn: Connection) -> None:
        if conn is self._conn:
            dout("monc", 10, "mon session reset; will re-hunt")
            self._conn = None
