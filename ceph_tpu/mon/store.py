"""MonStore — MonitorDBStore-lite (src/mon/MonitorDBStore.h).

Prefixed key/value store with atomic transactions and JSON-file
persistence. The reference runs RocksDB; monitor state is tiny (maps,
paxos versions, service state), so a dict snapshotted to disk with
atomic rename gives the same contract: a transaction is either fully
visible after restart or not at all.
"""
from __future__ import annotations

import json
import os
import tempfile


class MonStoreTxn:
    def __init__(self):
        self.ops: list[tuple] = []

    def put(self, prefix: str, key: str, value) -> None:
        self.ops.append(("put", prefix, str(key), value))

    def erase(self, prefix: str, key: str) -> None:
        self.ops.append(("erase", prefix, str(key)))


class MonStore:
    def __init__(self, path: str | None = None):
        self.path = path
        self._data: dict[str, dict[str, object]] = {}
        if path and os.path.exists(path):
            with open(path) as f:
                self._data = json.load(f)

    # -- reads ---------------------------------------------------------------

    def get(self, prefix: str, key: str, default=None):
        return self._data.get(prefix, {}).get(str(key), default)

    def exists(self, prefix: str, key: str) -> bool:
        return str(key) in self._data.get(prefix, {})

    def keys(self, prefix: str) -> list[str]:
        return sorted(self._data.get(prefix, {}))

    # -- writes --------------------------------------------------------------

    def apply_transaction(self, txn: MonStoreTxn) -> None:
        for op in txn.ops:
            if op[0] == "put":
                _, prefix, key, value = op
                self._data.setdefault(prefix, {})[key] = value
            else:
                _, prefix, key = op
                self._data.get(prefix, {}).pop(key, None)
        self._persist()

    def put_one(self, prefix: str, key: str, value) -> None:
        txn = MonStoreTxn()
        txn.put(prefix, key, value)
        self.apply_transaction(txn)

    # -- full sync (Monitor store sync for hopelessly-behind peers) ----------

    def dump(self) -> dict:
        return json.loads(json.dumps(self._data))   # deep, JSON-safe copy

    def load_dump(self, data: dict) -> None:
        self._data = data
        self._persist()

    def size_bytes(self) -> int:
        """Serialized size — used by the bounded-growth test."""
        return len(json.dumps(self._data))

    def _persist(self) -> None:
        if not self.path:
            return
        d = os.path.dirname(self.path) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".monstore.")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._data, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
