"""Monitor: cluster control plane.

Re-creation of the reference's src/mon/: a quorum of monitor daemons runs
single-Paxos (collect/begin/accept/commit/lease, src/mon/Paxos.cc) over a
versioned store, with PaxosServices batching state changes into proposed
transactions (src/mon/PaxosService.cc). The OSDMonitor service owns the
OSDMap: EC profiles and pools are validated in-monitor by instantiating
the plugin (OSDMonitor.cc:7506), osd boots and failure reports become
map incrementals, and committed epochs are pushed to subscribers.

  store       MonitorDBStore-lite: prefixed KV + atomic transactions,
              JSON-file persistence
  paxos       elections + collect/begin/accept/commit/lease over the
              messenger
  monitor     Monitor daemon + OSDMonitor service + subscriptions
  mon_client  MonClient: bootstrap, subscriptions, commands
"""
from ceph_tpu.mon.store import MonStore
from ceph_tpu.mon.monitor import Monitor, MonMap
from ceph_tpu.mon.mon_client import MonClient

__all__ = ["MonStore", "Monitor", "MonMap", "MonClient"]
