"""Monitor daemon: quorum member + OSDMonitor service + client plane.

Reference shape (src/mon/Monitor.cc, OSDMonitor.cc, PaxosService.cc):
the monitor owns a Paxos instance; services express state changes as
pending transactions proposed through it; every quorum member applies
committed transactions in order, so service state is identical across
monitors. The OSDMonitor's state is the OSDMap:

  * EC profiles and pools are validated in-monitor by instantiating the
    erasure-code plugin from the profile (OSDMonitor.cc:7506
    get_erasure_code; :11260 profile set) — a bad profile never reaches
    the map;
  * pool create derives size=k+m / min_size=k+1 from the plugin and
    builds the CRUSH rule via the EC default (indep, ErasureCode.cc:70);
  * osd boots (MOSDBoot) add the osd under its crush_location and mark
    it up; failure reports (MOSDFailure) mark it down once enough
    distinct reporters agree (OSDMonitor.cc:2868 reporter quorum); a
    leader tick marks long-down osds out (down_out_interval);
  * committed epochs are pushed to osdmap subscribers as incrementals.

Peons forward osd-plane messages to the leader and bounce commands with
a leader hint (the reference forwards those too; the client retry keeps
this simpler without changing observable behavior).
"""
from __future__ import annotations

import asyncio
import collections
import json
import time

from ceph_tpu.crush import CrushMap, Incremental, OSDMap, Pool, Rule, Step
from ceph_tpu.mon.paxos import NotLeader, Paxos
from ceph_tpu.mon.store import MonStore, MonStoreTxn
from ceph_tpu.msg.messages import (MLog, Message, MMgrMap, MMonCommand,
                                   MMonCommandAck, MMonElection,
                                   MMonGetMap, MMonMap, MMonMgrReport,
                                   MMonPaxos, MMonSubscribe, MOSDBoot,
                                   MOSDFailure, MOSDMapMsg, MPing,
                                   MPingReply)
from ceph_tpu.msg.messenger import Connection, Dispatcher, Messenger
from ceph_tpu.utils import flight
from ceph_tpu.utils.async_util import reap, reap_all
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.perf_counters import PerfCountersCollection


class MonMap:
    """Names -> addrs; rank = index in sorted names (src/mon/MonMap.h)."""

    def __init__(self, mons: dict[str, tuple[str, int]], epoch: int = 1):
        self.epoch = epoch
        self.mons = {name: tuple(addr) for name, addr in mons.items()}

    @property
    def ranks(self) -> list[str]:
        return sorted(self.mons)

    def rank_of(self, name: str) -> int:
        return self.ranks.index(name)

    def addr_of_rank(self, rank: int) -> tuple[str, int]:
        return self.mons[self.ranks[rank]]

    def to_dict(self) -> dict:
        return {"epoch": self.epoch,
                "mons": {n: list(a) for n, a in self.mons.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "MonMap":
        return cls({n: tuple(a) for n, a in d["mons"].items()}, d["epoch"])


class OSDMonitor:
    """The OSDMap service (src/mon/OSDMonitor.cc essentials)."""

    MIN_DOWN_REPORTERS = 2      # mon_osd_min_down_reporters (OSDMonitor.cc:2868)
    DOWN_OUT_INTERVAL = 30.0
    KEEP_EPOCHS = 64            # bounded full-map/inc history window

    def __init__(self, mon: "Monitor"):
        self.mon = mon
        self.osdmap = OSDMap(CrushMap())
        self.pending: Incremental | None = None
        self.down_at: dict[int, float] = {}
        # failed osd -> set of reporter osds (reporter quorum)
        self.failure_reports: dict[int, set[int]] = {}
        # one proposal in flight at a time (PaxosService serializes);
        # the pending epoch is assigned at encode time under this lock,
        # after the previous commit has applied — two racing callers can
        # never build two incrementals with the same epoch (ADVICE r3)
        self._propose_lock = asyncio.Lock()

    # -- state recovery ------------------------------------------------------

    def load(self) -> None:
        store = self.mon.store
        epochs = [int(e) for e in store.keys("osdmap_full")]
        if epochs:
            latest = max(epochs)
            self.osdmap.load_dict(store.get("osdmap_full", str(latest)))
        # seed the down->out clock for osds already down in the loaded map
        # so a later leadership here still marks them out eventually
        now = time.monotonic()
        for osd, state in self.osdmap.osds.items():
            if not state.up and state.in_cluster:
                self.down_at.setdefault(osd, now)

    # -- pending / propose ---------------------------------------------------

    def get_pending(self) -> Incremental:
        if self.pending is None:
            # epoch 0 is a placeholder: the real epoch is stamped in
            # encode_pending, under the propose lock
            self.pending = Incremental(epoch=0)
        return self.pending

    def encode_pending(self) -> bytes:
        inc = self.pending
        self.pending = None
        inc.epoch = self.osdmap.epoch + 1
        return json.dumps({"service": "osdmap",
                           "inc": inc.to_dict()}).encode()

    async def propose_pending(self) -> int | None:
        """Propose the pending incremental; resolves at commit. Proposals
        are serialized: while one is in flight, later mutations pile into
        a fresh pending that is proposed (with a rebased epoch) after the
        first commit applies."""
        async with self._propose_lock:
            if self.pending is None or self.pending.empty():
                self.pending = None
                return None
            value = self.encode_pending()
            fut = self.mon.paxos.propose(value)
            return await asyncio.wait_for(fut, 30)

    def apply_commit(self, inc_dict: dict, txn: MonStoreTxn) -> None:
        inc = Incremental.from_dict(inc_dict)
        if inc.epoch != self.osdmap.epoch + 1:
            dout("mon", 10, f"{self.mon.name}: skip stale inc "
                            f"{inc.epoch} at {self.osdmap.epoch}")
            return
        self.osdmap.apply_incremental(inc)
        for osd in inc.new_down:
            self.down_at[osd] = time.monotonic()
            self.failure_reports.pop(osd, None)
        for osd in inc.new_up:
            self.down_at.pop(osd, None)
            self.failure_reports.pop(osd, None)
        txn.put("osdmap_full", str(self.osdmap.epoch), self.osdmap.to_dict())
        txn.put("osdmap_inc", str(inc.epoch), inc_dict)
        # bounded map history (the reference trims to
        # [first_committed, last]): old epochs can never be needed again —
        # subscribers older than the window get the full map
        floor = self.osdmap.epoch - self.KEEP_EPOCHS
        for prefix in ("osdmap_full", "osdmap_inc"):
            for e in self.mon.store.keys(prefix):
                if int(e) <= floor:
                    txn.erase(prefix, e)
        self.mon.kick_subscribers()

    # -- control-plane verbs -------------------------------------------------

    def _get_erasure_code(self, profile_name: str):
        """Instantiate the plugin from a stored profile — in-monitor
        validation (OSDMonitor.cc:7506)."""
        from ceph_tpu.ec.registry import ErasureCodePluginRegistry
        profile = self.osdmap.ec_profiles.get(profile_name)
        if profile is None:
            raise ValueError(f"erasure-code profile {profile_name!r} "
                             "does not exist")
        plugin = profile.get("plugin", "jerasure")
        return ErasureCodePluginRegistry.instance().factory(
            plugin, dict(profile))

    def cmd_profile_set(self, name: str, profile: dict) -> dict:
        from ceph_tpu.ec.registry import ErasureCodePluginRegistry
        plugin = profile.get("plugin", "jerasure")
        # validate by instantiation before it can enter the map
        ErasureCodePluginRegistry.instance().factory(plugin, dict(profile))
        self.get_pending().new_ec_profiles[name] = dict(profile)
        return {"profile": name}

    def _ensure_root(self, crush: CrushMap) -> None:
        if "default" not in crush._names:
            crush.add_bucket(10, "default")

    def _next_rule_id(self, crush: CrushMap) -> int:
        return max(crush._rules, default=-1) + 1

    def cmd_pool_create(self, name: str, pg_num: int = 32,
                        pool_type: str = "replicated", size: int = 3,
                        erasure_code_profile: str = "",
                        crush_failure_domain: int = 1) -> dict:
        if name in self.osdmap.pool_names:
            # idempotent: commands are at-least-once (client retries after
            # ack timeouts may follow a commit that actually landed), so a
            # re-create of an existing pool reports the existing pool
            # (divergence from the reference's EEXIST, which relies on the
            # CLI user to interpret it)
            pool = self.osdmap.get_pool(name)
            return {"pool": name, "pool_id": pool.id, "size": pool.size,
                    "min_size": pool.min_size, "crush_rule": pool.crush_rule,
                    "existed": True}
        crush = CrushMap.from_dict(self.osdmap.crush.to_dict())
        self._ensure_root(crush)
        rule_id = self._next_rule_id(crush)
        if pool_type == "erasure":
            ec = self._get_erasure_code(erasure_code_profile)
            k = ec.get_data_chunk_count()
            m = ec.get_chunk_count() - k
            size = k + m
            min_size = k + 1
            # EC rule: indep with holes (ErasureCode::create_rule, mode
            # "indep"; OSDMonitor crush_rule_create_erasure :7470)
            crush.make_simple_rule(rule_id, f"{name}_rule", "default",
                                   crush_failure_domain, mode="indep")
            # chunk size through the plugin's own get_chunk_size (the
            # reference derives stripe_width the same way, OSDMonitor
            # prepare_new_pool): bitmatrix techniques need chunks
            # divisible by w, and sub-chunk codes (clay) need chunks
            # divisible by sub_chunk_no — alignment-only math broke
            # clay at k=8,m=3,d=10 (sub_chunk_no=81 does not divide a
            # 128-aligned 4096 chunk)
            chunk = ec.get_chunk_size(k * 4096)
            stripe_width = k * chunk
        else:
            min_size = max(1, size - 1)
            crush.make_simple_rule(rule_id, f"{name}_rule", "default",
                                   crush_failure_domain, mode="firstn")
            stripe_width = 0
        pid = max(self.osdmap.pools, default=0) + 1
        pending = self.get_pending()
        for other in pending.new_pools.values():
            if other.name == name:
                raise ValueError(f"pool {name!r} pending")
            pid = max(pid, other.id + 1)
        pending.new_pools[pid] = Pool(
            id=pid, name=name, type=pool_type, size=size, min_size=min_size,
            pg_num=pg_num, crush_rule=rule_id,
            ec_profile=erasure_code_profile, stripe_width=stripe_width)
        pending.new_crush = crush.to_dict()
        return {"pool": name, "pool_id": pid, "size": size,
                "min_size": min_size, "crush_rule": rule_id}

    def cmd_pool_snap(self, pool_name: str, action: str,
                      snap_name: str | None = None,
                      snapid: int | None = None) -> dict:
        """Pool + self-managed snapshot id allocation/removal
        (OSDMonitor prepare_pool_op SNAP_CREATE/SNAP_DELETE and
        IoCtxImpl::selfmanaged_snap_create's mon round-trip): snap ids
        are monotonically allocated from the pool's snap_seq; removals
        land in removed_snaps for the OSDs' snaptrim to consume."""
        import dataclasses as _dc
        pid = self.osdmap.pool_names.get(pool_name)
        if pid is None:
            raise ValueError(f"pool {pool_name!r} does not exist")
        # snapshots work on both pool types: EC pools clone per-shard
        # chunk blobs via clone sub-ops (see osd/ec_backend.py)
        pending = self.get_pending()
        base = pending.new_pools.get(pid, self.osdmap.pools[pid])
        p = _dc.replace(base, pool_snaps=dict(base.pool_snaps),
                        removed_snaps=list(base.removed_snaps))
        if action == "mksnap":
            if snap_name in p.pool_snaps.values():
                raise ValueError(f"snap {snap_name!r} exists")
            sid = p.snap_seq + 1
            p.snap_seq = sid
            p.pool_snaps[str(sid)] = snap_name
        elif action == "rmsnap":
            sid = next((int(k) for k, v in p.pool_snaps.items()
                        if v == snap_name), None)
            if sid is None:
                raise ValueError(f"snap {snap_name!r} does not exist")
            del p.pool_snaps[str(sid)]
            p.removed_snaps.append(sid)
        elif action == "selfmanaged_create":
            sid = p.snap_seq + 1
            p.snap_seq = sid
        elif action == "selfmanaged_rm":
            sid = int(snapid)
            if sid not in p.removed_snaps:
                p.removed_snaps.append(sid)
            p.snap_seq = max(p.snap_seq, sid)
        else:
            raise ValueError(f"unknown snap action {action!r}")
        pending.new_pools[pid] = p
        return {"snapid": sid, "pool": pool_name}

    def handle_boot(self, payload: dict) -> bool:
        """MOSDBoot: add under crush_location, mark up. True if changed."""
        osd = payload["osd"]
        addr = payload["addr"]
        loc = payload.get("crush_location", {})
        weight = payload.get("weight", 1.0)
        state = self.osdmap.osds.get(osd)
        pending = self.get_pending()
        in_crush = any(osd in b.items
                       for b in self.osdmap.crush._buckets.values())
        if state is None or not in_crush or state.addr != addr:
            crush = CrushMap.from_dict(self.osdmap.crush.to_dict())
            self._ensure_root(crush)
            host = loc.get("host", f"host{osd}")
            if host not in crush._names:
                crush.add_bucket(1, host)
                crush.add_item("default", crush._names[host], 0.0)
            bid = crush._names[host]
            bucket = crush._buckets[bid]
            if osd not in bucket.items:
                crush.add_item(bid, osd, weight, name=f"osd.{osd}")
            else:
                crush.reweight_item(bid, osd, weight)
            # recompute (never increment) the host's weight in the root so
            # a re-boot can't inflate it (VERDICT r3 weak #9)
            root = crush._buckets[crush._names["default"]]
            root.weights[root.items.index(bid)] = bucket.weight()
            pending.new_crush = crush.to_dict()
        if state is None:
            pending.new_osds[osd] = addr
        if state is None or not state.up or state.addr != addr:
            pending.new_up[osd] = addr
            if state is not None and not state.in_cluster:
                pending.new_in.append(osd)
            return True
        return not pending.empty()

    def handle_failure(self, payload: dict) -> bool:
        failed = payload["failed"]
        reporter = payload.get("from", -1)
        state = self.osdmap.osds.get(failed)
        if state is None or not state.up:
            return False
        reporters = self.failure_reports.setdefault(failed, set())
        reporters.add(reporter)
        if len(reporters) >= self.MIN_DOWN_REPORTERS:
            pending = self.get_pending()
            if failed not in pending.new_down:
                pending.new_down.append(failed)
                self.mon.clog(
                    "WRN", f"mon.{self.mon.name}",
                    f"osd.{failed} marked down "
                    f"({len(reporters)} reporters: {sorted(reporters)})")
                flight.record("osd_markdown", f"osd.{failed}",
                              reporters=sorted(reporters),
                              mon=self.mon.name)
            return True
        return False

    def tick(self) -> bool:
        """Leader periodic work: down -> out after the interval."""
        changed = False
        now = time.monotonic()
        for osd, when in list(self.down_at.items()):
            state = self.osdmap.osds.get(osd)
            if state is None or state.up:
                continue
            if state.in_cluster and now - when > self.DOWN_OUT_INTERVAL:
                pending = self.get_pending()
                if osd not in pending.new_out:
                    pending.new_out.append(osd)
                    changed = True
        return changed


class MgrMonitor:
    """MgrMap service (src/mon/MgrMonitor.cc essentials): the active
    mgr's identity + report address, replicated through paxos so every
    quorum member — and any daemon asking `mgr dump` — agrees on where
    reports go. Beacons keep it fresh; the leader drops an active mgr
    whose beacons stop, which raises MGR_DOWN cluster-wide."""

    BEACON_GRACE = 8.0          # mon_mgr_beacon_grace analog

    def __init__(self, mon: "Monitor"):
        self.mon = mon
        self.map: dict = {"epoch": 0, "active_name": None,
                          "active_addr": None}
        self.last_beacon = 0.0      # monotonic; leader-local liveness

    def load(self) -> None:
        m = self.mon.store.get("mgrmap", "latest")
        if m:
            self.map = m

    def beacon(self, name: str, addr) -> dict | None:
        """Record a beacon; returns a new map to propose when the
        active identity changed (first mgr, restart on a new port).
        While an active mgr holds the slot, other mgrs' beacons are
        STANDBY (ignored) — they take over only after the active is
        dropped for beacon loss, like the reference's standby pool."""
        addr = list(addr) if addr else None
        active = self.map.get("active_name")
        if active is not None and active != name:
            return None
        self.last_beacon = time.monotonic()
        if active == name and self.map.get("active_addr") == addr:
            return None
        return {"epoch": self.map.get("epoch", 0) + 1,
                "active_name": name, "active_addr": addr}

    def tick(self) -> dict | None:
        """Leader periodic work: drop an active mgr whose beacons
        stopped (returns the map to propose)."""
        if not self.map.get("active_name"):
            return None
        if not self.last_beacon:
            # fresh leadership: grant a full grace window before
            # declaring the recorded active mgr dead
            self.last_beacon = time.monotonic()
            return None
        if time.monotonic() - self.last_beacon > self.BEACON_GRACE:
            return {"epoch": self.map.get("epoch", 0) + 1,
                    "active_name": None, "active_addr": None}
        return None

    def apply_commit(self, m: dict, txn: MonStoreTxn) -> None:
        if m.get("epoch", 0) <= self.map.get("epoch", 0):
            return
        self.map = m
        txn.put("mgrmap", "latest", m)
        self.mon.push_mgrmap()


class Monitor(Dispatcher):
    """One monitor daemon: messenger + paxos + services + client plane."""

    def __init__(self, name: str, monmap: MonMap,
                 store_path: str | None = None,
                 auth_key: bytes | None = None):
        self.name = name
        self.monmap = monmap
        self.rank = monmap.rank_of(name)
        self.store = MonStore(store_path)
        self.messenger = Messenger(f"mon.{name}", auth_key=auth_key)
        self.messenger.add_dispatcher(self)
        peers = {monmap.rank_of(n): addr for n, addr in monmap.mons.items()
                 if n != name}
        self.paxos = Paxos(self.messenger, self.rank, peers, self.store,
                           on_commit=self._on_paxos_commit,
                           on_role_change=self._on_role_change)
        self.paxos.on_sync = self._on_store_sync
        self.osdmon = OSDMonitor(self)
        self.mgrmon = MgrMonitor(self)
        # mgr-fed health digest (MMonMgrReport): checks + progress +
        # per-daemon report ages, merged into the health engine while
        # fresh
        self.mgr_digest: dict | None = None
        self._mgr_digest_mono = 0.0
        # health mutes: code -> {"expires": wall|None, "stamp": wall};
        # persisted through the mon store so a restart keeps them
        self.health_mutes: dict[str, dict] = {}
        self._prev_checks: dict[str, str] = {}   # code -> severity
        # osdmap subscribers: conn -> next epoch wanted
        self.subs: dict[Connection, int] = {}
        # mgrmap subscribers: conn -> next epoch wanted (daemons learn
        # the active mgr by push, never by polling commands)
        self.mgr_subs: dict[Connection, int] = {}
        self._tick_task: asyncio.Task | None = None
        # in-flight background proposals (_spawn_proposal): tracked so
        # stop() can reap them — a detached proposal task left pending
        # at loop close is the monitor's own _dispatch_loop leak
        self._proposal_tasks: set[asyncio.Task] = set()
        self._applied = 0      # last paxos version applied to services
        # cluster log (LogMonitor-lite, src/mon/LogMonitor.cc): WARN+
        # events from daemons (MLog) and this mon's own map-change
        # events, in a bounded ring queryable via `log last`
        self.cluster_log: collections.deque[dict] = \
            collections.deque(maxlen=1000)
        # per-daemon perf counters: quorum/paxos activity, shipped to
        # the mgr like every other daemon's
        coll = PerfCountersCollection.instance()
        coll.remove(f"mon.{name}")      # a restarted mon re-registers
        self.perf = coll.create(f"mon.{name}")
        self.perf.add("paxos_commit", description="paxos values committed")
        self.perf.add("election", description="elections called")
        self.perf.add("command", description="mon commands served")
        self.perf.add("cluster_log_lines",
                      description="cluster-log lines recorded")
        self.paxos.perf = self.perf
        # report session to the active mgr (resolved from the replicated
        # mgrmap — every mon, leader or peon, knows it). Lazy import:
        # ceph_tpu.mgr pulls in mon_client, which would cycle here.
        from ceph_tpu.mgr.mgr_client import MgrClient
        self.mgr_client = MgrClient(
            self.messenger, f"mon.{name}", "mon",
            resolve=lambda: self.mgrmon.map.get("active_addr"),
            status_cb=lambda: {
                "rank": self.rank, "leader": self.paxos.is_leader(),
                "quorum": sorted(self.paxos.quorum),
                "osdmap_epoch": self.osdmon.osdmap.epoch,
                "applied_version": self._applied},
            perf_name=f"mon.{name}",
            extra_loggers=("sanitizer",))

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        addr = await self.messenger.bind(*self.monmap.mons[self.name])
        self.osdmon.load()
        self.mgrmon.load()
        self.health_mutes = self.store.get("health", "mutes", {}) or {}
        self._applied = self.store.get("mon", "applied_version", 0)
        self.paxos.recover_from_store()
        self._replay_missing()
        await self.paxos.start()
        self.mgr_client.start()
        self._tick_task = asyncio.get_running_loop().create_task(self._tick())
        dout("mon", 1, f"mon.{self.name} up at {addr} rank {self.rank}")
        return addr

    async def stop(self) -> None:
        await reap(self._tick_task)
        await reap_all(list(self._proposal_tasks))
        self._proposal_tasks.clear()
        await self.mgr_client.stop()
        await self.paxos.stop()
        await self.messenger.shutdown()

    def _replay_missing(self) -> None:
        """Apply any paxos values committed but not yet service-applied
        (crash between paxos txn and service txn)."""
        for v in range(self._applied + 1, self.paxos.last_committed + 1):
            raw = self.store.get("paxos_values", str(v))
            if raw is not None:
                self._apply_value(v, raw.encode("latin1"))

    async def _tick(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            try:
                if self.paxos.is_leader() and self.paxos.is_active():
                    if self.osdmon.tick():
                        await self.osdmon.propose_pending()
                    m = self.mgrmon.tick()
                    if m is not None:
                        await self._propose_mgrmap(m)
                    self._log_health_transitions()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # a proposal timeout/leadership loss must not kill the
                # periodic task (VERDICT r3 weak #4) — the work retries
                # on the next tick
                dout("mon", 5, f"mon.{self.name}: tick proposal failed: "
                               f"{type(e).__name__} {e}")

    async def _propose_mgrmap(self, m: dict) -> None:
        value = json.dumps({"service": "mgrmap", "map": m}).encode()
        await asyncio.wait_for(self.paxos.propose(value), 30)

    async def _propose_health_mutes(self, mutes: dict) -> None:
        """Mute set/clear rides paxos so every quorum member answers
        `health` identically and mutes survive leadership changes."""
        value = json.dumps({"service": "health",
                            "mutes": mutes}).encode()
        await asyncio.wait_for(self.paxos.propose(value), 30)

    # -- paxos plumbing ------------------------------------------------------

    def _on_paxos_commit(self, version: int, value: bytes) -> None:
        self._apply_value(version, value)

    def _apply_value(self, version: int, value: bytes) -> None:
        txn = MonStoreTxn()
        try:
            decoded = json.loads(value)
            if decoded.get("service") == "osdmap":
                self.osdmon.apply_commit(decoded["inc"], txn)
            elif decoded.get("service") == "mgrmap":
                self.mgrmon.apply_commit(decoded["map"], txn)
            elif decoded.get("service") == "health":
                self.health_mutes = decoded.get("mutes", {}) or {}
                txn.put("health", "mutes", self.health_mutes)
        except Exception as e:
            dout("mon", 0, f"mon.{self.name}: apply v{version} failed: "
                           f"{type(e).__name__} {e}")
        self._applied = version
        txn.put("mon", "applied_version", version)
        self.store.apply_transaction(txn)

    def _on_store_sync(self) -> None:
        """Paxos replaced our whole store (we were behind the leader's
        trim horizon): reload service state from it."""
        self.osdmon.osdmap = OSDMap(CrushMap())
        self.osdmon.down_at.clear()
        self.osdmon.failure_reports.clear()
        self.osdmon.load()
        self.mgrmon.load()
        self.health_mutes = self.store.get("health", "mutes", {}) or {}
        self._applied = self.store.get("mon", "applied_version", 0)
        dout("mon", 1, f"mon.{self.name}: full sync -> osdmap epoch "
                       f"{self.osdmon.osdmap.epoch}")

    def _on_role_change(self) -> None:
        if self.paxos.is_leader():
            # beacons landed on the previous leader while we were a
            # peon: re-arm the grace window instead of dropping a live
            # active mgr on our stale clock
            self.mgrmon.last_beacon = 0.0
        if self.paxos.is_leader() and self.osdmon.osdmap.epoch == 0:
            # first leader seeds the initial map (epoch 1: empty crush root)
            crush = CrushMap()
            crush.add_bucket(10, "default")
            inc = self.osdmon.get_pending()
            inc.new_crush = crush.to_dict()
            self._spawn_proposal()

    def _spawn_proposal(self) -> None:
        """Background propose_pending with failures logged, never
        raised into the event loop; the handle is tracked so stop()
        reaps any proposal still in flight."""
        async def run():
            try:
                await self.osdmon.propose_pending()
            except Exception as e:
                dout("mon", 5, f"mon.{self.name}: background proposal "
                               f"failed: {type(e).__name__} {e}")
        task = asyncio.get_running_loop().create_task(run())
        self._proposal_tasks.add(task)
        task.add_done_callback(self._proposal_tasks.discard)

    # -- dispatch ------------------------------------------------------------

    async def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, MMonElection):
            await self.paxos.handle_election(conn, msg)
        elif isinstance(msg, MMonPaxos):
            await self.paxos.handle_paxos(conn, msg)
        elif isinstance(msg, MPing):
            conn.send_message(MPingReply(dict(msg.payload)))
        elif isinstance(msg, MMonGetMap):
            self._handle_get_map(conn, msg)
        elif isinstance(msg, MMonSubscribe):
            self._handle_subscribe(conn, msg)
        elif isinstance(msg, MMonCommand):
            await self._handle_command(conn, msg)
        elif isinstance(msg, MOSDBoot):
            await self._osd_plane(msg, self.osdmon.handle_boot)
        elif isinstance(msg, MOSDFailure):
            await self._osd_plane(msg, self.osdmon.handle_failure)
        elif isinstance(msg, MLog):
            p = msg.payload
            self.clog(p.get("level", "WRN"), p.get("who", "?"),
                      p.get("message", ""), stamp=p.get("stamp"))
        elif isinstance(msg, MMonMgrReport):
            # only the ACTIVE mgr's digest counts: a just-demoted mgr
            # whose fire-and-forget sends are still in flight must not
            # clobber its successor's fresher digest
            sender = msg.payload.get("from")
            if sender is not None and \
                    sender != self.mgrmon.map.get("active_name"):
                return True
            self.mgr_digest = msg.payload
            self._mgr_digest_mono = time.monotonic()
            # the health engine runs wherever `health` is asked: forward
            # so the leader (and through it, transitions -> clog) always
            # has the freshest digest even when the mgr's session landed
            # on a peon
            if not self.paxos.is_leader():
                leader = self.paxos.leader
                if leader is not None and leader != self.rank:
                    await self.paxos._send(
                        leader, MMonMgrReport(dict(msg.payload)))
        else:
            return False
        return True

    # -- cluster log ---------------------------------------------------------

    def clog(self, level: str, who: str, message: str,
             stamp: float | None = None) -> None:
        """Append one cluster-log line (whichever mon a daemon's session
        lands on records it; `log last` reads that mon's ring)."""
        self.cluster_log.append(
            {"stamp": stamp if stamp is not None else time.time(),
             "level": level, "who": who, "message": message})
        self.perf.inc("cluster_log_lines")
        dout("mon", 2, f"mon.{self.name} clog [{level}] {who}: {message}")

    def ms_handle_reset(self, conn: Connection) -> None:
        self.subs.pop(conn, None)
        self.mgr_subs.pop(conn, None)

    # -- client plane --------------------------------------------------------

    def _handle_get_map(self, conn: Connection, msg: MMonGetMap) -> None:
        what = msg.payload.get("what", "monmap")
        if what == "monmap":
            conn.send_message(MMonMap({"monmap": self.monmap.to_dict()}))
        else:
            osdmap = self.osdmon.osdmap
            conn.send_message(MOSDMapMsg(
                {"full": osdmap.to_dict() if osdmap.epoch else None,
                 "incrementals": []}))

    def _handle_subscribe(self, conn: Connection, msg: MMonSubscribe) -> None:
        want = msg.payload.get("what", {})
        if "osdmap" in want:
            start = int(want["osdmap"])
            self.subs[conn] = start
            self._push_maps(conn)
        if "mgrmap" in want:
            self.mgr_subs[conn] = int(want["mgrmap"])
            self._push_mgrmap(conn)

    def kick_subscribers(self) -> None:
        for conn in list(self.subs):
            self._push_maps(conn)

    def push_mgrmap(self) -> None:
        for conn in list(self.mgr_subs):
            self._push_mgrmap(conn)

    def _push_mgrmap(self, conn: Connection) -> None:
        epoch = self.mgrmon.map.get("epoch", 0)
        if epoch < self.mgr_subs.get(conn, 0):
            return
        try:
            conn.send_message(MMgrMap({"mgrmap": dict(self.mgrmon.map)}))
        except Exception:
            self.mgr_subs.pop(conn, None)
            return
        self.mgr_subs[conn] = epoch + 1

    def _push_maps(self, conn: Connection) -> None:
        start = self.subs.get(conn, 0)
        cur = self.osdmon.osdmap.epoch
        if start > cur:
            return
        incs = []
        for e in range(max(start, 1), cur + 1):
            inc = self.store.get("osdmap_inc", str(e))
            if inc is None:
                incs = None
                break
            incs.append(inc)
        if incs is not None and incs and start >= 1:
            conn.send_message(MOSDMapMsg({"full": None,
                                          "incrementals": incs}))
        else:
            conn.send_message(MOSDMapMsg(
                {"full": self.osdmon.osdmap.to_dict(), "incrementals": []}))
        self.subs[conn] = cur + 1

    async def _osd_plane(self, msg: Message, handler) -> None:
        if not self.paxos.is_leader():
            leader = self.paxos.leader
            if leader is not None and leader != self.rank:
                await self.paxos._send(leader, type(msg)(dict(msg.payload),
                                                         msg.data))
            return
        try:
            if handler(msg.payload):
                await self.osdmon.propose_pending()
        except Exception as e:
            # osd-plane messages are fire-and-forget: a failed proposal
            # (leadership churn) must not look like a transport fault to
            # the messenger; the osd re-sends on the next map/boot retry
            dout("mon", 5, f"mon.{self.name}: osd-plane proposal failed: "
                           f"{type(e).__name__} {e}")

    async def _handle_command(self, conn: Connection, msg: MMonCommand) -> None:
        tid = msg.payload.get("tid", 0)
        cmd = msg.payload.get("cmd", {})
        prefix = cmd.get("prefix", "")
        self.perf.inc("command")
        # `health`/`health detail`/`status` are leader-routed (NOT
        # read-only): the mgr digest and mute state live with the
        # leader, and a peon answering from local state would hide
        # SLOW_OPS, a mute, or in-flight progress
        read_only = prefix in ("mon stat", "osd dump", "osd tree",
                               "osd erasure-code-profile ls",
                               "osd erasure-code-profile get",
                               "mgr dump", "log last")
        if not read_only and not (self.paxos.is_leader()
                                  and self.paxos.is_active()):
            conn.send_message(self._retry_ack(tid, "not leader"))
            return
        try:
            out = await self._run_command(prefix, cmd)
            conn.send_message(MMonCommandAck({"tid": tid, "rc": 0,
                                              "out": out}))
        except (NotLeader, asyncio.TimeoutError) as e:
            # leadership churned mid-command: tell the client to retry
            # (against the new leader if we know it)
            conn.send_message(self._retry_ack(
                tid, f"retry: {type(e).__name__}: {e}"))
        except Exception as e:
            conn.send_message(MMonCommandAck(
                {"tid": tid, "rc": -22,
                 "error": f"{type(e).__name__}: {e}"}))

    def _retry_ack(self, tid: int, error: str) -> MMonCommandAck:
        """rc=-11 'bounce to the leader' ack with the hint we have."""
        leader = self.paxos.leader
        return MMonCommandAck(
            {"tid": tid, "rc": -11, "error": error,
             "leader": (self.monmap.ranks[leader]
                        if leader is not None else None),
             "leader_addr": (list(self.monmap.addr_of_rank(leader))
                             if leader is not None else None)})

    # -- health engine (health_check_map_t, src/mon/health_check.h) ----------

    DIGEST_STALE = 15.0         # ignore a mgr digest older than this

    def _raw_health_checks(self) -> dict[str, dict]:
        """The full check map: local map-derived checks + mgr-fed checks
        (SLOW_OPS, PG_DEGRADED/UNDERSIZED, OSD_NEARFULL/FULL) while the
        digest is fresh. Mutes are applied by the caller."""
        om = self.osdmon
        checks: dict[str, dict] = {}
        down = [i for i, st in om.osdmap.osds.items() if not st.up]
        if down:
            checks["OSD_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(down)} osds down",
                "detail": [f"osd.{i} is down" for i in sorted(down)]}
        out = [i for i, st in om.osdmap.osds.items()
               if not getattr(st, "in_cluster", True)]
        if out:
            checks["OSD_OUT"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(out)} osds out",
                "detail": [f"osd.{i} is out" for i in sorted(out)]}
        quorum = sorted(self.paxos.quorum)
        if len(quorum) <= len(self.monmap.mons) // 2:
            checks["MON_QUORUM"] = {
                "severity": "HEALTH_ERR",
                "summary": f"quorum {quorum} of "
                           f"{len(self.monmap.mons)} monitors"}
        # global up-count vs per-pool min_size: a coarse availability
        # check (placement-level starvation is a pg-state concern the
        # mon does not track here)
        up_osds = sum(1 for st in om.osdmap.osds.values() if st.up)
        for pool in om.osdmap.pools.values():
            if up_osds < pool.min_size:
                checks.setdefault("POOL_UNAVAILABLE", {
                    "severity": "HEALTH_ERR",
                    "summary": "pools below min_size",
                    "detail": []})["detail"].append(
                    f"pool {pool.name!r} needs {pool.min_size} "
                    f"up osds, have {up_osds}")
        # MGR_DOWN: a mgr was active (mgrmap epoch moved) but none is
        # now — daemon reports and labeled metrics have stopped. A
        # cluster that never ran a mgr stays clean.
        if self.mgrmon.map.get("epoch", 0) > 0 \
                and not self.mgrmon.map.get("active_name"):
            checks["MGR_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": "no active mgr (daemon reports stopped)"}
        if self.mgr_digest is not None and self._mgr_digest_mono and \
                time.monotonic() - self._mgr_digest_mono \
                < self.DIGEST_STALE:
            for code, chk in (self.mgr_digest.get("checks")
                              or {}).items():
                checks.setdefault(str(code), dict(chk))
        return checks

    def _active_mutes(self) -> dict[str, dict]:
        """Prune expired mutes (TTL), persisting the change."""
        now = time.time()
        expired = [c for c, m in self.health_mutes.items()
                   if m.get("expires") and now >= m["expires"]]
        for code in expired:
            del self.health_mutes[code]
            self.clog("WRN", f"mon.{self.name}",
                      f"health mute {code} expired")
        if expired:
            self.store.put_one("health", "mutes", self.health_mutes)
        return self.health_mutes

    def _health_checks(self, detail: bool = False) -> dict:
        """HEALTH_OK/WARN/ERR from the unmuted check map; muted checks
        are excluded from the summary status but reported under
        "muted" (fully, in `health detail`)."""
        checks = self._raw_health_checks()
        mutes = self._active_mutes()
        visible = {c: chk for c, chk in checks.items() if c not in mutes}
        if any(c["severity"] == "HEALTH_ERR" for c in visible.values()):
            status = "HEALTH_ERR"
        elif visible:
            status = "HEALTH_WARN"
        else:
            status = "HEALTH_OK"
        muted = {}
        for code, mute in mutes.items():
            entry = {"expires_in_s":
                     (round(mute["expires"] - time.time(), 1)
                      if mute.get("expires") else None)}
            if detail and code in checks:
                entry.update(checks[code])
            muted[code] = entry
        return {"status": status, "checks": visible, "muted": muted}

    def _log_health_transitions(self) -> None:
        """WARN+ check transitions land in the cluster log (the
        reference LogMonitor's `Health check failed:` lines)."""
        checks = self._raw_health_checks()
        for code, chk in checks.items():
            sev = chk.get("severity", "HEALTH_WARN")
            if self._prev_checks.get(code) != sev:
                self.clog("ERR" if sev == "HEALTH_ERR" else "WRN",
                          f"mon.{self.name}",
                          f"Health check failed: "
                          f"{chk.get('summary')} ({code})")
                flight.record("health_fail", code, severity=sev,
                              summary=chk.get("summary", ""))
                # WARN+ transition: freeze the ring — the run-up to a
                # SLOW_OPS / PG_DEGRADED flip is exactly what an
                # operator wants post-hoc
                flight.snapshot(f"health:{code}")
        for code in self._prev_checks:
            if code not in checks:
                self.clog("INF", f"mon.{self.name}",
                          f"Health check cleared: {code}")
                flight.record("health_clear", code)
        self._prev_checks = {c: chk.get("severity", "HEALTH_WARN")
                             for c, chk in checks.items()}

    async def _run_command(self, prefix: str, cmd: dict) -> dict:
        om = self.osdmon
        if prefix == "health":
            return self._health_checks()
        if prefix == "health detail":
            return self._health_checks(detail=True)
        if prefix == "health mute":
            code = cmd["code"]
            ttl = cmd.get("ttl")
            mutes = dict(self.health_mutes)
            mutes[code] = {
                "stamp": time.time(),
                "expires": time.time() + float(ttl) if ttl else None}
            await self._propose_health_mutes(mutes)
            self.clog("WRN", f"mon.{self.name}",
                      f"health check {code} muted"
                      + (f" for {float(ttl):.0f}s" if ttl else ""))
            return {"muted": code, "ttl": ttl}
        if prefix == "health unmute":
            existed = cmd["code"] in self.health_mutes
            if existed:
                mutes = dict(self.health_mutes)
                del mutes[cmd["code"]]
                await self._propose_health_mutes(mutes)
            return {"unmuted": cmd["code"], "existed": existed}
        if prefix == "mgr dump":
            out = dict(self.mgrmon.map)
            digest = self.mgr_digest or {}
            out["daemons"] = digest.get("daemons", {})
            out["digest_age_s"] = (
                round(time.monotonic() - self._mgr_digest_mono, 2)
                if self._mgr_digest_mono else None)
            return out
        if prefix == "mgr beacon":
            new_map = self.mgrmon.beacon(cmd.get("name", "?"),
                                         cmd.get("addr"))
            if new_map is not None:
                await self._propose_mgrmap(new_map)
                self.clog("WRN", f"mon.{self.name}",
                          f"mgr.{cmd.get('name', '?')} is now active")
            # the reply names the active mgr: a standby learns its role
            # from this and keeps its digest to itself
            return {"epoch": self.mgrmon.map.get("epoch", 0),
                    "active_name": self.mgrmon.map.get("active_name")}
        if prefix == "status":
            # `ceph -s` analog: health + mon + mgr + osd + pool summary
            up = sum(1 for st in om.osdmap.osds.values() if st.up)
            digest = self.mgr_digest or {}
            return {
                "health": self._health_checks(),
                "monmap": {"mons": sorted(self.monmap.mons),
                           "quorum": sorted(self.paxos.quorum),
                           "leader": self.paxos.leader},
                "mgrmap": {"active": self.mgrmon.map.get("active_name"),
                           "epoch": self.mgrmon.map.get("epoch", 0)},
                "osdmap": {"epoch": om.osdmap.epoch,
                           "num_osds": len(om.osdmap.osds),
                           "num_up_osds": up},
                "pools": {p.name: {"type": p.type, "size": p.size,
                                   "pg_num": p.pg_num}
                          for p in om.osdmap.pools.values()},
                "progress": digest.get("progress", []),
            }
        if prefix == "log last":
            n = int(cmd.get("num", 20))
            lines = list(self.cluster_log)
            level = cmd.get("level")
            if level:
                lines = [e for e in lines if e["level"] == level]
            return {"lines": lines[-n:] if n > 0 else []}
        if prefix == "mon stat":
            return {"name": self.name, "rank": self.rank,
                    "leader": self.paxos.leader,
                    "quorum": sorted(self.paxos.quorum),
                    "election_epoch": self.paxos.epoch}
        if prefix == "osd dump":
            return om.osdmap.to_dict()
        if prefix == "osd tree":
            crush = om.osdmap.crush
            return {"buckets": {b.name: {"type": b.type,
                                         "items": list(b.items),
                                         "weights": list(b.weights)}
                                for b in crush._buckets.values()}}
        if prefix == "osd erasure-code-profile ls":
            return {"profiles": sorted(om.osdmap.ec_profiles)}
        if prefix == "osd erasure-code-profile get":
            name = cmd["name"]
            return {"profile": om.osdmap.ec_profiles[name]}
        if prefix == "osd erasure-code-profile set":
            out = om.cmd_profile_set(cmd["name"], cmd.get("profile", {}))
            await om.propose_pending()
            return out
        if prefix == "osd pool create":
            out = om.cmd_pool_create(
                cmd["pool"], pg_num=int(cmd.get("pg_num", 32)),
                pool_type=cmd.get("pool_type", "replicated"),
                size=int(cmd.get("size", 3)),
                erasure_code_profile=cmd.get("erasure_code_profile", ""),
                crush_failure_domain=int(cmd.get("crush_failure_domain", 1)))
            await om.propose_pending()
            return out
        if prefix in ("osd pool mksnap", "osd pool rmsnap",
                      "osd pool selfmanaged snap create",
                      "osd pool selfmanaged snap rm"):
            if prefix.endswith("mksnap"):
                out = om.cmd_pool_snap(cmd["pool"], "mksnap",
                                       snap_name=cmd["snap"])
            elif prefix.endswith("rmsnap"):
                out = om.cmd_pool_snap(cmd["pool"], "rmsnap",
                                       snap_name=cmd["snap"])
            elif prefix.endswith("create"):
                out = om.cmd_pool_snap(cmd["pool"], "selfmanaged_create")
            else:
                out = om.cmd_pool_snap(cmd["pool"], "selfmanaged_rm",
                                       snapid=int(cmd["snapid"]))
            await om.propose_pending()
            # the epoch the snap committed in (>= is enough: any map at
            # this epoch carries the mutated pool record) — clients wait
            # on THIS, not on "my epoch + 1", which a concurrent
            # unrelated proposal could satisfy early
            out["epoch"] = om.osdmap.epoch
            return out
        if prefix == "osd pg-temp":
            # balancer/upmap plane (OSDMonitor prepare_command
            # "osd pg-temp"): override one PG's acting set; [] erases
            from ceph_tpu.crush.osdmap import PG as PGId
            pool_id, ps = cmd["pgid"]
            osds = [int(o) for o in cmd.get("osds", [])]
            pending = om.get_pending()
            pending.new_pg_temp[PGId(int(pool_id), int(ps))] = osds
            await om.propose_pending()
            return {"pgid": [pool_id, ps], "osds": osds,
                    "epoch": om.osdmap.epoch}
        if prefix in ("osd out", "osd in", "osd down"):
            ids = [int(i) for i in cmd.get("ids", [])]
            unknown = [i for i in ids if i not in om.osdmap.osds]
            if unknown:
                # an unknown id must never enter paxos: the committed
                # incremental would KeyError on every map applier,
                # permanently wedging the map plane
                raise ValueError(f"osd ids {unknown} do not exist")
            pending = om.get_pending()
            for osd in ids:
                {"osd out": pending.new_out, "osd down": pending.new_down,
                 "osd in": pending.new_in}[prefix].append(osd)
            await om.propose_pending()
            return {"ids": ids}
        raise ValueError(f"unknown command {prefix!r}")
