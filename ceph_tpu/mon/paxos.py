"""Paxos + elections over the messenger (src/mon/Paxos.cc, Elector.cc).

The reference's design, kept faithfully:

  * rank-based elections (lowest live rank wins a majority vote;
    epoch odd = electing, even = stable — ElectionLogic simplified to
    rank priority, without connectivity scoring);
  * one Paxos instance commits a totally-ordered sequence of opaque
    values ("versions"); services batch their state changes into these
    values (PaxosService);
  * leader phases after victory: collect (Paxos.cc:154 — gather promises
    and any uncommitted value, learn newer commits), then active;
  * proposals: begin (:613) -> every quorum peon accepts (:772) ->
    commit_start (:847) -> commit broadcast, then lease extension (:974)
    so peons can serve reads; a peon whose lease expires calls for a new
    election (leader failure detection);
  * proposal numbers are rank-salted (pn = ceil * 100 + rank) so
    competing leaders never collide.

Values are opaque bytes in the message data segment; the Monitor layer
feeds service transactions in and applies them on commit in version
order on every quorum member.
"""
from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable

from ceph_tpu.msg.messages import MMonElection, MMonPaxos
from ceph_tpu.msg.messenger import Connection, Messenger, Policy
from ceph_tpu.utils.async_util import reap_all
from ceph_tpu.utils.dout import dout


class NotLeader(Exception):
    """A proposal was made by (or survived into) a non-leader."""


class Paxos:
    ELECTION_TIMEOUT = 0.35     # victory claim after silence from betters
    LEASE_INTERVAL = 0.8        # leader re-extends this often
    LEASE_TIMEOUT = 3.0         # peon calls election when lease this stale
    ACCEPT_TIMEOUT = 2.0        # begin->accept stragglers force election

    def __init__(self, messenger: Messenger, rank: int,
                 peer_addrs: dict[int, tuple[str, int]], store,
                 on_commit: Callable[[int, bytes], None],
                 on_role_change: Callable[[], None] | None = None):
        self.messenger = messenger
        self.rank = rank
        self.peers = dict(peer_addrs)          # rank -> addr, excluding self
        self.store = store
        self.on_commit = on_commit             # (version, value) in order
        self.on_role_change = on_role_change or (lambda: None)
        self.on_sync: Callable[[], None] | None = None  # after sync_full
        self.perf = None           # hosting mon's PerfCounters, if any

        # durable state
        self.last_pn = store.get("paxos", "last_pn", 0)
        self.accepted_pn = store.get("paxos", "accepted_pn", 0)
        self.last_committed = store.get("paxos", "last_committed", 0)
        self.uncommitted: tuple[int, int, bytes] | None = None  # pn, v, value

        # volatile
        self.epoch = store.get("paxos", "election_epoch", 0)
        self.role = "probing"                  # probing|electing|leader|peon
        self.leader: int | None = None
        self.quorum: set[int] = {self.rank}
        self._election_acks: set[int] = set()
        self._collect_acks: set[int] = set()
        self._accept_acks: set[int] = set()
        self._pending_value: bytes | None = None
        self._proposal_queue: list[tuple[bytes, asyncio.Future]] = []
        self._inflight: asyncio.Future | None = None
        self._lease_expiry = 0.0
        self._active = False
        self._tasks: set[asyncio.Task] = set()
        self._started = False

    # ------------------------------------------------------------------ util

    def _spawn(self, coro) -> None:
        t = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _send(self, rank: int, msg) -> None:
        try:
            conn = await self.messenger.connect(self.peers[rank],
                                                Policy.lossless_peer())
            conn.send_message(msg)
        except Exception as e:
            dout("paxos", 10, f"mon.{self.rank}: send to mon.{rank} "
                              f"failed: {e}")

    def _broadcast(self, make_msg) -> None:
        for r in self.peers:
            self._spawn(self._send(r, make_msg()))

    @property
    def majority(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    def is_leader(self) -> bool:
        return self.role == "leader"

    def is_active(self) -> bool:
        return self._active

    # ------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self._started = True
        self._spawn(self._tick())
        self.start_election()

    async def stop(self) -> None:
        self._started = False
        await reap_all(list(self._tasks))

    async def _tick(self) -> None:
        while True:
            await asyncio.sleep(self.LEASE_INTERVAL / 2)
            now = time.monotonic()
            if self.role == "leader" and self._active:
                if self.uncommitted is not None and \
                        now > self._accept_deadline:
                    # a quorum member died mid-proposal: accept acks will
                    # never arrive; bounce into an election so the quorum
                    # shrinks to the live set (Paxos.cc accept timeout)
                    dout("paxos", 5, f"mon.{self.rank}: accept timeout, "
                                     "electing")
                    self.start_election()
                else:
                    self._extend_lease()
            elif self.role == "leader" and not self._active and \
                    now > self._accept_deadline:
                # collect phase stalled the same way (dead quorum member
                # between victory and active): re-elect with the live set
                dout("paxos", 5, f"mon.{self.rank}: collect timeout, "
                                 "electing")
                self.start_election()
            elif self.role == "peon" and now > self._lease_expiry:
                dout("paxos", 5, f"mon.{self.rank}: lease expired, electing")
                self.start_election()
            elif self.role in ("probing", "electing") and \
                    now > self._election_deadline:
                self._finish_election()

    # -------------------------------------------------------------- election

    def start_election(self) -> None:
        self.role = "electing"
        self._active = False
        if self.perf is not None:
            self.perf.inc("election")
        self.epoch += 1 if self.epoch % 2 == 0 else 2
        self.store.put_one("paxos", "election_epoch", self.epoch)
        self._election_acks = {self.rank}
        self._election_deadline = time.monotonic() + self.ELECTION_TIMEOUT
        dout("paxos", 10, f"mon.{self.rank}: election epoch {self.epoch}")
        self._broadcast(lambda: MMonElection(
            {"op": "propose", "epoch": self.epoch, "rank": self.rank}))
        if not self.peers:
            self._finish_election()

    def _finish_election(self) -> None:
        if self.role != "electing":
            return
        if len(self._election_acks) >= self.majority:
            self._declare_victory()
        else:
            # couldn't form quorum: retry
            self._election_deadline = time.monotonic() + self.ELECTION_TIMEOUT
            self._broadcast(lambda: MMonElection(
                {"op": "propose", "epoch": self.epoch, "rank": self.rank}))

    def _declare_victory(self) -> None:
        self.epoch += 1 if self.epoch % 2 == 1 else 2
        self.store.put_one("paxos", "election_epoch", self.epoch)
        self.role = "leader"
        self.leader = self.rank
        self.quorum = set(self._election_acks)
        dout("paxos", 5, f"mon.{self.rank}: leader of {sorted(self.quorum)} "
                         f"epoch {self.epoch}")
        self._broadcast(lambda: MMonElection(
            {"op": "victory", "epoch": self.epoch, "rank": self.rank,
             "quorum": sorted(self.quorum)}))
        self._collect()
        self.on_role_change()

    async def handle_election(self, conn: Connection, msg: MMonElection) -> None:
        op = msg.payload["op"]
        peer_rank = msg.payload["rank"]
        peer_epoch = msg.payload["epoch"]
        if peer_epoch > self.epoch:
            self.epoch = peer_epoch
            self.store.put_one("paxos", "election_epoch", self.epoch)
        if op == "propose":
            if peer_rank < self.rank:
                # they outrank us (lower rank wins): defer
                self.role = "electing" if self.role != "peon" else self.role
                self._active = False
                self._election_deadline = time.monotonic() + \
                    self.LEASE_TIMEOUT
                await self._send(peer_rank, MMonElection(
                    {"op": "ack", "epoch": peer_epoch, "rank": self.rank}))
            else:
                # we outrank them: push our own candidacy
                if self.role in ("leader", "peon") and self._active and \
                        self.leader is not None and self.leader < peer_rank \
                        and peer_rank in self.quorum:
                    # stable quorum under a better leader and the proposer
                    # is already a member (a duplicate/late propose);
                    # re-assert it
                    if self.is_leader():
                        self._broadcast(lambda: MMonElection(
                            {"op": "victory", "epoch": self.epoch,
                             "rank": self.rank,
                             "quorum": sorted(self.quorum)}))
                else:
                    # a rank OUTSIDE the quorum proposing means a mon
                    # booted/rejoined: run a full election so the quorum
                    # grows to include it (the reference joins every
                    # propose; re-asserting the stale quorum would lock
                    # the newcomer out forever — ADVICE r3)
                    self.start_election()
        elif op == "ack":
            if self.role == "electing" and peer_epoch == self.epoch:
                self._election_acks.add(peer_rank)
                if len(self._election_acks) == len(self.peers) + 1:
                    self._finish_election()   # everyone answered: no wait
        elif op == "victory":
            if peer_rank <= self.rank:
                self.role = "peon"
                self.leader = peer_rank
                self.quorum = set(msg.payload.get("quorum", []))
                self._lease_expiry = time.monotonic() + self.LEASE_TIMEOUT
                self._fail_proposals("lost leadership")
                self.on_role_change()
            else:
                self.start_election()   # a worse rank claims victory: contest

    # --------------------------------------------------------------- collect

    def _new_pn(self) -> int:
        pn = ((max(self.last_pn, self.accepted_pn) // 100) + 1) * 100 \
            + self.rank
        self.last_pn = pn
        self.store.put_one("paxos", "last_pn", pn)
        return pn

    def _collect(self) -> None:
        """Leader phase 1 (Paxos.cc:154): gather promises + stray state."""
        self._active = False
        pn = self._new_pn()
        self.accepted_pn = pn
        self.store.put_one("paxos", "accepted_pn", pn)
        self._collect_acks = {self.rank}
        self._accept_deadline = time.monotonic() + self.ACCEPT_TIMEOUT
        if self.uncommitted and self.uncommitted[1] == self.last_committed + 1:
            self._pending_value = self.uncommitted[2]
        for r in sorted(self.quorum - {self.rank}):
            self._spawn(self._send(r, MMonPaxos(
                {"op": "collect", "pn": pn,
                 "last_committed": self.last_committed})))
        self._maybe_collect_done()

    def _maybe_collect_done(self) -> None:
        if self.role != "leader" or self._active:
            return
        if self._collect_acks >= self.quorum:
            self._active = True
            dout("paxos", 10, f"mon.{self.rank}: collect done, active")
            self._extend_lease()
            if self._pending_value is not None:
                value = self._pending_value
                self._pending_value = None
                self._begin(value)
            else:
                if self._inflight is not None:
                    # we re-won an election but the value we had in flight
                    # wasn't carried into this round (it either committed
                    # through a share or is gone): its outcome is unknown,
                    # so fail the waiter — callers retry and the service
                    # layer dedupes stale epochs
                    if not self._inflight.done():
                        self._inflight.set_exception(NotLeader(
                            "proposal outcome unknown after re-election"))
                    self._inflight = None
                self._kick_queue()

    # --------------------------------------------------------- begin/commit

    def propose(self, value: bytes) -> asyncio.Future:
        """Queue a value; resolves with its committed version (leader only;
        callers check is_leader)."""
        fut = asyncio.get_running_loop().create_future()
        if self.role != "leader":
            fut.set_exception(NotLeader(f"mon.{self.rank} is {self.role}"))
            return fut
        self._proposal_queue.append((value, fut))
        self._kick_queue()
        return fut

    def _fail_proposals(self, why: str) -> None:
        """Fail queued/in-flight proposal futures (leadership lost). The
        in-flight value may still commit through the new leader's collect;
        callers dedupe via service-level stale-epoch skip."""
        for _, fut in self._proposal_queue:
            if not fut.done():
                fut.set_exception(NotLeader(why))
        self._proposal_queue.clear()
        if self._inflight is not None and not self._inflight.done():
            self._inflight.set_exception(NotLeader(why))
        self._inflight = None

    def _kick_queue(self) -> None:
        if (self.role == "leader" and self._active
                and self._inflight is None and self._proposal_queue):
            value, fut = self._proposal_queue.pop(0)
            self._inflight = fut
            self._begin(value)

    def _begin(self, value: bytes) -> None:
        version = self.last_committed + 1
        self.uncommitted = (self.accepted_pn, version, value)
        self.store.put_one("paxos", "uncommitted",
                           [self.accepted_pn, version,
                            value.decode("latin1")])
        self._accept_acks = {self.rank}
        self._accept_deadline = time.monotonic() + self.ACCEPT_TIMEOUT
        for r in sorted(self.quorum - {self.rank}):
            self._spawn(self._send(r, MMonPaxos(
                {"op": "begin", "pn": self.accepted_pn, "version": version},
                value)))
        self._maybe_accepted()

    def _maybe_accepted(self) -> None:
        if self.uncommitted is None or self.role != "leader":
            return
        if self._accept_acks >= self.quorum:
            # whole quorum accepted (Paxos.cc:847 commit_start)
            pn, version, value = self.uncommitted
            self._commit(version, value)
            for r in sorted(self.quorum - {self.rank}):
                self._spawn(self._send(r, MMonPaxos(
                    {"op": "commit", "version": version}, value)))
            self._extend_lease()
            if self._inflight is not None and not self._inflight.done():
                self._inflight.set_result(version)
            self._inflight = None
            self._kick_queue()

    KEEP_VERSIONS = 256   # paxos trim window (mon_max_log_entries analog)

    def _commit(self, version: int, value: bytes) -> None:
        from ceph_tpu.mon.store import MonStoreTxn
        txn = MonStoreTxn()
        txn.put("paxos_values", str(version), value.decode("latin1"))
        txn.put("paxos", "last_committed", version)
        txn.erase("paxos", "uncommitted")
        # trim: keep a bounded version window (reference Paxos::trim) so
        # the store stays O(live state), not O(history)
        first = self.store.get("paxos", "first_committed", 1)
        new_first = version - self.KEEP_VERSIONS + 1
        if new_first > first:
            for v in range(first, new_first):
                txn.erase("paxos_values", str(v))
            txn.put("paxos", "first_committed", new_first)
        self.store.apply_transaction(txn)
        self.last_committed = version
        self.uncommitted = None
        if self.perf is not None:
            self.perf.inc("paxos_commit")
        self.on_commit(version, value)

    def _extend_lease(self) -> None:
        for r in sorted(self.quorum - {self.rank}):
            self._spawn(self._send(r, MMonPaxos(
                {"op": "lease", "last_committed": self.last_committed})))

    # ------------------------------------------------------------- peon side

    async def handle_paxos(self, conn: Connection, msg: MMonPaxos) -> None:
        op = msg.payload["op"]
        if op == "collect":
            pn = msg.payload["pn"]
            reply = {"op": "last", "pn": pn, "rank": self.rank,
                     "last_committed": self.last_committed}
            data = b""
            if pn > self.accepted_pn:
                self.accepted_pn = pn
                self.store.put_one("paxos", "accepted_pn", pn)
                if self.uncommitted:
                    reply["uncommitted_pn"] = self.uncommitted[0]
                    reply["uncommitted_version"] = self.uncommitted[1]
                    data = self.uncommitted[2]
            # share newer commits with a lagging leader regardless of
            # whether we also hold an uncommitted value (Paxos share_state)
            leader_lc = msg.payload.get("last_committed", 0)
            if self.last_committed > leader_lc:
                first = self.store.get("paxos", "first_committed", 1)
                if leader_lc + 1 < first:
                    # the LEADER is behind our trim horizon (it restarted
                    # after a long outage and won on rank): a gappy share
                    # would apply nothing; hand it the whole store instead
                    conn.send_message(MMonPaxos(
                        {"op": "sync_full", "store": self.store.dump(),
                         "last_committed": self.last_committed}))
                    return
                share = self._values_since(leader_lc)
                reply["share"] = [[v, val.decode("latin1")]
                                  for v, val in share]
            conn.send_message(MMonPaxos(reply, data))
        elif op == "last":
            if self.role != "leader":
                return
            peer = msg.payload["rank"]
            # learn newer commits from the peon
            for v, val in msg.payload.get("share", []):
                if v == self.last_committed + 1:
                    self._commit(v, val.encode("latin1"))
            if msg.payload.get("uncommitted_version") == \
                    self.last_committed + 1 and msg.data:
                self._pending_value = msg.data
            # catch a lagging peon up BEFORE counting it into the quorum:
            # ordered lossless delivery means these commits land before
            # any later begin, so the peon can accept version lc+1
            # (Paxos::share_state — the r3 'lagging peon rejects every
            # begin' wedge)
            peer_lc = msg.payload.get("last_committed", 0)
            if peer_lc < self.last_committed:
                first = self.store.get("paxos", "first_committed", 1)
                if peer_lc + 1 < first:
                    # beyond our trim horizon: full store sync
                    conn.send_message(MMonPaxos(
                        {"op": "sync_full",
                         "store": self.store.dump(),
                         "last_committed": self.last_committed}))
                else:
                    for v, val in self._values_since(peer_lc):
                        conn.send_message(MMonPaxos(
                            {"op": "commit", "version": v}, val))
            self._collect_acks.add(peer)
            self._maybe_collect_done()
        elif op == "sync_full":
            # we are hopelessly behind (restarted past the peer's trim
            # horizon): adopt the peer's whole store (Monitor sync). This
            # runs on a behind peon (leader caught us up) or on a behind
            # LEADER (a peon refused a gappy share) — a leader re-collects
            # with its recovered state so begins line up with the quorum.
            if msg.payload.get("last_committed", 0) <= self.last_committed:
                return      # stale/duplicate sync
            self.store.load_dump(msg.payload["store"])
            self.last_committed = self.store.get("paxos",
                                                 "last_committed", 0)
            self.accepted_pn = self.store.get("paxos", "accepted_pn", 0)
            self.uncommitted = None
            if self.on_sync is not None:
                self.on_sync()
            if self.role == "leader":
                self._collect()
        elif op == "begin":
            pn = msg.payload["pn"]
            version = msg.payload["version"]
            if pn >= self.accepted_pn and version == self.last_committed + 1:
                self.uncommitted = (pn, version, msg.data)
                self.store.put_one("paxos", "uncommitted",
                                   [pn, version, msg.data.decode("latin1")])
                conn.send_message(MMonPaxos(
                    {"op": "accept", "pn": pn, "version": version,
                     "rank": self.rank}))
        elif op == "accept":
            if self.role == "leader" and \
                    msg.payload["pn"] == self.accepted_pn:
                self._accept_acks.add(msg.payload["rank"])
                self._maybe_accepted()
        elif op == "commit":
            version = msg.payload["version"]
            if version == self.last_committed + 1:
                self._commit(version, msg.data)
            self._lease_expiry = time.monotonic() + self.LEASE_TIMEOUT
        elif op == "lease":
            self._lease_expiry = time.monotonic() + self.LEASE_TIMEOUT
            # catch up if we missed commits (shouldn't happen on lossless)
            conn.send_message(MMonPaxos(
                {"op": "lease_ack", "rank": self.rank,
                 "last_committed": self.last_committed}))
        elif op == "lease_ack":
            pass

    def _values_since(self, version: int) -> list[tuple[int, bytes]]:
        out = []
        for v in range(version + 1, self.last_committed + 1):
            val = self.store.get("paxos_values", str(v))
            if val is not None:
                out.append((v, val.encode("latin1")))
        return out

    # -------------------------------------------------------------- recovery

    def recover_from_store(self) -> None:
        """Reload committed history pointers after restart; the Monitor
        replays service state from its own store keys."""
        unc = self.store.get("paxos", "uncommitted")
        if unc:
            self.uncommitted = (unc[0], unc[1], unc[2].encode("latin1"))

    _election_deadline = float("inf")
    _accept_deadline = float("inf")
