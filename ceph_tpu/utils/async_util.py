"""Shared asyncio lifecycle helpers: cancellation-correct task reaping.

Every daemon in this stack ends the same way: cancel background tasks,
await them, swallow the expected CancelledError. Hand-rolled versions of
that dance keep re-growing the same two bugs radoslint's
cancellation-swallow rule exists for:

  * `except (asyncio.CancelledError, Exception): pass` swallows OUR OWN
    cancellation too — a teardown coroutine that is itself cancelled
    (test timeout, parent daemon dying) silently keeps running instead
    of unwinding, which is exactly how half-dead daemons linger;
  * `Task.cancelling()` is 3.11+; calling it on 3.10 raises
    AttributeError from inside the except handler (seen latent in the
    messenger's transport close path).

`reap()` centralizes the correct version: cancel the task, await it,
swallow only the CancelledError that belongs to the reaped task, and
re-raise when the *current* task is the one being cancelled.
"""
from __future__ import annotations

import asyncio
from typing import Iterable


def being_cancelled() -> bool:
    """True when the current task has a pending cancellation request.

    Uses Task.cancelling() on 3.11+; on 3.10 there is no reliable
    signal, so this degrades to False (matching the historical swallow
    behavior instead of crashing on a missing attribute)."""
    task = asyncio.current_task()
    if task is None:
        return False
    cancelling = getattr(task, "cancelling", None)
    if cancelling is None:
        return False
    return bool(cancelling())


async def reap(task: asyncio.Task | None) -> None:
    """Cancel `task` and await its completion.

    Swallows the task's own CancelledError and logged-elsewhere
    exceptions (the task already ran its error handling; reapers only
    care that it is DONE), but re-raises when the reaping task is
    itself being cancelled — teardown must stay cancellable."""
    if task is None:
        return
    task.cancel()
    try:
        # shield: a cancel aimed at US must not be delivered by
        # cancelling `task` (Task.cancel() cancels the awaited future —
        # without the shield that IS `task`, which then finishes
        # cancelled and makes our own cancellation indistinguishable
        # from the reaped task's on 3.10, where being_cancelled() is
        # blind). With the shield, `task.done()` is a reliable witness.
        await asyncio.shield(task)
    except asyncio.CancelledError:
        # two sources: the reaped task finishing cancelled (swallow) or
        # our own wait being interrupted (propagate). If the reaped
        # task is not done, the cancellation was ours.
        if being_cancelled() or not task.done():
            raise
    except Exception:
        pass


async def reap_all(tasks: Iterable[asyncio.Task | None]) -> None:
    """Cancel every task first (concurrent teardown), then await each.

    Cancellation-complete: when the reaping task is ITSELF cancelled
    mid-loop, the first reap() re-raises — the old version then skipped
    the remaining tasks, leaving them cancelled-but-never-awaited, i.e.
    pending at loop close ("Task was destroyed but it is pending!", the
    messenger _pump sub-task flavor of the BENCH_r05 tail spam). Our
    own CancelledError is held until every task has been awaited, then
    re-raised — teardown stays cancellable without abandoning work."""
    live = [t for t in tasks if t is not None]
    for t in live:
        t.cancel()
    interrupted: asyncio.CancelledError | None = None
    for t in live:
        try:
            await reap(t)
        # deferred re-raise below, once every task is done — not a
        # swallow
        # radoslint: disable-next=cancellation-swallow
        except asyncio.CancelledError as e:
            interrupted = e          # finish reaping before unwinding
            if not t.done():
                # our own cancel interrupted THIS task's reap — await it
                # through (it is already cancelled); a repeated cancel
                # during the retry abandons it as the last resort
                try:
                    await reap(t)
                # radoslint: disable-next=cancellation-swallow
                except asyncio.CancelledError:
                    pass
    if interrupted is not None:
        raise interrupted


async def drain(task: asyncio.Task | None) -> None:
    """Await `task` WITHOUT cancelling it — for work that must complete
    (a detached close(), an in-flight commit), where cancelling would
    leave shared state half-torn-down. Same cancellation contract as
    reap(): the task's own failure/cancellation is swallowed, our own
    cancellation propagates."""
    if task is None:
        return
    try:
        # shield, for two reasons: cancelling US must not collaterally
        # cancel the task we promised to await WITHOUT cancelling, and
        # (as in reap) it keeps `task.done()` a reliable witness of
        # whose CancelledError this is on 3.10.
        await asyncio.shield(task)
    except asyncio.CancelledError:
        if being_cancelled() or not task.done():
            raise
    except Exception:
        pass


async def drain_all(tasks: Iterable[asyncio.Task | None]) -> None:
    """drain() each task; like reap_all, our own cancellation is held
    until every task was awaited (abandoning the tail leaks it)."""
    interrupted: asyncio.CancelledError | None = None
    for t in list(tasks):
        try:
            await drain(t)
        # deferred re-raise below, once every task was awaited
        # radoslint: disable-next=cancellation-swallow
        except asyncio.CancelledError as e:
            interrupted = e
            if t is not None and not t.done():
                # finish waiting out the interrupted task; a repeated
                # cancel during the retry abandons it as the last resort
                try:
                    await drain(t)
                # radoslint: disable-next=cancellation-swallow
                except asyncio.CancelledError:
                    pass
    if interrupted is not None:
        raise interrupted


async def bounded_stop(coro, timeout: float) -> bool:
    """Await a teardown coroutine under a deadline WITHOUT leaking it.

    The old pattern — `asyncio.wait_for(daemon.stop(), 20)` inside
    `except Exception: pass` — cancels a slow stop() halfway through
    its own reaping and abandons it, leaving connection/dispatch tasks
    pending at loop close ("Task was destroyed but it is pending!", the
    BENCH_r05 tail spam). Here the timeout instead REAPS the
    half-finished teardown (cancel + await), so everything it owns is
    done before we return. Returns True when the stop completed
    cleanly, False on timeout or failure."""
    task = asyncio.get_running_loop().create_task(coro)
    try:
        await asyncio.wait_for(asyncio.shield(task), timeout)
        return True
    except asyncio.TimeoutError:
        # the reap gets its own deadline: a stop() that swallows the
        # injected cancel (or whose finally awaits a wedged peer) must
        # not hang teardown forever — abandoning it, and eating one
        # destroyed-pending report, is the last resort
        try:
            await asyncio.wait_for(reap(task), timeout)
        except asyncio.TimeoutError:
            pass
        return False
    except asyncio.CancelledError:
        await reap(task)
        raise
    except Exception:
        return False


# -- executor-backed file I/O -------------------------------------------------
# Sync open()/read()/write() inside a coroutine stalls the whole event
# loop behind one syscall (radoslint: blocking-in-coroutine). The CLI
# tools route one-shot blob I/O through the default executor instead.

async def read_file(path: str) -> bytes:
    def _read() -> bytes:
        with open(path, "rb") as f:
            return f.read()
    return await asyncio.get_running_loop().run_in_executor(None, _read)


async def write_file(path: str, data: bytes) -> None:
    def _write() -> None:
        with open(path, "wb") as f:
            f.write(data)
    await asyncio.get_running_loop().run_in_executor(None, _write)
