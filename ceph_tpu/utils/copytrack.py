"""Byte-flow copy ledger: bytes-copied vs bytes-referenced per stage.

The 450x device-vs-cluster gap (BENCH_r05: device encode ~32 GB/s,
cluster EC write 69.77 MB/s) is transfer- and event-loop-bound, and the
planned zero-copy buffer discipline needs a before/after meter: without
one, "we removed a copy" is a code-review claim, not a measurement.
This module is that meter — a process-wide ledger the data path feeds
at every point where bytes either move (copied) or merely change hands
(referenced):

  frame_tx           message segments assembled into a wire frame blob
  frame_rx           wire blob sliced back into frame segment buffers
  frame_to_buffer    message data handed to the codec-facing buffer
                     (np.frombuffer = referenced; bytes() = copied)
  buffer_to_staging  per-op buffers stacked into a staged device batch
  h2d                staged batch transferred into device memory
  d2h                device result transferred back to host memory
  reply_assemble     host result planes copied into per-shard replies

Each stage tracks copied bytes, referenced bytes, copy wall time, and
event count. The hot-path cost is one lock + three int adds per event
(events are per-op/per-frame, never per-byte). Surfaces:

  * `snapshot()` — the raw ledger (bench attribution stage, tests);
  * span attributes — the offload batch / encode spans tag their own
    copy bytes+time, so `trace dump` shows where an op's copies were;
  * perf counters — a pull-model "copyflow" logger in the process-wide
    collection: values sync from the ledger at dump() time, so they
    ride `perf dump`, the MgrClient report stream, and /metrics like
    any other counter without double bookkeeping on the hot path.
"""
from __future__ import annotations

import threading

from ceph_tpu.utils.perf_counters import (PerfCounters,
                                          PerfCountersCollection)

#: the pipeline stages, in data-path order (the attribution waterfall
#: renders them in this order)
STAGES = ("frame_tx", "frame_rx", "frame_to_buffer",
          "buffer_to_staging", "h2d", "d2h", "reply_assemble")

_lock = threading.Lock()
_copied = dict.fromkeys(STAGES, 0)
_referenced = dict.fromkeys(STAGES, 0)
_seconds = dict.fromkeys(STAGES, 0.0)
_events = dict.fromkeys(STAGES, 0)


def copied(stage: str, nbytes: int, seconds: float = 0.0) -> None:
    """Record `nbytes` physically copied at `stage` (optionally with the
    wall time the copy took, for the attribution copy bucket)."""
    with _lock:
        _copied[stage] += int(nbytes)
        _seconds[stage] += seconds
        _events[stage] += 1


def referenced(stage: str, nbytes: int) -> None:
    """Record `nbytes` passed through `stage` zero-copy (a view/window
    changed hands; no bytes moved)."""
    with _lock:
        _referenced[stage] += int(nbytes)
        _events[stage] += 1


def snapshot() -> dict:
    """The ledger as one dict: per-stage and totals."""
    with _lock:
        stages = {s: {"copied_bytes": _copied[s],
                      "referenced_bytes": _referenced[s],
                      "copy_seconds": round(_seconds[s], 6),
                      "events": _events[s]}
                  for s in STAGES}
    return {"stages": stages,
            "copied_bytes_total": sum(d["copied_bytes"]
                                      for d in stages.values()),
            "referenced_bytes_total": sum(d["referenced_bytes"]
                                          for d in stages.values()),
            "copy_seconds_total": round(sum(d["copy_seconds"]
                                            for d in stages.values()), 6)}


def amplification(bytes_written: int) -> float:
    """Copy amplification: bytes physically copied anywhere in the
    pipeline per byte the client logically wrote. The zero-copy work's
    target metric — 0.0 when nothing was written."""
    if bytes_written <= 0:
        return 0.0
    with _lock:
        total = sum(_copied.values())
    return round(total / bytes_written, 3)


def reset() -> None:
    with _lock:
        for s in STAGES:
            _copied[s] = 0
            _referenced[s] = 0
            _seconds[s] = 0.0
            _events[s] = 0


class _CopyflowCounters(PerfCounters):
    """Pull-model perf counters: values sync from the ledger when
    dumped, so the per-event hot path never touches the counter lock."""

    def __init__(self):
        super().__init__("copyflow")
        for s in STAGES:
            self.add(f"copied_bytes_{s}",
                     description=f"bytes physically copied at the "
                                 f"{s} stage")
            self.add(f"referenced_bytes_{s}",
                     description=f"bytes passed zero-copy through the "
                                 f"{s} stage")
            self.add(f"copy_micros_{s}",
                     description=f"wall time (µs) spent copying at the "
                                 f"{s} stage")

    def dump(self) -> dict:
        snap = snapshot()
        for s, d in snap["stages"].items():
            self.set(f"copied_bytes_{s}", d["copied_bytes"])
            self.set(f"referenced_bytes_{s}", d["referenced_bytes"])
            self.set(f"copy_micros_{s}", round(d["copy_seconds"] * 1e6))
        return super().dump()


def perf() -> PerfCounters:
    """The ledger's perf-counter mirror, registered on first use (so it
    rides the MgrClient `extra_loggers` report path and /metrics)."""
    coll = PerfCountersCollection.instance()
    pc = coll.get("copyflow")
    if pc is None:
        try:
            pc = coll.register(_CopyflowCounters())
        except ValueError:
            pc = coll.get("copyflow")   # another shard loop won the race
    return pc
