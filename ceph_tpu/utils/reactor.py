"""Sharded reactor runtime: N OS threads, each owning one asyncio loop.

BENCH_r05's attribution stage pins the 450x device-vs-cluster gap on a
single saturated Python event loop (`loop_busy_fraction` ~1.0 on the
only loop in the process): every OSD, the mon, the mgr, and the client
all contend for the same reactor thread, so the cluster's ceiling is
one core's worth of frame parsing and dispatch no matter how many
devices the offload service fans across. This module is the
Crimson/seastar analog the SURVEY names: a pool of reactor *shards*,
each an OS thread running its own event loop, with daemons placed
whole onto shards —

  * shard 0 is the CALLING loop (the harness/main loop): the mon, mgr,
    and clients stay there, exactly like the pre-shard world;
  * OSDs are placed round-robin across all shards (`place()`), so the
    data-plane daemons stop sharing one reactor;
  * connections between daemons on different shards are real localhost
    socket hops (the messenger already speaks TCP between daemons, so
    cross-shard needs no new wire plumbing); same-shard messaging
    stays in-loop;
  * a `ShardPool(1)` is the degenerate case: no threads, no behavior
    change — the knob dials concurrency without forking the code path.

Loop-affinity discipline (enforced by radoslint's `loop-affinity`
rule): loop-bound objects (asyncio primitives, the OffloadService, a
messenger Connection) belong to exactly one shard. Touching one from
another shard must go through the threadsafe seams — `run_on()` /
`run_on_each()` here, `loop.call_soon_threadsafe`, or
`asyncio.run_coroutine_threadsafe` — never a bare `call_soon`/
`create_task` on a foreign loop handle.

The pool also carries `shared(key, factory)`: process-level services
that must span every shard (the offload device topology and its
per-device circuit breakers) hang their one shared instance off the
pool instead of the loop, so four shards see one breaker state per
chip rather than four conflicting ones.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import functools
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable

from ceph_tpu.utils import flight
from ceph_tpu.utils.async_util import reap_all
from ceph_tpu.utils.dout import dout

#: process-wide switch-interval management: the 0.5 ms bound is a
#: property of "any multi-shard pool is live", not of one pool — two
#: overlapping pools with per-pool save/restore would let the first
#: shutdown restore 5 ms under the second pool, then the second
#: shutdown "restore" 0.5 ms forever. Refcounted instead.
_switch_lock = threading.Lock()
_multi_pool_count = 0
_saved_interval: float | None = None


def _switch_interval_enter(interval_s: float) -> None:
    global _multi_pool_count, _saved_interval
    with _switch_lock:
        if _multi_pool_count == 0:
            _saved_interval = sys.getswitchinterval()
            sys.setswitchinterval(interval_s)
        _multi_pool_count += 1


def _switch_interval_exit() -> None:
    global _multi_pool_count, _saved_interval
    with _switch_lock:
        if _multi_pool_count == 0:
            return
        _multi_pool_count -= 1
        if _multi_pool_count == 0 and _saved_interval is not None:
            sys.setswitchinterval(_saved_interval)
            _saved_interval = None


#: loop -> [(pool, shard_index), ...]; the process-wide placement
#: registry. Lets loop-keyed services (offload, loopprof) answer "which
#: shard am I, and which pool do I share state with" from any thread.
#: A STACK per loop, not a single slot: the parent loop is shard 0 of a
#: live ProcShardPool AND of a nested thread ShardPool in mixed mode —
#: the inner pool's teardown must restore the outer registration, not
#: erase it.
_registry_lock = threading.Lock()
_by_loop: dict[asyncio.AbstractEventLoop, list[tuple]] = {}


def _register(loop, pool, index: int) -> None:
    with _registry_lock:
        for stale in [lp for lp in _by_loop if lp.is_closed()]:
            del _by_loop[stale]
        _by_loop.setdefault(loop, []).append((pool, index))


def _unregister(loop, pool=None) -> None:
    """Remove `pool`'s registration of `loop` (the newest entry when
    pool is None), restoring whatever outer pool registered it first."""
    with _registry_lock:
        stack = _by_loop.get(loop)
        if not stack:
            return
        if pool is None:
            stack.pop()
        else:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] is pool:
                    del stack[i]
                    break
        if not stack:
            del _by_loop[loop]


def pool_for(loop) -> "ShardPool | None":
    """The ShardPool `loop` belongs to (None for unpooled loops —
    standalone tests and single-loop tools keep their private world)."""
    with _registry_lock:
        stack = _by_loop.get(loop)
        return stack[-1][0] if stack else None


def shard_index_of(loop) -> int | None:
    with _registry_lock:
        stack = _by_loop.get(loop)
        return stack[-1][1] if stack else None


def shard_label(loop) -> str | None:
    """Stable display label ("shard0"...) for exports, or None."""
    idx = shard_index_of(loop)
    return None if idx is None else f"shard{idx}"


def current_pool() -> "ShardPool | None":
    """The running loop's pool, or None (callable from coroutines)."""
    try:
        return pool_for(asyncio.get_running_loop())
    except RuntimeError:
        return None


class Shard:
    """One reactor: an event loop plus the thread that runs it (thread
    is None for shard 0, which borrows the creating loop)."""

    __slots__ = ("index", "loop", "thread", "ready")

    def __init__(self, index: int):
        self.index = index
        self.loop: asyncio.AbstractEventLoop | None = None
        self.thread: threading.Thread | None = None
        self.ready = threading.Event()


class ShardPool:
    """`n` reactor shards: the creating loop plus n-1 loop threads.

    Must be constructed on a running event loop (it becomes shard 0).
    `shutdown()` reaps every thread shard's leftover tasks before
    stopping its loop, so a pool teardown is as tail-clean as a daemon
    stop (no "Task was destroyed but it is pending")."""

    START_TIMEOUT = 10.0

    #: shards share this process's memory (the ProcShardPool analog is
    #: "process"); consumers like the offload topology key their
    #: shared-vs-private decision on this
    backend = "thread"

    #: GIL switch interval while a multi-shard pool is live. A
    #: cross-shard hop (call_soon_threadsafe wakeup, socket readable on
    #: another shard) can wait up to a FULL switch interval for the GIL
    #: when every loop thread is busy; at CPython's default 5 ms that
    #: convoys a multi-hop EC write into tens of ms of pure handoff
    #: latency (measured: the 4-shard curve collapsed ~6x on a 2-core
    #: box before this). 0.5 ms trades a little single-thread
    #: throughput for bounded cross-shard latency.
    SWITCH_INTERVAL_S = 0.0005

    def __init__(self, num_shards: int, name: str = "reactor"):
        if num_shards < 1:
            raise ValueError("a reactor pool needs at least one shard")
        self.name = name
        self._closed = False
        self._holds_switch_interval = num_shards > 1
        if self._holds_switch_interval:
            _switch_interval_enter(self.SWITCH_INTERVAL_S)
        self._shared_lock = threading.Lock()
        self._shared: dict[str, Any] = {}
        shard0 = Shard(0)
        shard0.loop = asyncio.get_running_loop()
        shard0.ready.set()
        self._shards = [shard0]
        _register(shard0.loop, self, 0)
        try:
            for i in range(1, num_shards):
                shard = Shard(i)
                shard.thread = threading.Thread(
                    target=self._shard_main, args=(shard,),
                    name=f"{name}-shard{i}", daemon=True)
                self._shards.append(shard)
                shard.thread.start()
            for shard in self._shards[1:]:
                if not shard.ready.wait(self.START_TIMEOUT):
                    raise RuntimeError(f"{name} shard {shard.index} "
                                       f"never came up")
        except BaseException:
            # a failed boot must not leak running shard threads nor
            # leave the process-wide switch interval degraded
            self._abort_started_shards()
            raise
        dout("reactor", 1, f"{name}: {num_shards} shard(s) up")

    def _abort_started_shards(self) -> None:
        if self._holds_switch_interval:
            _switch_interval_exit()
            self._holds_switch_interval = False
        for shard in self._shards[1:]:
            loop = shard.loop
            if loop is not None and not loop.is_closed():
                loop.call_soon_threadsafe(loop.stop)
            if shard.thread is not None:
                shard.thread.join(self.START_TIMEOUT)
        _unregister(self._shards[0].loop, self)
        self._closed = True

    # -- placement -----------------------------------------------------------

    @property
    def num_shards(self) -> int:
        return len(self._shards)

    def place(self, seq: int) -> int:
        """Round-robin shard index for the seq-th data-plane daemon."""
        return seq % len(self._shards)

    def loop(self, index: int) -> asyncio.AbstractEventLoop:
        return self._shards[index].loop

    # -- cross-shard seams ---------------------------------------------------

    async def run_on(self, index: int, coro) -> Any:
        """Run `coro` on shard `index` and await its result from the
        calling shard. Same-shard awaits inline; cross-shard hops via
        run_coroutine_threadsafe (the call_soon_threadsafe handoff)."""
        target = self._shards[index].loop
        if target is asyncio.get_running_loop():
            return await coro
        cfut = asyncio.run_coroutine_threadsafe(coro, target)
        return await asyncio.wrap_future(cfut)

    async def run_on_each(self, fn: Callable[[], Any]) -> list:
        """Run sync `fn()` ON every shard's loop thread (shard 0
        inline) — the arming hook for per-loop instruments (loopprof
        install/uninstall need the loop thread's ident)."""
        out = []
        for shard in self._shards:
            if shard.loop is asyncio.get_running_loop():
                out.append(fn())
                continue
            done: concurrent.futures.Future = concurrent.futures.Future()

            def call(done=done):
                try:
                    done.set_result(fn())
                except BaseException as e:   # marshal failures back whole
                    done.set_exception(e)
            shard.loop.call_soon_threadsafe(call)
            out.append(await asyncio.wrap_future(done))
        return out

    # -- pool-scoped shared state --------------------------------------------

    def shared(self, key: str, factory: Callable[[], Any]) -> Any:
        """Get-or-create the pool-wide instance of a cross-shard
        service (one offload device topology per pool, not per loop)."""
        with self._shared_lock:
            obj = self._shared.get(key)
            if obj is None:
                obj = self._shared[key] = factory()
            return obj

    # -- lifecycle -----------------------------------------------------------

    def _shard_main(self, shard: Shard) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        shard.loop = loop
        _register(loop, self, shard.index)
        shard.ready.set()
        try:
            loop.run_forever()
            # post-stop drain: anything still pending here was created
            # after the final reap (or leaked past a daemon stop) —
            # cancel-and-await so loop.close() destroys nothing pending
            leftovers = asyncio.all_tasks(loop)
            if leftovers:
                loop.run_until_complete(reap_all(leftovers))
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.run_until_complete(loop.shutdown_default_executor())
        finally:
            try:
                from ceph_tpu.utils import loopprof
                loopprof.uninstall(loop)     # defensive: sampler unarm
            except Exception:
                pass
            _unregister(loop, self)
            loop.close()

    async def _drain_shard(self) -> None:
        """Runs ON a thread shard: reap every task but ourselves."""
        cur = asyncio.current_task()
        await reap_all([t for t in asyncio.all_tasks() if t is not cur])

    async def shutdown(self, timeout: float = 20.0) -> None:
        """Reap and stop every thread shard (idempotent). The daemons
        on each shard must already be stopped — this reaps stragglers,
        parks the loop, and joins the thread."""
        if self._closed:
            return
        self._closed = True
        if self._holds_switch_interval:
            _switch_interval_exit()
            self._holds_switch_interval = False
        for shard in self._shards[1:]:
            loop = shard.loop
            if loop is None or loop.is_closed():
                continue
            cfut = asyncio.run_coroutine_threadsafe(
                self._drain_shard(), loop)
            try:
                await asyncio.wait_for(asyncio.wrap_future(cfut), timeout)
            except Exception as e:
                dout("reactor", 1,
                     f"{self.name}: shard {shard.index} drain failed "
                     f"({type(e).__name__}: {e}); stopping it anyway")
                cfut.cancel()
            loop.call_soon_threadsafe(loop.stop)
            if shard.thread is not None:
                await asyncio.get_running_loop().run_in_executor(
                    None, shard.thread.join, timeout)
        _unregister(self._shards[0].loop, self)
        dout("reactor", 1, f"{self.name}: pool down")


# ---------------------------------------------------------------------------
# process-backed shards: the true GIL escape
# ---------------------------------------------------------------------------
#
# The thread-backed ShardPool buys loops, not parallelism: on a 2-core
# box the 1->2 shard curve measured 0.74x because every loop thread
# still serializes on one interpreter lock (ROADMAP, BENCH trend). The
# process-backed mode below forks the shards into real OS processes —
# each worker runs its own interpreter, its own event loop, its own
# OffloadService front end over a PARTITIONED device topology — and the
# messenger already speaks TCP between daemons, so the data path crosses
# the process boundary with zero new wire plumbing. What needs building
# is the lifecycle (spawn/supervise/reap/respawn) and the seams:
#
#   * control channel: each worker binds an AdminSocket (the same
#     plumbing every daemon already exposes) and the parent drives it
#     with JSON verbs — boot_osd / stop_osd / config set / inject /
#     worker status / profile dump / shutdown. Hot-togglable knobs reach
#     worker observers through `config set` exactly as an operator's
#     would.
#   * supervision: a parent-loop task polls worker liveness; a dead
#     worker is reaped immediately (no zombies) and its OSDs go through
#     the EXISTING reporter-quorum mark-down — peers stop hearing
#     heartbeats, report failures, the mon marks down. `respawn()`
#     re-spawns the worker and re-boots its recorded OSDs.
#   * rejected conveniences: `shared()` and `run_on()` raise — there is
#     no cross-process memory and a coroutine cannot be marshalled.
#     State crosses through `call()` (JSON over the control channel) or
#     the cluster's own wire protocol, full stop. radoslint's
#     `proc-shared-state` rule enforces the same contract statically.
#
# A ProcShardPool never touches the GIL switch interval: its shards do
# not share an interpreter, so the 0.5 ms override would be a pure
# context-switch tax on the parent (and the refcount above keeps a
# concurrently-live thread pool's override correct in mixed mode).


class _WorkerShard:
    """In-worker identity stub: `pool_for()` / `shard_index_of()` inside
    a spawned worker process resolve to this, so shard labels (loopprof
    gauges, `OSD.shard` in daemon status) carry the POOL-WIDE shard
    index the parent assigned — not a pid-local counter. Cross-process
    conveniences are structurally absent: state is marshalled over the
    admin-socket control channel."""

    backend = "process"

    def __init__(self, name: str, index: int):
        self.name = name
        self.index = index

    def shared(self, key: str, factory: Callable[[], Any]) -> Any:
        raise NotImplementedError(
            "shared() inside a process-backed shard: cross-process "
            "memory does not exist — marshal state over the control "
            "channel or the cluster wire protocol")


def adopt_worker_shard(index: int, name: str = "reactor") -> None:
    """Register the RUNNING loop as pool-wide shard `index` of a
    process-backed pool (called once by the worker entry point before
    any daemon boots, so every loop-keyed service sees the identity)."""
    _register(asyncio.get_running_loop(), _WorkerShard(name, index), index)


class _ProcWorker:
    """Parent-side record of one spawned shard worker."""

    __slots__ = ("index", "proc", "socket_path", "boot_specs",
                 "osd_overrides", "alive", "generation")

    def __init__(self, index: int):
        self.index = index
        self.proc: subprocess.Popen | None = None
        self.socket_path = ""
        # whoami -> boot_osd request payload; respawn() replays these so
        # a killed worker's daemons rejoin under their original ids
        self.boot_specs: dict[int, dict] = {}
        # whoami -> {option: value} set through a per-OSD handle
        # (WorkerOSDRef.config_set); replayed after a respawned boot so
        # a rejoining daemon keeps its operator-set knobs too
        self.osd_overrides: dict[int, dict] = {}
        self.alive = False
        self.generation = 0


class ProcShardPool:
    """`reactor_procs` worker PROCESSES plus the calling loop (shard 0).

    Placement mirrors the thread pool — OSDs round-robin over the
    workers (shard indices 1..n) while the mon/mgr/clients stay on the
    parent loop — but each worker is a spawned interpreter running
    `ceph_tpu.utils.reactor_worker`, so shard parallelism is deliverable
    CPU parallelism, not GIL time-slicing. Construction spawns the
    processes; `await start()` waits for every control channel to come
    up and arms the supervisor. `shutdown()` drains workers through the
    `shutdown` verb (each worker bounded-stops its daemons and reaps its
    loop's stragglers before exiting), then reaps the processes — the
    parent side leaves no pending tasks behind (conftest leak gate)."""

    backend = "process"
    START_TIMEOUT = 30.0
    SUPERVISE_INTERVAL_S = 0.25

    def __init__(self, num_procs: int, name: str = "reactor",
                 base_dir: str | None = None):
        if num_procs < 1:
            raise ValueError("a process pool needs at least one worker")
        self.name = name
        self.num_procs = num_procs
        self._closed = False
        self._started = False
        self._loop0 = asyncio.get_running_loop()
        self._supervisor: asyncio.Task | None = None
        self._own_dir = base_dir is None
        self._dir = base_dir or tempfile.mkdtemp(prefix="reactor-proc-")
        # operator-set hot knobs, replayed onto a respawned worker's
        # re-booted OSDs so it rejoins with the SAME effective config as
        # its peers (a fresh process knows nothing of earlier
        # broadcasts). Values are (seq, value): per-OSD and pool-wide
        # settings replay in their ORIGINAL chronological order, so the
        # newest write wins after a respawn exactly as it did live.
        self._config_overrides: dict[str, tuple[int, Any]] = {}
        self._override_seq = 0
        self._workers = [_ProcWorker(i + 1) for i in range(num_procs)]
        _register(self._loop0, self, 0)
        try:
            for w in self._workers:
                self._spawn(w)
        except BaseException:
            self._kill_all()
            for w in self._workers:
                if w.socket_path:
                    try:
                        os.unlink(w.socket_path)
                    except OSError:
                        pass
            if self._own_dir:
                try:
                    os.rmdir(self._dir)
                except OSError:
                    pass
            _unregister(self._loop0, self)
            raise

    # -- spawn / supervise ----------------------------------------------------

    def _spawn(self, w: _ProcWorker) -> None:
        if w.socket_path:
            # a SIGKILLed worker never unlinked its previous-generation
            # socket; reap the file here or crash/respawn cycles leak
            # them (and keep our own mkdtemp dir from ever emptying)
            try:
                os.unlink(w.socket_path)
            except OSError:
                pass
        w.generation += 1
        w.socket_path = os.path.join(
            self._dir, f"rw{w.index}.{w.generation}.sock")
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        # device-affine chip partitioning: worker j of W serves the
        # round-robin slice devs[j::W], so per-chip XLA-compile and
        # pinned-bitmatrix warmth stays process-local (offload/service
        # reads this at device enumeration)
        env["CEPH_TPU_OFFLOAD_DEVICE_PARTITION"] = \
            f"{w.index - 1}/{self.num_procs}"
        w.proc = subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.utils.reactor_worker",
             "--index", str(w.index), "--socket", w.socket_path,
             "--pool-name", self.name],
            env=env, stdout=subprocess.DEVNULL)
        w.alive = True
        dout("reactor", 2, f"{self.name}: worker shard{w.index} spawned "
                           f"(pid {w.proc.pid})")

    async def start(self, timeout: float | None = None) -> None:
        """Wait until every worker's control channel answers, then arm
        the supervisor. Must run on the creating (shard 0) loop."""
        await self._wait_ready(self._workers, timeout)
        if self._supervisor is None:
            self._supervisor = asyncio.get_running_loop().create_task(
                self._supervise())
        self._started = True
        dout("reactor", 1,
             f"{self.name}: {self.num_procs} worker process(es) up")

    async def _wait_ready(self, workers: list[_ProcWorker],
                          timeout: float | None = None) -> None:
        deadline = time.monotonic() + (timeout or self.START_TIMEOUT)
        for w in workers:
            while True:
                if w.proc is not None and w.proc.poll() is not None:
                    raise RuntimeError(
                        f"{self.name} worker shard{w.index} exited "
                        f"rc={w.proc.returncode} before its control "
                        f"channel came up")
                try:
                    await self.call(w.index, "version", timeout=2.0)
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"{self.name} worker shard{w.index} control "
                            f"channel never came up") from None
                    await asyncio.sleep(0.05)

    async def _supervise(self) -> None:
        """Reap dead workers promptly: a SIGKILLed (or crashed) worker
        must not linger as a zombie, and its death is WARN-logged — the
        mark-down of its OSDs rides the existing peer-heartbeat
        reporter-quorum path, no parent intervention needed."""
        while True:
            await asyncio.sleep(self.SUPERVISE_INTERVAL_S)
            for w in self._workers:
                if w.alive and w.proc is not None \
                        and w.proc.poll() is not None:
                    w.proc.wait()       # already exited: reap, no block
                    w.alive = False
                    dout("reactor", 1,
                         f"{self.name}: worker shard{w.index} died "
                         f"(rc {w.proc.returncode}); reaped — its OSDs "
                         f"will be marked down via heartbeat loss")
                    flight.record("worker_death", f"shard{w.index}",
                                  pool=self.name, pid=w.proc.pid,
                                  rc=w.proc.returncode,
                                  osds=sorted(w.boot_specs))

    # -- placement / identity -------------------------------------------------

    @property
    def num_shards(self) -> int:
        return self.num_procs + 1

    def place(self, seq: int) -> int:
        """Round-robin WORKER shard for the seq-th data-plane daemon
        (never 0: the parent keeps the control plane)."""
        return 1 + seq % self.num_procs

    def loop(self, index: int) -> asyncio.AbstractEventLoop:
        if index != 0:
            raise NotImplementedError(
                f"shard {index} runs in another process: its loop is "
                f"not addressable from the parent — use call()")
        return self._loop0

    def worker_alive(self, index: int) -> bool:
        return self._worker(index).alive

    def worker_pid(self, index: int) -> int | None:
        w = self._worker(index)
        return w.proc.pid if w.proc is not None else None

    def _worker(self, index: int) -> _ProcWorker:
        if not 1 <= index <= self.num_procs:
            raise IndexError(f"no worker shard {index}")
        return self._workers[index - 1]

    # -- rejected thread-pool conveniences ------------------------------------

    def shared(self, key: str, factory: Callable[[], Any]) -> Any:
        raise NotImplementedError(
            "ProcShardPool.shared(): cross-process memory does not "
            "exist — marshal explicit state through call() (the "
            "admin-socket control channel) instead")

    async def run_on(self, index: int, coro) -> Any:
        coro.close()        # unawaited-coroutine warning suppression
        raise NotImplementedError(
            "ProcShardPool.run_on(): a coroutine (and anything its "
            "closure captures) cannot cross a process boundary — use "
            "call(index, request) with JSON-marshalled arguments")

    # -- control channel ------------------------------------------------------

    async def call(self, index: int, request: dict | str,
                   timeout: float = 30.0) -> Any:
        """One JSON verb to worker `index` over its admin-socket
        control channel (executor-hopped: the parent loop never blocks
        on the socket). Raises RuntimeError on a verb-level error."""
        from ceph_tpu.utils.admin_socket import admin_command
        w = self._worker(index)
        resp = await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(admin_command, w.socket_path,
                                    request, timeout))
        if "error" in resp:
            raise RuntimeError(f"worker shard{index}: {resp['error']}")
        return resp.get("result")

    async def config_set(self, name: str, value) -> dict:
        """Propagate one hot-togglable option to every live worker's
        daemons: each worker applies it to its OSDs' Configs, so the
        observers (offload batcher, pipeline depth, profiler, SLO
        engine...) fire in the owning process exactly as they would
        from an operator's `config set`. Recorded once ANY worker
        accepted it — so respawn() replays it onto a rejoining worker,
        while a key/value every worker rejected is not replayed
        forever. A worker whose channel is already dead (it is being
        reaped; a respawn replays the recorded overrides anyway) must
        not abort the broadcast for the rest — the live workers are
        driven CONCURRENTLY so one wedged channel cannot stack
        timeouts either. With NO live workers the override is recorded
        unconditionally: deferring it to the respawn replay is the
        whole point of the record."""
        live = [w for w in self._workers if w.alive]

        async def one(w: _ProcWorker):
            try:
                return await self.call(
                    w.index, {"prefix": "config set", "key": name,
                              "value": value}), None
            except Exception as e:
                return {"error": str(e)}, str(e)

        results = await asyncio.gather(*[one(w) for w in live])
        out = {f"shard{w.index}": res
               for w, (res, _err) in zip(live, results)}
        errors = {f"shard{w.index}": err
                  for w, (_res, err) in zip(live, results)
                  if err is not None}
        if errors and len(errors) == len(live):
            raise RuntimeError(
                f"{self.name}: config set {name} accepted by no "
                f"worker: {errors}")
        self._override_seq += 1
        self._config_overrides[name] = (self._override_seq, value)
        return out

    async def boot_osd(self, whoami: int,
                       mon_addrs: list[tuple[str, int]],
                       crush_location: dict | None = None,
                       timeout: float = 60.0) -> dict:
        """Boot OSD `whoami` in its placed worker; the spec is recorded
        so respawn() can replay it."""
        if self._closed:
            raise RuntimeError(f"{self.name}: pool is shut down")
        idx = self.place(whoami)
        spec = {"whoami": whoami,
                "mon_addrs": [list(a) for a in mon_addrs],
                "crush_location": crush_location}
        res = await self.call(idx, {"prefix": "boot_osd", **spec},
                              timeout=timeout)
        # record AFTER the worker accepted: a failed boot the caller
        # never admitted must not be replayed by a later respawn (the
        # same record-after-accept rule as config_set)
        self._worker(idx).boot_specs[whoami] = spec
        res["shard"] = idx
        return res

    def record_osd_override(self, whoami: int, name: str,
                            value) -> None:
        """Remember a per-OSD knob (WorkerOSDRef.config_set) so a
        respawned worker replays it onto that daemon's fresh boot, in
        chronological order with the pool-wide broadcasts."""
        w = self._worker(self.place(whoami))
        self._override_seq += 1
        w.osd_overrides.setdefault(whoami, {})[name] = \
            (self._override_seq, value)

    async def stop_osd(self, whoami: int, timeout: float = 30.0) -> None:
        idx = self.place(whoami)
        await self.call(idx, {"prefix": "stop_osd", "whoami": whoami},
                        timeout=timeout)
        # untrack only after the worker confirmed the stop: a failed
        # stop leaves a running daemon, and a later respawn must still
        # know about it
        self._worker(idx).boot_specs.pop(whoami, None)
        self._worker(idx).osd_overrides.pop(whoami, None)

    async def inject_crash(self, index: int) -> dict:
        """Drive the worker's faultinject `crash` verb: the worker
        SIGKILLs itself — heartbeat silence, reporter quorum, mark-down,
        exactly like an OOM-killed production daemon host. The SIGKILL
        deliberately races the JSON reply (that's the point of a
        crash): a connection torn down before the response flushed
        still means the kill fired."""
        import json
        flight.record("inject_crash", f"shard{index}", pool=self.name,
                      osds=sorted(self._worker(index).boot_specs))
        try:
            return await self.call(index, {"prefix": "inject",
                                           "what": "crash"},
                                   timeout=10.0)
        except (json.JSONDecodeError, OSError, ValueError):
            return {"injected": "crash", "shard": index,
                    "confirmed": False}

    async def respawn(self, index: int, timeout: float | None = None) -> dict:
        """Replace a dead worker with a fresh process and re-boot its
        recorded OSDs (fresh stores; recovery repopulates them)."""
        if self._closed:
            # shutdown is idempotent and already ran (or is running):
            # spawning now would orphan a process nothing ever reaps
            raise RuntimeError(f"{self.name}: pool is shut down")
        w = self._worker(index)
        if w.alive:
            raise RuntimeError(f"worker shard{index} is still alive")
        self._spawn(w)
        await self._wait_ready([w], timeout)
        booted = []
        for spec in list(w.boot_specs.values()):
            res = await self.call(index, {"prefix": "boot_osd", **spec},
                                  timeout=60.0)
            booted.append(res)
        # replay the operator's hot knobs — pool-wide broadcasts AND
        # per-OSD handle settings, in their ORIGINAL chronological
        # order (a broadcast that superseded a per-OSD value must win
        # again): a fresh process knows nothing of earlier config_set
        # calls, and rejoining with defaults while peers run tightened
        # values diverges the cluster silently
        replays = [(seq, None, name, value)
                   for name, (seq, value)
                   in self._config_overrides.items()]
        replays += [(seq, whoami, name, value)
                    for whoami, opts in w.osd_overrides.items()
                    if whoami in w.boot_specs
                    for name, (seq, value) in opts.items()]
        for _seq, whoami, name, value in sorted(replays):
            req = {"prefix": "config set", "key": name, "value": value}
            if whoami is not None:
                req["whoami"] = whoami
            try:
                await self.call(index, req)
            except Exception as e:
                dout("reactor", 1,
                     f"{self.name}: shard{index} config replay "
                     f"{name}={value!r} failed ({e})")
        dout("reactor", 1, f"{self.name}: worker shard{index} respawned "
                           f"(pid {w.proc.pid}), {len(booted)} OSD(s) "
                           f"re-booted")
        flight.record("worker_respawn", f"shard{index}", pool=self.name,
                      pid=w.proc.pid, osds_rebooted=len(booted))
        return {"pid": w.proc.pid, "osds": booted}

    # -- cross-process observability ------------------------------------------

    async def profile_stats(self) -> dict:
        """Pool-wide loop profiler view: the parent's own shard stats
        merged with every live worker's (`profile dump` over the
        control channel), keyed by POOL-WIDE shard label, plus the
        cross-process busy skew the bench trend guard watches."""
        from ceph_tpu.utils import loopprof
        # the parent contributes ONLY its own shard-0 loop: the
        # process-wide _per_loop store can carry stale shard1..N labels
        # from an earlier THREAD-pool profiling run in this process,
        # which would contaminate the identically-labeled worker stats
        parts = [{lbl: d for lbl, d in loopprof.shard_stats().items()
                  if lbl == "shard0"}]
        for w in self._workers:
            if not w.alive:
                continue
            try:
                prof = await self.call(w.index, "profile dump")
                parts.append(prof.get("shards", {}))
            except Exception as e:
                dout("reactor", 3,
                     f"{self.name}: shard{w.index} profile fetch "
                     f"failed ({type(e).__name__}: {e})")
        shards = loopprof.merge_shard_stats(*parts)
        # skew over the WORKER shards only: shard 0 is the control
        # plane and hosts no OSDs by design here (unlike the thread
        # pool), so including its near-idle loop would pin the skew at
        # ~1.0 and bury real worker imbalance
        workers = {lbl: d for lbl, d in shards.items()
                   if lbl != "shard0"}
        return {"shards": shards,
                "shard_busy_skew": loopprof.shard_busy_skew(workers)}

    # -- lifecycle ------------------------------------------------------------

    def _kill_all(self) -> None:
        for w in self._workers:
            if w.proc is not None and w.proc.poll() is None:
                try:
                    w.proc.kill()
                    w.proc.wait(5.0)
                except Exception:
                    pass
            w.alive = False

    async def shutdown(self, timeout: float = 20.0) -> None:
        """Drain and reap every worker (idempotent): graceful shutdown
        verb first (the worker bounded-stops its daemons and reaps its
        loop before exiting), escalate to SIGTERM/SIGKILL on a wedge."""
        if self._closed:
            return
        self._closed = True
        if self._supervisor is not None:
            await reap_all([self._supervisor])
            self._supervisor = None
        loop = asyncio.get_running_loop()

        async def drain(w: _ProcWorker) -> None:
            if w.proc is None:
                return
            if w.alive and w.proc.poll() is None:
                try:
                    await self.call(w.index, "shutdown", timeout=5.0)
                except Exception:
                    pass
            try:
                await asyncio.wait_for(loop.run_in_executor(
                    None, w.proc.wait), timeout)
            except Exception:
                dout("reactor", 1, f"{self.name}: worker shard{w.index} "
                                   f"did not exit cleanly; killing")
                try:
                    w.proc.send_signal(signal.SIGTERM)
                    await asyncio.wait_for(loop.run_in_executor(
                        None, w.proc.wait), 5.0)
                except Exception:
                    w.proc.kill()
                    await loop.run_in_executor(None, w.proc.wait)
            w.alive = False
            try:
                os.unlink(w.socket_path)
            except OSError:
                pass

        # drain workers CONCURRENTLY: the per-worker verb/wait/escalate
        # chains are independent, and a serial drain would cost
        # num_procs x timeout wall clock when several workers wedge
        await asyncio.gather(*[drain(w) for w in self._workers])
        if self._own_dir:
            try:
                os.rmdir(self._dir)
            except OSError:
                pass
        _unregister(self._loop0, self)
        dout("reactor", 1, f"{self.name}: process pool down")
