"""Sharded reactor runtime: N OS threads, each owning one asyncio loop.

BENCH_r05's attribution stage pins the 450x device-vs-cluster gap on a
single saturated Python event loop (`loop_busy_fraction` ~1.0 on the
only loop in the process): every OSD, the mon, the mgr, and the client
all contend for the same reactor thread, so the cluster's ceiling is
one core's worth of frame parsing and dispatch no matter how many
devices the offload service fans across. This module is the
Crimson/seastar analog the SURVEY names: a pool of reactor *shards*,
each an OS thread running its own event loop, with daemons placed
whole onto shards —

  * shard 0 is the CALLING loop (the harness/main loop): the mon, mgr,
    and clients stay there, exactly like the pre-shard world;
  * OSDs are placed round-robin across all shards (`place()`), so the
    data-plane daemons stop sharing one reactor;
  * connections between daemons on different shards are real localhost
    socket hops (the messenger already speaks TCP between daemons, so
    cross-shard needs no new wire plumbing); same-shard messaging
    stays in-loop;
  * a `ShardPool(1)` is the degenerate case: no threads, no behavior
    change — the knob dials concurrency without forking the code path.

Loop-affinity discipline (enforced by radoslint's `loop-affinity`
rule): loop-bound objects (asyncio primitives, the OffloadService, a
messenger Connection) belong to exactly one shard. Touching one from
another shard must go through the threadsafe seams — `run_on()` /
`run_on_each()` here, `loop.call_soon_threadsafe`, or
`asyncio.run_coroutine_threadsafe` — never a bare `call_soon`/
`create_task` on a foreign loop handle.

The pool also carries `shared(key, factory)`: process-level services
that must span every shard (the offload device topology and its
per-device circuit breakers) hang their one shared instance off the
pool instead of the loop, so four shards see one breaker state per
chip rather than four conflicting ones.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import sys
import threading
from typing import Any, Callable

from ceph_tpu.utils.async_util import reap_all
from ceph_tpu.utils.dout import dout

#: process-wide switch-interval management: the 0.5 ms bound is a
#: property of "any multi-shard pool is live", not of one pool — two
#: overlapping pools with per-pool save/restore would let the first
#: shutdown restore 5 ms under the second pool, then the second
#: shutdown "restore" 0.5 ms forever. Refcounted instead.
_switch_lock = threading.Lock()
_multi_pool_count = 0
_saved_interval: float | None = None


def _switch_interval_enter(interval_s: float) -> None:
    global _multi_pool_count, _saved_interval
    with _switch_lock:
        if _multi_pool_count == 0:
            _saved_interval = sys.getswitchinterval()
            sys.setswitchinterval(interval_s)
        _multi_pool_count += 1


def _switch_interval_exit() -> None:
    global _multi_pool_count, _saved_interval
    with _switch_lock:
        if _multi_pool_count == 0:
            return
        _multi_pool_count -= 1
        if _multi_pool_count == 0 and _saved_interval is not None:
            sys.setswitchinterval(_saved_interval)
            _saved_interval = None


#: loop -> (pool, shard_index); the process-wide placement registry.
#: Lets loop-keyed services (offload, loopprof) answer "which shard am
#: I, and which pool do I share state with" from any thread.
_registry_lock = threading.Lock()
_by_loop: dict[asyncio.AbstractEventLoop, tuple["ShardPool", int]] = {}


def _register(loop, pool: "ShardPool", index: int) -> None:
    with _registry_lock:
        for stale in [lp for lp in _by_loop if lp.is_closed()]:
            del _by_loop[stale]
        _by_loop[loop] = (pool, index)


def _unregister(loop) -> None:
    with _registry_lock:
        _by_loop.pop(loop, None)


def pool_for(loop) -> "ShardPool | None":
    """The ShardPool `loop` belongs to (None for unpooled loops —
    standalone tests and single-loop tools keep their private world)."""
    with _registry_lock:
        ent = _by_loop.get(loop)
    return ent[0] if ent is not None else None


def shard_index_of(loop) -> int | None:
    with _registry_lock:
        ent = _by_loop.get(loop)
    return ent[1] if ent is not None else None


def shard_label(loop) -> str | None:
    """Stable display label ("shard0"...) for exports, or None."""
    idx = shard_index_of(loop)
    return None if idx is None else f"shard{idx}"


def current_pool() -> "ShardPool | None":
    """The running loop's pool, or None (callable from coroutines)."""
    try:
        return pool_for(asyncio.get_running_loop())
    except RuntimeError:
        return None


class Shard:
    """One reactor: an event loop plus the thread that runs it (thread
    is None for shard 0, which borrows the creating loop)."""

    __slots__ = ("index", "loop", "thread", "ready")

    def __init__(self, index: int):
        self.index = index
        self.loop: asyncio.AbstractEventLoop | None = None
        self.thread: threading.Thread | None = None
        self.ready = threading.Event()


class ShardPool:
    """`n` reactor shards: the creating loop plus n-1 loop threads.

    Must be constructed on a running event loop (it becomes shard 0).
    `shutdown()` reaps every thread shard's leftover tasks before
    stopping its loop, so a pool teardown is as tail-clean as a daemon
    stop (no "Task was destroyed but it is pending")."""

    START_TIMEOUT = 10.0

    #: GIL switch interval while a multi-shard pool is live. A
    #: cross-shard hop (call_soon_threadsafe wakeup, socket readable on
    #: another shard) can wait up to a FULL switch interval for the GIL
    #: when every loop thread is busy; at CPython's default 5 ms that
    #: convoys a multi-hop EC write into tens of ms of pure handoff
    #: latency (measured: the 4-shard curve collapsed ~6x on a 2-core
    #: box before this). 0.5 ms trades a little single-thread
    #: throughput for bounded cross-shard latency.
    SWITCH_INTERVAL_S = 0.0005

    def __init__(self, num_shards: int, name: str = "reactor"):
        if num_shards < 1:
            raise ValueError("a reactor pool needs at least one shard")
        self.name = name
        self._closed = False
        self._holds_switch_interval = num_shards > 1
        if self._holds_switch_interval:
            _switch_interval_enter(self.SWITCH_INTERVAL_S)
        self._shared_lock = threading.Lock()
        self._shared: dict[str, Any] = {}
        shard0 = Shard(0)
        shard0.loop = asyncio.get_running_loop()
        shard0.ready.set()
        self._shards = [shard0]
        _register(shard0.loop, self, 0)
        try:
            for i in range(1, num_shards):
                shard = Shard(i)
                shard.thread = threading.Thread(
                    target=self._shard_main, args=(shard,),
                    name=f"{name}-shard{i}", daemon=True)
                self._shards.append(shard)
                shard.thread.start()
            for shard in self._shards[1:]:
                if not shard.ready.wait(self.START_TIMEOUT):
                    raise RuntimeError(f"{name} shard {shard.index} "
                                       f"never came up")
        except BaseException:
            # a failed boot must not leak running shard threads nor
            # leave the process-wide switch interval degraded
            self._abort_started_shards()
            raise
        dout("reactor", 1, f"{name}: {num_shards} shard(s) up")

    def _abort_started_shards(self) -> None:
        if self._holds_switch_interval:
            _switch_interval_exit()
            self._holds_switch_interval = False
        for shard in self._shards[1:]:
            loop = shard.loop
            if loop is not None and not loop.is_closed():
                loop.call_soon_threadsafe(loop.stop)
            if shard.thread is not None:
                shard.thread.join(self.START_TIMEOUT)
        _unregister(self._shards[0].loop)
        self._closed = True

    # -- placement -----------------------------------------------------------

    @property
    def num_shards(self) -> int:
        return len(self._shards)

    def place(self, seq: int) -> int:
        """Round-robin shard index for the seq-th data-plane daemon."""
        return seq % len(self._shards)

    def loop(self, index: int) -> asyncio.AbstractEventLoop:
        return self._shards[index].loop

    # -- cross-shard seams ---------------------------------------------------

    async def run_on(self, index: int, coro) -> Any:
        """Run `coro` on shard `index` and await its result from the
        calling shard. Same-shard awaits inline; cross-shard hops via
        run_coroutine_threadsafe (the call_soon_threadsafe handoff)."""
        target = self._shards[index].loop
        if target is asyncio.get_running_loop():
            return await coro
        cfut = asyncio.run_coroutine_threadsafe(coro, target)
        return await asyncio.wrap_future(cfut)

    async def run_on_each(self, fn: Callable[[], Any]) -> list:
        """Run sync `fn()` ON every shard's loop thread (shard 0
        inline) — the arming hook for per-loop instruments (loopprof
        install/uninstall need the loop thread's ident)."""
        out = []
        for shard in self._shards:
            if shard.loop is asyncio.get_running_loop():
                out.append(fn())
                continue
            done: concurrent.futures.Future = concurrent.futures.Future()

            def call(done=done):
                try:
                    done.set_result(fn())
                except BaseException as e:   # marshal failures back whole
                    done.set_exception(e)
            shard.loop.call_soon_threadsafe(call)
            out.append(await asyncio.wrap_future(done))
        return out

    # -- pool-scoped shared state --------------------------------------------

    def shared(self, key: str, factory: Callable[[], Any]) -> Any:
        """Get-or-create the pool-wide instance of a cross-shard
        service (one offload device topology per pool, not per loop)."""
        with self._shared_lock:
            obj = self._shared.get(key)
            if obj is None:
                obj = self._shared[key] = factory()
            return obj

    # -- lifecycle -----------------------------------------------------------

    def _shard_main(self, shard: Shard) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        shard.loop = loop
        _register(loop, self, shard.index)
        shard.ready.set()
        try:
            loop.run_forever()
            # post-stop drain: anything still pending here was created
            # after the final reap (or leaked past a daemon stop) —
            # cancel-and-await so loop.close() destroys nothing pending
            leftovers = asyncio.all_tasks(loop)
            if leftovers:
                loop.run_until_complete(reap_all(leftovers))
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.run_until_complete(loop.shutdown_default_executor())
        finally:
            try:
                from ceph_tpu.utils import loopprof
                loopprof.uninstall(loop)     # defensive: sampler unarm
            except Exception:
                pass
            _unregister(loop)
            loop.close()

    async def _drain_shard(self) -> None:
        """Runs ON a thread shard: reap every task but ourselves."""
        cur = asyncio.current_task()
        await reap_all([t for t in asyncio.all_tasks() if t is not cur])

    async def shutdown(self, timeout: float = 20.0) -> None:
        """Reap and stop every thread shard (idempotent). The daemons
        on each shard must already be stopped — this reaps stragglers,
        parks the loop, and joins the thread."""
        if self._closed:
            return
        self._closed = True
        if self._holds_switch_interval:
            _switch_interval_exit()
            self._holds_switch_interval = False
        for shard in self._shards[1:]:
            loop = shard.loop
            if loop is None or loop.is_closed():
                continue
            cfut = asyncio.run_coroutine_threadsafe(
                self._drain_shard(), loop)
            try:
                await asyncio.wait_for(asyncio.wrap_future(cfut), timeout)
            except Exception as e:
                dout("reactor", 1,
                     f"{self.name}: shard {shard.index} drain failed "
                     f"({type(e).__name__}: {e}); stopping it anyway")
                cfut.cancel()
            loop.call_soon_threadsafe(loop.stop)
            if shard.thread is not None:
                await asyncio.get_running_loop().run_in_executor(
                    None, shard.thread.join, timeout)
        _unregister(self._shards[0].loop)
        dout("reactor", 1, f"{self.name}: pool down")
