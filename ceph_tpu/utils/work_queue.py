"""Sharded op queue, Finisher, and OpTracker — the OSD's intra-node
parallelism + per-op observability substrate.

Re-creations of:
  * ShardedThreadPool / op shards (src/common/WorkQueue.h:569,
    src/osd/OSD.h:1282 osd_op_tp): ops are hashed to a shard by PG so
    same-PG ops stay FIFO while shards run concurrently; every shard
    worker checks into the HeartbeatMap so a wedged shard is detected
    (src/common/HeartbeatMap.h contract);
  * Finisher (src/common/Finisher.h): ordered completion-callback
    drain, decoupling completions from the paths that queue them;
  * OpTracker / TrackedOp (src/common/TrackedOp.h, src/osd/OpRequest.h):
    per-op event timelines, in-flight dump, bounded historic ring and
    slow-op accounting, exposed via the admin socket
    (`dump_ops_in_flight`, `dump_historic_ops` — the reference's
    debugging workhorse);
  * per-client accountant (ClientTable): the OpTracker grown into the
    multi-tenant lens — a bounded top-K table attributing ops, bytes,
    in-flight depth, and read/write latency histograms to individual
    `client.<id>` entities (identity negotiated at the msgr2 handshake,
    stamped on MOSDOp), with a configurable SLO engine
    (`slo_read_ms`/`slo_write_ms`) counting good-vs-violating ops per
    client. This is the accounting substrate an mClock-style QoS
    scheduler arbitrates on (src/osd/scheduler/mClockScheduler.h needs
    exactly these per-client tallies), surfaced via the admin-socket
    `dump_clients` verb and the MgrReport `client_metrics` path.

Idiomatic divergences: shards are asyncio tasks on one loop rather than
threads (the loop is the concurrency substrate everywhere in this
stack); timeline stamps come from time.monotonic with wall-clock start.
All age/duration math derives from the monotonic `_t0` ONLY — the
wall-clock `initiated_at` is display metadata (an NTP step must never
turn into a phantom slow op or a negative latency).
"""
from __future__ import annotations

import asyncio
import collections
import contextvars
import threading
import time
from typing import Awaitable, Callable

from ceph_tpu.osd.scheduler import MClockScheduler, default_profile
from ceph_tpu.utils import flight
from ceph_tpu.utils.async_util import being_cancelled
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.perf_counters import (TYPE_GAUGE, PerfCounters,
                                          pow2_bucket)
from ceph_tpu.utils.throttle import HeartbeatMap

#: op kinds that mutate state — a client op carrying any of these is
#: accounted as a WRITE (bytes = the data segment it shipped); pure
#: reads are accounted by the bytes they returned. Watch/notify and
#: listing ops are "other": they gather for seconds by design, and
#: folding them into the read histogram would poison every read SLO.
#: This is the ONE mutating-op set: PG.MOD_OPS (the ops that get a log
#: entry) derives from it, so the two can never drift apart.
WRITE_OP_KINDS = frozenset({
    "write_full", "write", "append", "truncate", "zero", "create",
    "delete", "setxattr", "rmxattr", "omap_set", "omap_rm", "rollback",
    "snaptrim", "call"})
OTHER_OP_KINDS = frozenset({"watch", "unwatch", "notify", "list",
                            "list_watchers", "list_snaps"})


def classify_ops(ops: list[dict]) -> str:
    """'write' | 'read' | 'other' for a client op vector."""
    kinds = {o.get("op") for o in ops}
    if kinds & WRITE_OP_KINDS:
        return "write"
    if kinds and kinds <= OTHER_OP_KINDS:
        return "other"
    return "read"

# the op being processed by the current task — backends stamp events on
# it without threading a handle through every call (the reference passes
# OpRequestRef the same way a thread-local trace context would)
_current_op: contextvars.ContextVar["TrackedOp | None"] = \
    contextvars.ContextVar("tracked_op", default=None)


def set_current_op(op: "TrackedOp | None"):
    return _current_op.set(op)


def reset_current_op(token) -> None:
    _current_op.reset(token)


def mark_op_event(event: str) -> None:
    """Stamp `event` on the current task's TrackedOp, if any."""
    op = _current_op.get()
    if op is not None and not op.done:
        op.mark_event(event)


def current_op() -> "TrackedOp | None":
    """The TrackedOp the current task is executing (None outside one).
    Op-execution paths use this to stamp per-client byte/kind
    accounting without threading the handle through every call."""
    return _current_op.get()


def _win_quantile(window, q: float) -> float:
    """Quantile (µs) over a rolling latency window; 0 when empty."""
    if not window:
        return 0.0
    vals = sorted(window)
    return vals[min(len(vals) - 1, int(q * (len(vals) - 1)))]


class _ClientEntry:
    """One client's running tallies (all timing monotonic-derived)."""

    __slots__ = ("name", "tenant", "ops", "rd_ops", "wr_ops",
                 "rd_bytes", "wr_bytes", "in_flight",
                 "rd_buckets", "wr_buckets", "rd_win", "wr_win",
                 "slo_good", "slo_violations", "viol_stamps",
                 "last_active", "folded_from")

    def __init__(self, name: str, tenant: str | None,
                 window: int) -> None:
        self.name = name
        self.tenant = tenant
        self.ops = 0
        self.rd_ops = 0
        self.wr_ops = 0
        self.rd_bytes = 0
        self.wr_bytes = 0
        self.in_flight = 0
        self.rd_buckets: dict[int, int] = {}
        self.wr_buckets: dict[int, int] = {}
        self.rd_win: collections.deque[float] = \
            collections.deque(maxlen=window)
        self.wr_win: collections.deque[float] = \
            collections.deque(maxlen=window)
        self.slo_good = 0
        self.slo_violations = 0
        # monotonic stamps of recent violations: the health surface
        # reports violations within a sliding window, so SLO_VIOLATIONS
        # clears by itself once an overload ends
        self.viol_stamps: collections.deque[float] = \
            collections.deque(maxlen=512)
        self.last_active = time.monotonic()
        self.folded_from = 0        # entries merged into this one

    def absorb(self, other: "_ClientEntry") -> None:
        """Fold `other`'s tallies into this (the `_other` overflow row).
        in_flight is deliberately NOT absorbed: the victim's still-open
        ops re-materialize its row at finish time with a clamped
        decrement, so moving the count here would strand it in `_other`
        forever (a gauge that only ever rises). In-flight depth is a
        property of LIVE identities; a folded client forfeits its
        snapshot and restarts at zero."""
        self.ops += other.ops
        self.rd_ops += other.rd_ops
        self.wr_ops += other.wr_ops
        self.rd_bytes += other.rd_bytes
        self.wr_bytes += other.wr_bytes
        for b, n in other.rd_buckets.items():
            self.rd_buckets[b] = self.rd_buckets.get(b, 0) + n
        for b, n in other.wr_buckets.items():
            self.wr_buckets[b] = self.wr_buckets.get(b, 0) + n
        self.slo_good += other.slo_good
        self.slo_violations += other.slo_violations
        self.viol_stamps.extend(other.viol_stamps)
        self.folded_from += 1 + other.folded_from


class ClientTable(PerfCounters):
    """Bounded top-K per-client accountant + SLO engine.

    A PerfCounters subclass so the process-wide collection owns its
    aggregate counters AND `perf reset` (admin socket) zeroes the
    per-client tables with everything else. The per-client detail
    travels the MgrReport `client_metrics` path (mgr merges across
    OSDs; exporter renders `ceph_client_*` families), never the
    counter delta path — 64-bucket histograms per client would bloat
    every report.

    Thread contract: mutation happens on the OSD loop; `dump_clients`
    and `perf dump`/`perf reset` arrive from admin-socket threads. A
    dedicated table lock (separate from the PerfCounters counter lock,
    which `self.inc` takes internally) covers the entry dict; lock
    order is always table -> counter, never the reverse.
    """

    WINDOW = 512                   # rolling-latency samples per client
    SLO_RECENT_S = 30.0            # violation freshness window (health)
    SLOW_CLIENT_FACTOR = 4.0       # p99 > factor*SLO => SLOW_CLIENT
    OTHER = "_other"               # the overflow fold row

    def __init__(self, name: str = "optracker.clients",
                 max_entries: int = 256):
        super().__init__(name)
        self.add("clients", type=TYPE_GAUGE,
                 description="distinct client entities tracked")
        self.add("client_ops",
                 description="client ops accounted to an entity")
        self.add("client_read_bytes",
                 description="bytes returned to clients by reads")
        self.add("client_written_bytes",
                 description="bytes accepted from clients by writes "
                             "(dup-op replays excluded)")
        self.add("client_slo_good",
                 description="ops that met their class SLO")
        self.add("client_slo_violations",
                 description="ops that blew their class SLO")
        self.add("clients_folded",
                 description="client entries folded into _other by "
                             "the top-K table bound")
        self._tlock = threading.Lock()
        self._entries: dict[str, _ClientEntry] = {}
        self.max_entries = max(2, int(max_entries))
        # SLO thresholds in SECONDS (0 = class unguarded); set from the
        # slo_read_ms / slo_write_ms config observer, hot
        self.slo_read_s = 0.0
        self.slo_write_s = 0.0

    # -- config hooks --------------------------------------------------------

    def set_slo(self, read_ms: float | None = None,
                write_ms: float | None = None) -> None:
        if read_ms is not None:
            self.slo_read_s = max(0.0, float(read_ms)) / 1e3
        if write_ms is not None:
            self.slo_write_s = max(0.0, float(write_ms)) / 1e3

    def resize(self, max_entries: int) -> None:
        self.max_entries = max(2, int(max_entries))
        with self._tlock:
            while len(self._entries) > self.max_entries:
                if not self._fold_one_locked():
                    break

    # -- accounting (OSD loop) -----------------------------------------------

    def _entry_locked(self, client: str,
                      tenant: str | None) -> _ClientEntry:
        e = self._entries.get(client)
        if e is None:
            # fold until the INSERT below lands within the bound — the
            # first fold may be size-neutral (it creates `_other`), so
            # loop; _fold_one_locked returning False (only `_other`
            # left) breaks the loop
            while len(self._entries) >= self.max_entries:
                if not self._fold_one_locked():
                    break
            e = self._entries[client] = _ClientEntry(client, tenant,
                                                     self.WINDOW)
        elif tenant and e.tenant is None:
            e.tenant = tenant
        return e

    def _fold_one_locked(self) -> bool:
        """Evict the least-recently-active entry into `_other` (bounded
        top-K: identities churn, tallies are never dropped)."""
        victim = min(
            (e for k, e in self._entries.items() if k != self.OTHER),
            key=lambda e: e.last_active, default=None)
        if victim is None:
            return False
        del self._entries[victim.name]
        other = self._entries.get(self.OTHER)
        if other is None:
            other = self._entries[self.OTHER] = _ClientEntry(
                self.OTHER, None, self.WINDOW)
        other.absorb(victim)
        other.last_active = time.monotonic()
        self.inc("clients_folded")
        return True

    def op_start(self, client: str, tenant: str | None = None) -> None:
        with self._tlock:
            e = self._entry_locked(client, tenant)
            e.in_flight += 1
            e.last_active = time.monotonic()
            n = len(self._entries)
        self.set("clients", n)

    def op_finished(self, op: "TrackedOp") -> None:
        """Account a finished tracked op: latency into the client's
        kind histogram + rolling window, bytes, SLO verdict. Duration
        is the op's monotonic duration — wall time never enters."""
        dur_s = op.duration
        us = dur_s * 1e6
        now = time.monotonic()
        viol = good = 0
        with self._tlock:
            # a folded (or reset-raced) client re-materializes: its
            # in-flight decrement must land on the row that carries it
            e = self._entries.get(op.client) \
                or self._entry_locked(op.client, op.tenant)
            e.in_flight = max(0, e.in_flight - 1)
            e.last_active = now
            e.ops += 1
            if op.kind == "read":
                e.rd_ops += 1
                e.rd_bytes += op.rd_bytes
                b = pow2_bucket(us)
                e.rd_buckets[b] = e.rd_buckets.get(b, 0) + 1
                e.rd_win.append(us)
                slo = self.slo_read_s
                if slo > 0:
                    if dur_s > slo:
                        viol, e.slo_violations = 1, e.slo_violations + 1
                        e.viol_stamps.append(now)
                    else:
                        good, e.slo_good = 1, e.slo_good + 1
            elif op.kind == "write":
                e.wr_ops += 1
                e.wr_bytes += op.wr_bytes
                b = pow2_bucket(us)
                e.wr_buckets[b] = e.wr_buckets.get(b, 0) + 1
                e.wr_win.append(us)
                slo = self.slo_write_s
                if slo > 0:
                    if dur_s > slo:
                        viol, e.slo_violations = 1, e.slo_violations + 1
                        e.viol_stamps.append(now)
                    else:
                        good, e.slo_good = 1, e.slo_good + 1
        self.inc("client_ops")
        if op.rd_bytes:
            self.inc("client_read_bytes", op.rd_bytes)
        if op.wr_bytes:
            self.inc("client_written_bytes", op.wr_bytes)
        if viol:
            self.inc("client_slo_violations")
        elif good:
            self.inc("client_slo_good")

    # -- surfaces ------------------------------------------------------------

    def dump_clients(self, limit: int | None = None) -> dict:
        """Admin-socket `dump_clients`: the top-K table, ops-sorted,
        with rolling-window p50/p99 per class and the SLO ledger."""
        now = time.monotonic()
        with self._tlock:
            entries = sorted(self._entries.values(),
                             key=lambda e: e.ops, reverse=True)
            if limit:
                entries = entries[:int(limit)]
            rows = []
            for e in entries:
                rows.append({
                    "client": e.name, "tenant": e.tenant,
                    "ops": e.ops, "read_ops": e.rd_ops,
                    "write_ops": e.wr_ops,
                    "read_bytes": e.rd_bytes,
                    "written_bytes": e.wr_bytes,
                    "in_flight": e.in_flight,
                    "read_ms": {
                        "p50": round(_win_quantile(e.rd_win, 0.5) / 1e3,
                                     3),
                        "p99": round(_win_quantile(e.rd_win, 0.99) / 1e3,
                                     3)},
                    "write_ms": {
                        "p50": round(_win_quantile(e.wr_win, 0.5) / 1e3,
                                     3),
                        "p99": round(_win_quantile(e.wr_win, 0.99) / 1e3,
                                     3)},
                    "slo": {"good": e.slo_good,
                            "violations": e.slo_violations},
                    "idle_s": round(now - e.last_active, 3),
                    "folded_from": e.folded_from})
            return {"num_clients": len(self._entries),
                    "table_bound": self.max_entries,
                    "slo_read_ms": round(self.slo_read_s * 1e3, 3),
                    "slo_write_ms": round(self.slo_write_s * 1e3, 3),
                    "clients": rows}

    def mgr_metrics(self) -> dict:
        """Per-client tallies for the MgrReport `client_metrics` path.
        Ships raw histogram buckets (power-of-two µs exponents) so the
        mgr can merge a client's latency distribution ACROSS OSDs and
        quote honest cross-cluster percentiles."""
        with self._tlock:
            out = {}
            for e in self._entries.values():
                out[e.name] = {
                    "tenant": e.tenant, "ops": e.ops,
                    "read_ops": e.rd_ops, "write_ops": e.wr_ops,
                    "read_bytes": e.rd_bytes,
                    "written_bytes": e.wr_bytes,
                    "in_flight": e.in_flight,
                    "slo_good": e.slo_good,
                    "slo_violations": e.slo_violations,
                    "read_buckets": {str(b): n for b, n
                                     in sorted(e.rd_buckets.items())},
                    "write_buckets": {str(b): n for b, n
                                      in sorted(e.wr_buckets.items())}}
            return out

    def health_metrics(self) -> dict:
        """The SLO health surface for the mgr digest: violations inside
        the freshness window (self-clearing once an overload ends) and
        clients whose rolling p99 sits far beyond the SLO."""
        now = time.monotonic()
        horizon = now - self.SLO_RECENT_S
        recent = 0
        violating = []
        slow = []
        with self._tlock:
            for e in self._entries.values():
                r = sum(1 for t in e.viol_stamps if t >= horizon)
                if r:
                    recent += r
                    violating.append({"client": e.name, "recent": r})
                for kind, win, slo in (("read", e.rd_win,
                                        self.slo_read_s),
                                       ("write", e.wr_win,
                                        self.slo_write_s)):
                    if slo <= 0 or len(win) < 8:
                        continue
                    p99_us = _win_quantile(win, 0.99)
                    if p99_us > self.SLOW_CLIENT_FACTOR * slo * 1e6:
                        slow.append({
                            "client": e.name, "kind": kind,
                            "p99_ms": round(p99_us / 1e3, 1),
                            "slo_ms": round(slo * 1e3, 1)})
            tracked = len(self._entries)
        violating.sort(key=lambda v: v["recent"], reverse=True)
        return {"tracked": tracked,
                "recent_violations": recent,
                "violating_clients": violating[:16],
                "slow_clients": slow[:16]}

    def reset(self) -> None:
        """`perf reset` contract: the aggregate counters AND the whole
        per-client table (histogram buckets, rolling windows, SLO
        ledgers) go to zero — a reset scrape shows empty buckets."""
        super().reset()
        with self._tlock:
            self._entries.clear()


class TrackedOp:
    """One op's lifetime: description + stamped event timeline."""

    __slots__ = ("tracker", "seq", "description", "initiated_at",
                 "_t0", "events", "done", "trace",
                 "client", "tenant", "kind", "rd_bytes", "wr_bytes")

    def __init__(self, tracker: "OpTracker", seq: int, description: str,
                 client: str | None = None, tenant: str | None = None):
        self.tracker = tracker
        self.seq = seq
        self.description = description
        # wall-clock stamp for DISPLAY ONLY (historic-op dumps show a
        # human-readable start time); every age/duration derives from
        # the monotonic _t0 so a wall-clock step cannot fake a slow op
        self.initiated_at = time.time()
        self._t0 = time.monotonic()
        self.events: list[tuple[float, str]] = [(0.0, "initiated")]
        self.done = False
        # tracer wire context ({"t","s"}) captured at ingest: carries the
        # trace through the sharded queue (closures run in a different
        # task, so the contextvar alone cannot), and lets historic-op
        # dumps name the trace an op belongs to
        self.trace: dict | None = None
        # per-client accounting: identity from the session handshake,
        # kind/bytes filled in by the op execution path (rd/wr bytes
        # stay zero on dup-op replays so a retry never double-counts)
        self.client = client
        self.tenant = tenant
        self.kind: str | None = None
        self.rd_bytes = 0
        self.wr_bytes = 0

    def mark_event(self, event: str) -> None:
        self.events.append((round(time.monotonic() - self._t0, 6), event))

    @property
    def duration(self) -> float:
        return self.events[-1][0] if self.done else \
            time.monotonic() - self._t0

    def finish(self) -> None:
        if not self.done:
            self.mark_event("done")
            self.done = True
            self.tracker._finished(self)

    def to_dict(self) -> dict:
        # "age" is monotonic-derived; "initiated_at" is the wall stamp
        # for humans correlating dumps with logs, nothing computes on it
        out = {"seq": self.seq, "description": self.description,
               "initiated_at": self.initiated_at,
               "age": round(self.duration, 6),
               "events": [{"t": t, "event": e} for t, e in self.events]}
        if self.client:
            out["client"] = self.client
            if self.tenant:
                out["tenant"] = self.tenant
        if self.trace is not None:
            out["trace_id"] = format(self.trace["t"], "016x")
            # per-stage durations from the op's span SKELETON (tracing
            # v2 tail reservoir: name -> max µs) — slow-op triage works
            # even on daemons whose traces were never sampled/promoted
            try:
                from ceph_tpu.utils import tracer
                stages = tracer.op_stages(self.trace["t"])
            except Exception:
                stages = None
            if stages:
                out["stages_us"] = stages
        return out


class OpTracker:
    """In-flight registry + bounded historic ring (TrackedOp.h)."""

    def __init__(self, history_size: int = 20, history_slow_size: int = 20,
                 slow_threshold: float = 1.0,
                 clients: ClientTable | None = None):
        self._seq = 0
        self.ops_in_flight: dict[int, TrackedOp] = {}
        self.historic: collections.deque[TrackedOp] = \
            collections.deque(maxlen=history_size)
        self.historic_slow: collections.deque[TrackedOp] = \
            collections.deque(maxlen=history_slow_size)
        self.slow_threshold = slow_threshold
        self.slow_count = 0
        # the per-client accountant rides the tracker: every tracked op
        # carrying a client identity lands in its table on finish
        self.clients = clients if clients is not None else ClientTable()

    def create(self, description: str, client: str | None = None,
               tenant: str | None = None) -> TrackedOp:
        self._seq += 1
        op = TrackedOp(self, self._seq, description,
                       client=client, tenant=tenant)
        self.ops_in_flight[op.seq] = op
        if client:
            self.clients.op_start(client, tenant)
        return op

    def _finished(self, op: TrackedOp) -> None:
        self.ops_in_flight.pop(op.seq, None)
        self.historic.append(op)
        if op.client:
            self.clients.op_finished(op)
        if op.duration >= self.slow_threshold:
            self.slow_count += 1
            self.historic_slow.append(op)
            dout("optracker", 2,
                 f"slow op ({op.duration:.3f}s): {op.description}")
            flight.record("slow_op", op.client or "",
                          duration_s=round(op.duration, 3),
                          description=op.description)

    def dump_ops_in_flight(self) -> dict:
        return {"num_ops": len(self.ops_in_flight),
                "ops": [op.to_dict()
                        for op in self.ops_in_flight.values()]}

    def dump_historic_ops(self) -> dict:
        return {"size": len(self.historic),
                "slow_count": self.slow_count,
                "ops": [op.to_dict() for op in self.historic]}

    def dump_historic_slow_ops(self) -> dict:
        return {"ops": [op.to_dict() for op in self.historic_slow]}

    def get_health_metrics(self) -> dict:
        """Daemon health metrics for the mgr report (the reference's
        OSDService::get_health_metrics feeding MMgrReport): in-flight
        ops older than the slow threshold + the oldest such age. These
        drive the mon's SLOW_OPS check."""
        now_slow = [op.duration for op in self.ops_in_flight.values()
                    if op.duration >= self.slow_threshold]
        return {"slow_ops": len(now_slow),
                "oldest_age_s": round(max(now_slow, default=0.0), 3)}


class Finisher:
    """Ordered async completion drain (Finisher.h). queue() preserves
    submission order; callbacks run on the finisher task, never inline."""

    def __init__(self, name: str = "finisher",
                 hb_map: HeartbeatMap | None = None):
        self.name = name
        self._q: asyncio.Queue = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._hb_map = hb_map
        self._hb_id: int | None = None

    def start(self) -> None:
        if self._hb_map is not None:
            self._hb_id = self._hb_map.add_worker(self.name, grace=30.0)
        self._task = asyncio.get_running_loop().create_task(self._drain())

    async def stop(self) -> None:
        if self._task is not None:
            await self._q.put(None)
            await self._task
            self._task = None
        if self._hb_map is not None and self._hb_id is not None:
            self._hb_map.remove_worker(self._hb_id)

    def queue(self, fn: Callable[[], object]) -> None:
        self._q.put_nowait(fn)

    async def _drain(self) -> None:
        while True:
            fn = await self._q.get()
            if fn is None:
                return
            if self._hb_map is not None and self._hb_id is not None:
                self._hb_map.touch(self._hb_id)
            try:
                res = fn()
                if asyncio.iscoroutine(res):
                    await res
            except Exception as e:
                dout("finisher", 1, f"{self.name}: callback raised "
                                    f"{type(e).__name__}: {e}")


class _KeyWindow:
    """Per-key (per-PG) in-flight execution state of one shard: how many
    items of each class are running, which object streams are occupied,
    and whether an exclusive (obj=None) item holds the key."""

    __slots__ = ("counts", "objs", "exclusive")

    def __init__(self):
        self.counts = collections.Counter()     # klass -> in-flight
        self.objs: set = set()                  # objects in execution
        self.exclusive = False                  # obj=None item running

    @property
    def total(self) -> int:
        return sum(self.counts.values())


class ShardedOpQueue:
    """N shards drained concurrently; work is routed by key hash so
    same-key (same-PG) items keep their order (osd_op_tp semantics).

    Each shard holds one FIFO per OP CLASS, drained by weighted round
    robin — the mClock-lite QoS split (src/osd/scheduler/
    mClockScheduler.h:92, OpSchedulerItem op classes): client traffic
    gets `WEIGHTS["client"]` dequeues for every 1 a background class
    gets, so recovery/backfill can neither starve clients nor be
    starved by them.

    Pipelined admission (`pipeline_depth` > 1, the PrimaryLogPG
    concurrent-op analog): instead of awaiting each item to completion,
    a shard worker ADMITS up to `pipeline_depth` items per key per
    class into concurrently-running tasks, with ordering guarantees:

      * FIFO within an object: an item is never started while an
        earlier same-key item for the same `obj` is queued or running
        (the obc write-lock ordering — same-object ops serialize in
        arrival order; different objects of one PG overlap);
      * an item with `obj=None` is an exclusive barrier for its key
        WITHIN ITS CLASS: it waits for the key to fully drain, runs
        alone, and no later item of its class starts until it
        completes (multi-object/unkeyed ops keep the old whole-PG
        serial semantics). Admission order ACROSS classes stays
        WRR-arbitrated, exactly as it was pre-pipelining — a recovery
        item enqueued after a client barrier may run first, and
        cannot starve it: recovery serializes per PG with the key
        going idle between items, at which point the barrier (scanned
        first, client credits) admits;
      * windows are per (key, class), so a saturated client window
        cannot starve recovery admission for the same PG — but object
        conflicts span classes (a recovery rebuild of X still
        serializes against a client write of X);
      * QoS credits are spent at START time only: a class whose head is
        window-blocked burns no credits, so weighted round robin
        arbitrates over STARTABLE work (the credit-holding stall bug).

    `pipeline_depth=1` runs the exact legacy path: the worker awaits
    each item inline, one in flight per shard, bit-identical ordering.
    Hot-resizable via set_pipeline_depth (the osd_pg_pipeline_depth
    observer); completions refill the window (completion-driven
    admission, no polling).

    dmclock mode (`osd_mclock_enabled`, set_mclock_enabled): the WRR
    class split is replaced by per-ENTITY tag-clock arbitration
    (osd/scheduler/dmclock.py) — an entity is a client tenant or a
    background class's pseudo-entity; each shard keeps one FIFO per
    entity and the scheduler orders entities by reservation/limit/
    weight tags, byte-cost normalized. The window/ordering guarantees
    above carry over per entity queue: same-object FIFO and obj=None
    barriers hold WITHIN an entity (Ceph's ordering contract is
    per-client; cross-tenant same-object execution still serializes on
    the windows, only admission order is QoS-arbitrated). Overload:
    limit-blocked shards sleep until the earliest l_tag matures
    (backpressure) or enqueue refuses past a depth cap (shed — the
    caller replies EAGAIN-style). Toggling is hot: queued items
    migrate between the class and entity queues preserving arrival
    order, and with the scheduler OFF this code path is bit-identical
    to the legacy WRR queue.
    """

    #: legacy-path class weights, derived from the declared profile
    #: (satellite fix: classes are registered in
    #: osd/scheduler/profile.py, not hardcoded — the phantom `scrub`
    #: entry with no producer is gone)
    WEIGHTS = default_profile().wrr_weights()

    def __init__(self, name: str = "osd_op_tp", num_shards: int = 5,
                 hb_map: HeartbeatMap | None = None,
                 hb_grace: float = 30.0, pipeline_depth: int = 1,
                 perf: "PerfCounters | None" = None,
                 profile=None, clock=time.monotonic):
        self.name = name
        self.num_shards = num_shards
        self.profile = profile if profile is not None \
            else default_profile()
        self._weights = self.profile.wrr_weights()
        # each queued item is (key, obj, work, entity, cost, seq);
        # entity/cost ride along even on the legacy path so a hot
        # toggle can migrate queued work without losing attribution
        self._queues: list[dict[str, collections.deque]] = [
            {k: collections.deque() for k in self._weights}
            for _ in range(num_shards)]
        self._wake = [asyncio.Event() for _ in range(num_shards)]
        self._credits: list[dict[str, int]] = [
            dict(self._weights) for _ in range(num_shards)]
        # dmclock mode: per-shard entity -> deque of
        # (key, obj, work, klass, cost, seq)
        self.sched = MClockScheduler(self.profile, clock=clock)
        self.mclock_enabled = False
        self._ent_queues: list[dict[str, collections.deque]] = [
            {} for _ in range(num_shards)]
        self._defer: list[float | None] = [None] * num_shards
        self._seq = 0
        self._last_defer_flight = 0.0
        self.deferred_waits = 0
        self._inflight: list[dict] = [{} for _ in range(num_shards)]
        self._exec_tasks: list[set] = [set() for _ in range(num_shards)]
        self._stalled = [False] * num_shards
        self._stopping = False
        self._tasks: list[asyncio.Task] = []
        self._hb_map = hb_map
        self._hb_grace = hb_grace
        self._hb_ids: list[int] = []
        self.pipeline_depth = max(1, int(pipeline_depth))
        # optional daemon counters: pg_pipeline_inflight gauge +
        # pg_pipeline_window_stalls (declared by the OSD)
        self.perf = perf
        self._inflight_total = 0
        self.window_stalls = 0
        # flight-recorder rate limit: a saturated window can stall
        # thousands of times a second, and the black box wants "the
        # queue was stalling around t", not a flooded ring
        self._last_stall_flight = 0.0
        self.processed = 0
        self.processed_by_class = collections.Counter()

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._stopping = False
        for i in range(self.num_shards):
            if self._hb_map is not None:
                self._hb_ids.append(self._hb_map.add_worker(
                    f"{self.name}.{i}", grace=self._hb_grace))
            self._tasks.append(loop.create_task(self._worker(i)))

    async def stop(self) -> None:
        self._stopping = True
        for ev in self._wake:
            ev.set()
        # workers exit via the wake events, not cancellation. Unlike
        # drain(), an unexpected worker crash must PROPAGATE out of
        # stop() — swallowing it would report clean shutdown over a
        # dead shard; only our own cancellation contract applies
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                if being_cancelled() or not t.done():
                    raise       # a cancelled stop() stays cancellable
        self._tasks.clear()
        # pipelined executions the workers spawned: _run_one swallows
        # work exceptions, so awaiting these only propagates our own
        # cancellation — nothing may stay pending past stop()
        for tasks in self._exec_tasks:
            for t in list(tasks):
                try:
                    await t
                except asyncio.CancelledError:
                    if being_cancelled() or not t.done():
                        raise
            tasks.clear()
        for hid in self._hb_ids:
            self._hb_map.remove_worker(hid)
        self._hb_ids.clear()

    def shard_of(self, key) -> int:
        return hash(key) % self.num_shards

    def set_pipeline_depth(self, depth: int) -> None:
        """Hot-resize the per-PG execution window (config observer).
        Growing wakes every shard so blocked work admits immediately;
        shrinking takes effect as in-flight items complete."""
        self.pipeline_depth = max(1, int(depth))
        for ev in self._wake:
            ev.set()

    def set_mclock_enabled(self, enabled: bool) -> None:
        """Hot-toggle the dmclock arbiter (osd_mclock_enabled
        observer). Queued work MIGRATES between the legacy class
        queues and the per-entity queues preserving arrival order
        (every item carries its enqueue seq), so a toggle mid-storm
        loses nothing and reorders nothing within an entity."""
        enabled = bool(enabled)
        if enabled == self.mclock_enabled:
            return
        self.mclock_enabled = enabled
        for shard in range(self.num_shards):
            if enabled:
                items = []
                for klass, q in self._queues[shard].items():
                    while q:
                        key, obj, work, entity, nbytes, seq = \
                            q.popleft()
                        items.append((seq, entity,
                                      (key, obj, work, klass,
                                       nbytes, seq)))
                for seq, entity, item in sorted(items,
                                                key=lambda t: t[0]):
                    klass = item[3]
                    self.sched.entity(entity, klass).queued += 1
                    self._ent_queues[shard].setdefault(
                        entity, collections.deque()).append(item)
            else:
                items = []
                for entity, q in self._ent_queues[shard].items():
                    while q:
                        key, obj, work, klass, nbytes, seq = \
                            q.popleft()
                        self.sched.note_drop(entity)
                        items.append((seq,
                                      (key, obj, work, entity,
                                       nbytes, seq), klass))
                self._ent_queues[shard].clear()
                for seq, item, klass in sorted(items,
                                               key=lambda t: t[0]):
                    if klass not in self._weights:
                        self._register_class(klass)
                    self._queues[shard][klass].append(item)
                self._defer[shard] = None
            self._wake[shard].set()
        flight.record("qos_toggle", self.name, enabled=enabled)

    def configure_qos(self, **kw) -> None:
        """Forward knob values to the scheduler (config observer path)
        and re-arbitrate: a loosened limit must unblock a deferred
        shard without waiting out its old sleep."""
        self.sched.configure(**kw)
        for ev in self._wake:
            ev.set()

    def qos_status(self) -> dict:
        """Admin-socket `qos status` body."""
        st = self.sched.status()
        st["enabled"] = self.mclock_enabled
        st["deferred_waits"] = self.deferred_waits
        st["queued"] = {
            "legacy": sum(len(q) for shard in self._queues
                          for q in shard.values()),
            "mclock": sum(len(q) for shard in self._ent_queues
                          for q in shard.values())}
        return st

    def total_in_flight(self) -> int:
        """Items currently in pipelined execution across all shards."""
        return self._inflight_total

    def in_flight(self, key) -> int:
        """Items of `key` currently in execution (window occupancy)."""
        st = self._inflight[self.shard_of(key)].get(key)
        return st.total if st is not None else 0

    def enqueue(self, key, work: Callable[[], Awaitable],
                klass: str = "client", obj=None, entity: str | None = None,
                nbytes: int = 0) -> bool:
        """Queue an async thunk on the shard owning `key`. `obj` names
        the object stream the item belongs to (same-obj items stay
        FIFO); None makes the item an exclusive barrier for its key.
        `entity` is the QoS accounting identity (client tenant;
        background classes default to a class pseudo-entity) and
        `nbytes` its payload size for byte-cost normalization.

        Returns False when admission control SHED the op (dmclock mode,
        shed policy, entity backlog past the depth cap) — the caller
        owes the client an EAGAIN-style throttle reply. Always True on
        the legacy path."""
        shard = self.shard_of(key)
        if entity is None:
            entity = f"class:{klass}" if klass != "client" else "client"
        self._seq += 1
        if self.mclock_enabled:
            if not self.sched.note_enqueue(entity, klass):
                if self.perf is not None:
                    self.perf.inc("qos_shed")
                flight.record("qos_shed", self.name, tenant=entity,
                              klass=klass,
                              depth=self.sched.shed_queue_depth)
                return False
            self._ent_queues[shard].setdefault(
                entity, collections.deque()).append(
                (key, obj, work, klass, nbytes, self._seq))
        else:
            if klass not in self._weights:
                self._register_class(klass)
            self._queues[shard][klass].append(
                (key, obj, work, entity, nbytes, self._seq))
        self._wake[shard].set()
        return True

    def _register_class(self, klass: str) -> None:
        """A producer enqueued a class no profile declared: register it
        late (wrr=1 best-effort) on every shard rather than KeyError —
        see QosProfile.ensure."""
        self.profile.ensure(klass)
        self._weights = self.profile.wrr_weights()
        for shard in range(self.num_shards):
            self._queues[shard].setdefault(klass, collections.deque())
            self._credits[shard].setdefault(
                klass, self._weights[klass])

    # -- admission -----------------------------------------------------------

    def _startable(self, infl: dict, key, obj, klass: str,
                   depth: int) -> bool:
        st = infl.get(key)
        if st is None:
            return True
        if st.exclusive or st.counts[klass] >= depth:
            return False
        if obj is None:
            return st.total == 0        # barrier: needs the key idle
        return obj not in st.objs

    def _scan(self, q: collections.deque, infl: dict, klass: str,
              depth: int) -> tuple | None:
        """First startable item of one class queue, honoring per-object
        FIFO: a skipped item shadows everything behind it that must not
        overtake it (its object stream; its whole key when the skip was
        a full window or a waiting barrier).

        O(queued) per admission — acceptable at OSD queue depths (a
        shard's class backlog is client-concurrency / (osds × shards));
        if deep backlogs ever profile here, the structural fix is
        per-key subqueues with a ready list so blocked streams are
        skipped without rescanning."""
        blocked_keys: set = set()
        blocked_objs: set = set()
        for i, item in enumerate(q):
            key, obj = item[0], item[1]
            if key in blocked_keys:
                continue
            if obj is not None and (key, obj) in blocked_objs:
                continue
            if self._startable(infl, key, obj, klass, depth):
                del q[i]
                return item
            if obj is None:
                # a waiting barrier: nothing behind it for this key
                # may overtake (it is a sync point)
                blocked_keys.add(key)
                continue
            st = infl.get(key)
            if st is not None and (st.exclusive
                                   or st.counts[klass] >= depth):
                blocked_keys.add(key)   # whole window full
            else:
                blocked_objs.add((key, obj))
        return None

    def _admit(self, shard: int, klass: str, key, obj) -> None:
        st = self._inflight[shard].setdefault(key, _KeyWindow())
        st.counts[klass] += 1
        if obj is None:
            st.exclusive = True
        else:
            st.objs.add(obj)
        self._inflight_total += 1
        if self.perf is not None:
            self.perf.set("pg_pipeline_inflight", self._inflight_total)

    def _complete(self, shard: int, klass: str, key, obj) -> None:
        infl = self._inflight[shard]
        st = infl.get(key)
        if st is not None:
            st.counts[klass] -= 1
            if obj is None:
                st.exclusive = False
            else:
                st.objs.discard(obj)
            if st.total <= 0:
                del infl[key]
        self._inflight_total -= 1
        if self.perf is not None:
            self.perf.set("pg_pipeline_inflight", self._inflight_total)
        self._wake[shard].set()         # completion-driven refill

    def _pick(self, shard: int) -> tuple | None:
        """Weighted round robin over STARTABLE work: class credits are
        spent only when an item actually admits (a window-blocked class
        holds its credits — satellite audit: the old picker charged the
        class before knowing the item could run); refill when no
        credited class can start anything. Sets the shard's stall flag
        when queued work existed but every item was window-blocked."""
        if self.mclock_enabled:
            return self._pick_mclock(shard)
        queues, credits = self._queues[shard], self._credits[shard]
        infl = self._inflight[shard]
        depth = self.pipeline_depth
        self._stalled[shard] = False
        blocked = False
        for attempt in range(2):
            blocked = False
            for klass in self._weights:
                if not queues[klass] or credits[klass] <= 0:
                    continue
                item = self._scan(queues[klass], infl, klass, depth)
                if item is None:
                    blocked = True
                    continue
                credits[klass] -= 1
                self.processed_by_class[klass] += 1
                self._admit(shard, klass, *item[:2])
                return (klass, *item[:3])
            # nothing admitted on credits: refill and retry once (an
            # uncredited class may hold startable work); a second dry
            # pass with blocked work means everything queued is
            # window-blocked
            self._credits[shard] = dict(self._weights)
            credits = self._credits[shard]
        self._stalled[shard] = blocked
        return None

    def _pick_mclock(self, shard: int) -> tuple | None:
        """dmclock admission: the scheduler orders entities by tag
        clocks; the first entity whose head-of-queue survives the
        ordering windows admits. Window semantics (same-obj FIFO,
        obj=None barriers) are enforced per entity queue by the same
        _scan shadowing — see the class docstring for the ordering
        contract. Sets the shard's defer hint when every queued entity
        is limit-blocked (backpressure sleep)."""
        queues = self._ent_queues[shard]
        infl = self._inflight[shard]
        depth = self.pipeline_depth
        self._stalled[shard] = False
        self._defer[shard] = None
        ready = [e for e, q in queues.items() if q]
        if not ready:
            return None
        order, defer_s, defer_ent = self.sched.schedule(ready)
        if not order and self._stopping:
            # shutdown drains ignore limit tags: stop() must not wait
            # out a throttle horizon to finish queued work
            order, defer_s = [(e, "weight") for e in sorted(ready)], None
        blocked = False
        for entity, phase in order:
            q = queues.get(entity)
            if not q:
                continue
            item = self._scan_entity(q, infl, depth)
            if item is None:
                blocked = True
                continue
            key, obj, work, klass, nbytes, _seq = item
            if not q:
                del queues[entity]
            self.sched.charge(entity, self.sched.cost_of(nbytes),
                              phase=phase)
            if self.perf is not None:
                self.perf.inc("qos_dequeue_reservation"
                              if phase == "reservation"
                              else "qos_dequeue_weight")
            self.processed_by_class[klass] += 1
            self._admit(shard, klass, key, obj)
            return (klass, key, obj, work)
        if defer_s is not None:
            self._defer[shard] = defer_s
            self.deferred_waits += 1
            if self.perf is not None:
                self.perf.inc("qos_deferred_waits")
            now = time.monotonic()
            if now - self._last_defer_flight >= 0.5:
                self._last_defer_flight = now
                flight.record("qos_backpressure", self.name,
                              shard=shard, tenant=defer_ent,
                              defer_ms=round(defer_s * 1000, 3))
        self._stalled[shard] = blocked
        return None

    def _scan_entity(self, q: collections.deque, infl: dict,
                     depth: int) -> tuple | None:
        """_scan for a per-entity queue: items carry their own class
        (an entity queue is single-class in practice, but the window
        check keys on the item's class either way)."""
        blocked_keys: set = set()
        blocked_objs: set = set()
        for i, item in enumerate(q):
            key, obj, klass = item[0], item[1], item[3]
            if key in blocked_keys:
                continue
            if obj is not None and (key, obj) in blocked_objs:
                continue
            if self._startable(infl, key, obj, klass, depth):
                del q[i]
                return item
            if obj is None:
                blocked_keys.add(key)
                continue
            st = infl.get(key)
            if st is not None and (st.exclusive
                                   or st.counts[klass] >= depth):
                blocked_keys.add(key)
            else:
                blocked_objs.add((key, obj))
        return None

    async def _run_one(self, shard: int, klass: str, key, obj,
                       work) -> None:
        try:
            await work()
        except Exception as e:
            dout("osd", 1, f"{self.name}.{shard}: work raised "
                           f"{type(e).__name__}: {e}")
        finally:
            self.processed += 1
            self._complete(shard, klass, key, obj)

    def _shard_empty(self, shard: int) -> bool:
        return not any(self._queues[shard].values()) and \
            not any(self._ent_queues[shard].values())

    async def _worker(self, shard: int) -> None:
        loop = asyncio.get_running_loop()
        while True:
            picked = self._pick(shard)
            if picked is None:
                if self._stopping and self._shard_empty(shard):
                    return
                self._wake[shard].clear()
                picked = self._pick(shard)      # close the enqueue race
            if picked is None:
                if self._stopping and self._shard_empty(shard):
                    return
                if self._stalled[shard]:
                    # queued work exists but every item is blocked
                    # behind a full window: a completion will wake us
                    self.window_stalls += 1
                    if self.perf is not None:
                        self.perf.inc("pg_pipeline_window_stalls")
                    now = time.monotonic()
                    if now - self._last_stall_flight >= 0.5:
                        self._last_stall_flight = now
                        flight.record(
                            "pg_window_stall", self.name, shard=shard,
                            stalls=self.window_stalls,
                            depth=self.pipeline_depth)
                defer = self._defer[shard]
                if defer is not None:
                    # backpressure: every queued entity is at its
                    # limit — sleep until the earliest l_tag matures
                    # (or an enqueue/completion wakes us early), then
                    # re-arbitrate
                    try:
                        await asyncio.wait_for(
                            self._wake[shard].wait(),
                            timeout=min(defer, 1.0))
                    except (asyncio.TimeoutError, TimeoutError):
                        pass
                    continue
                await self._wake[shard].wait()
                continue
            klass, key, obj, work = picked
            if self._hb_ids:
                self._hb_map.touch(self._hb_ids[shard])
            if self.pipeline_depth <= 1:
                # legacy serial path: bit-identical to the pre-pipeline
                # queue (one in-flight item per shard, awaited inline)
                await self._run_one(shard, klass, key, obj, work)
            else:
                t = loop.create_task(
                    self._run_one(shard, klass, key, obj, work))
                self._exec_tasks[shard].add(t)
                t.add_done_callback(self._exec_tasks[shard].discard)
