"""Sharded op queue, Finisher, and OpTracker — the OSD's intra-node
parallelism + per-op observability substrate.

Re-creations of:
  * ShardedThreadPool / op shards (src/common/WorkQueue.h:569,
    src/osd/OSD.h:1282 osd_op_tp): ops are hashed to a shard by PG so
    same-PG ops stay FIFO while shards run concurrently; every shard
    worker checks into the HeartbeatMap so a wedged shard is detected
    (src/common/HeartbeatMap.h contract);
  * Finisher (src/common/Finisher.h): ordered completion-callback
    drain, decoupling completions from the paths that queue them;
  * OpTracker / TrackedOp (src/common/TrackedOp.h, src/osd/OpRequest.h):
    per-op event timelines, in-flight dump, bounded historic ring and
    slow-op accounting, exposed via the admin socket
    (`dump_ops_in_flight`, `dump_historic_ops` — the reference's
    debugging workhorse).

Idiomatic divergences: shards are asyncio tasks on one loop rather than
threads (the loop is the concurrency substrate everywhere in this
stack); timeline stamps come from time.monotonic with wall-clock start.
"""
from __future__ import annotations

import asyncio
import collections
import contextvars
import time
from typing import Awaitable, Callable

from ceph_tpu.utils.async_util import being_cancelled
from ceph_tpu.utils.dout import dout
from ceph_tpu.utils.throttle import HeartbeatMap

# the op being processed by the current task — backends stamp events on
# it without threading a handle through every call (the reference passes
# OpRequestRef the same way a thread-local trace context would)
_current_op: contextvars.ContextVar["TrackedOp | None"] = \
    contextvars.ContextVar("tracked_op", default=None)


def set_current_op(op: "TrackedOp | None"):
    return _current_op.set(op)


def reset_current_op(token) -> None:
    _current_op.reset(token)


def mark_op_event(event: str) -> None:
    """Stamp `event` on the current task's TrackedOp, if any."""
    op = _current_op.get()
    if op is not None and not op.done:
        op.mark_event(event)


class TrackedOp:
    """One op's lifetime: description + stamped event timeline."""

    __slots__ = ("tracker", "seq", "description", "initiated_at",
                 "_t0", "events", "done", "trace")

    def __init__(self, tracker: "OpTracker", seq: int, description: str):
        self.tracker = tracker
        self.seq = seq
        self.description = description
        self.initiated_at = time.time()
        self._t0 = time.monotonic()
        self.events: list[tuple[float, str]] = [(0.0, "initiated")]
        self.done = False
        # tracer wire context ({"t","s"}) captured at ingest: carries the
        # trace through the sharded queue (closures run in a different
        # task, so the contextvar alone cannot), and lets historic-op
        # dumps name the trace an op belongs to
        self.trace: dict | None = None

    def mark_event(self, event: str) -> None:
        self.events.append((round(time.monotonic() - self._t0, 6), event))

    @property
    def duration(self) -> float:
        return self.events[-1][0] if self.done else \
            time.monotonic() - self._t0

    def finish(self) -> None:
        if not self.done:
            self.mark_event("done")
            self.done = True
            self.tracker._finished(self)

    def to_dict(self) -> dict:
        out = {"seq": self.seq, "description": self.description,
               "initiated_at": self.initiated_at,
               "age": round(self.duration, 6),
               "events": [{"t": t, "event": e} for t, e in self.events]}
        if self.trace is not None:
            out["trace_id"] = format(self.trace["t"], "016x")
        return out


class OpTracker:
    """In-flight registry + bounded historic ring (TrackedOp.h)."""

    def __init__(self, history_size: int = 20, history_slow_size: int = 20,
                 slow_threshold: float = 1.0):
        self._seq = 0
        self.ops_in_flight: dict[int, TrackedOp] = {}
        self.historic: collections.deque[TrackedOp] = \
            collections.deque(maxlen=history_size)
        self.historic_slow: collections.deque[TrackedOp] = \
            collections.deque(maxlen=history_slow_size)
        self.slow_threshold = slow_threshold
        self.slow_count = 0

    def create(self, description: str) -> TrackedOp:
        self._seq += 1
        op = TrackedOp(self, self._seq, description)
        self.ops_in_flight[op.seq] = op
        return op

    def _finished(self, op: TrackedOp) -> None:
        self.ops_in_flight.pop(op.seq, None)
        self.historic.append(op)
        if op.duration >= self.slow_threshold:
            self.slow_count += 1
            self.historic_slow.append(op)
            dout("optracker", 2,
                 f"slow op ({op.duration:.3f}s): {op.description}")

    def dump_ops_in_flight(self) -> dict:
        return {"num_ops": len(self.ops_in_flight),
                "ops": [op.to_dict()
                        for op in self.ops_in_flight.values()]}

    def dump_historic_ops(self) -> dict:
        return {"size": len(self.historic),
                "slow_count": self.slow_count,
                "ops": [op.to_dict() for op in self.historic]}

    def dump_historic_slow_ops(self) -> dict:
        return {"ops": [op.to_dict() for op in self.historic_slow]}

    def get_health_metrics(self) -> dict:
        """Daemon health metrics for the mgr report (the reference's
        OSDService::get_health_metrics feeding MMgrReport): in-flight
        ops older than the slow threshold + the oldest such age. These
        drive the mon's SLOW_OPS check."""
        now_slow = [op.duration for op in self.ops_in_flight.values()
                    if op.duration >= self.slow_threshold]
        return {"slow_ops": len(now_slow),
                "oldest_age_s": round(max(now_slow, default=0.0), 3)}


class Finisher:
    """Ordered async completion drain (Finisher.h). queue() preserves
    submission order; callbacks run on the finisher task, never inline."""

    def __init__(self, name: str = "finisher",
                 hb_map: HeartbeatMap | None = None):
        self.name = name
        self._q: asyncio.Queue = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._hb_map = hb_map
        self._hb_id: int | None = None

    def start(self) -> None:
        if self._hb_map is not None:
            self._hb_id = self._hb_map.add_worker(self.name, grace=30.0)
        self._task = asyncio.get_running_loop().create_task(self._drain())

    async def stop(self) -> None:
        if self._task is not None:
            await self._q.put(None)
            await self._task
            self._task = None
        if self._hb_map is not None and self._hb_id is not None:
            self._hb_map.remove_worker(self._hb_id)

    def queue(self, fn: Callable[[], object]) -> None:
        self._q.put_nowait(fn)

    async def _drain(self) -> None:
        while True:
            fn = await self._q.get()
            if fn is None:
                return
            if self._hb_map is not None and self._hb_id is not None:
                self._hb_map.touch(self._hb_id)
            try:
                res = fn()
                if asyncio.iscoroutine(res):
                    await res
            except Exception as e:
                dout("finisher", 1, f"{self.name}: callback raised "
                                    f"{type(e).__name__}: {e}")


class ShardedOpQueue:
    """N shards drained concurrently; work is routed by key hash so
    same-key (same-PG) items keep their order (osd_op_tp semantics).

    Each shard holds one FIFO per OP CLASS, drained by weighted round
    robin — the mClock-lite QoS split (src/osd/scheduler/
    mClockScheduler.h:92, OpSchedulerItem op classes): client traffic
    gets `WEIGHTS["client"]` dequeues for every 1 a background class
    gets, so recovery/backfill can neither starve clients nor be
    starved by them. FIFO order holds within a class per shard.
    """

    WEIGHTS = {"client": 4, "recovery": 1, "scrub": 1}

    def __init__(self, name: str = "osd_op_tp", num_shards: int = 5,
                 hb_map: HeartbeatMap | None = None,
                 hb_grace: float = 30.0):
        self.name = name
        self.num_shards = num_shards
        self._queues: list[dict[str, collections.deque]] = [
            {k: collections.deque() for k in self.WEIGHTS}
            for _ in range(num_shards)]
        self._wake = [asyncio.Event() for _ in range(num_shards)]
        self._credits: list[dict[str, int]] = [
            dict(self.WEIGHTS) for _ in range(num_shards)]
        self._stopping = False
        self._tasks: list[asyncio.Task] = []
        self._hb_map = hb_map
        self._hb_grace = hb_grace
        self._hb_ids: list[int] = []
        self.processed = 0
        self.processed_by_class = collections.Counter()

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._stopping = False
        for i in range(self.num_shards):
            if self._hb_map is not None:
                self._hb_ids.append(self._hb_map.add_worker(
                    f"{self.name}.{i}", grace=self._hb_grace))
            self._tasks.append(loop.create_task(self._worker(i)))

    async def stop(self) -> None:
        self._stopping = True
        for ev in self._wake:
            ev.set()
        # workers exit via the wake events, not cancellation. Unlike
        # drain(), an unexpected worker crash must PROPAGATE out of
        # stop() — swallowing it would report clean shutdown over a
        # dead shard; only our own cancellation contract applies
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                if being_cancelled() or not t.done():
                    raise       # a cancelled stop() stays cancellable
        self._tasks.clear()
        for hid in self._hb_ids:
            self._hb_map.remove_worker(hid)
        self._hb_ids.clear()

    def shard_of(self, key) -> int:
        return hash(key) % self.num_shards

    def enqueue(self, key, work: Callable[[], Awaitable],
                klass: str = "client") -> None:
        """Queue an async thunk on the shard owning `key`."""
        shard = self.shard_of(key)
        self._queues[shard][klass].append(work)
        self._wake[shard].set()

    def _pick(self, shard: int) -> Callable | None:
        """Weighted round robin: spend class credits in weight order;
        refill when every non-empty class is out of credits."""
        queues, credits = self._queues[shard], self._credits[shard]
        for _ in range(2):
            for klass in self.WEIGHTS:
                if queues[klass] and credits[klass] > 0:
                    credits[klass] -= 1
                    self.processed_by_class[klass] += 1
                    return queues[klass].popleft()
            # out of credits for every backlogged class: refill
            self._credits[shard] = dict(self.WEIGHTS)
            credits = self._credits[shard]
        return None

    async def _worker(self, shard: int) -> None:
        while True:
            work = self._pick(shard)
            if work is None:
                if self._stopping:
                    return
                self._wake[shard].clear()
                if any(self._queues[shard].values()):
                    continue        # raced a concurrent enqueue
                await self._wake[shard].wait()
                continue
            if self._hb_ids:
                self._hb_map.touch(self._hb_ids[shard])
            try:
                await work()
            except Exception as e:
                dout("osd", 1, f"{self.name}.{shard}: work raised "
                               f"{type(e).__name__}: {e}")
            self.processed += 1
