"""Composable `loop.call_soon` wrapper chains.

Two instruments wrap `call_soon` on the same loop — the sanitizer's
foreign-thread recorder and the qa interleaving explorer's
bounded-shuffler — and their install/uninstall order is NOT guaranteed
to nest (a `config set sanitizer_enabled false` can land mid-explore).
The composition protocol lives here ONCE so both layers stay in sync:

  * `wrap(loop, key, make_wrapper)` saves the current callable under
    `_<key>_orig`, installs `make_wrapper(orig)`, and is a no-op when
    that key's wrapper is already in the chain (the wrapper is REUSED —
    it must consult its own armed state at call time);
  * `unwrap(loop, key)` restores the saved callable only when this
    key's wrapper is the TOP of the chain. A buried wrapper (someone
    wrapped on top since) stays installed as a pass-through — popping
    it would strip everything above it — and the saved attrs remain so
    a later `wrap()` reuses it instead of double-wrapping.
"""
from __future__ import annotations

from typing import Callable


def wrap(loop, key: str, make_wrapper: Callable) -> None:
    """Install (or reuse) a call_soon wrapper under `key`.
    `make_wrapper(orig)` builds the wrapper; it MUST degrade to a
    pass-through when its owner is disarmed, because it can outlive
    an `unwrap()` (see module doc)."""
    if getattr(loop, f"_{key}_orig", None) is not None:
        return                          # in-chain wrapper reused
    orig = loop.call_soon
    wrapper = make_wrapper(orig)
    setattr(loop, f"_{key}_orig", orig)
    setattr(loop, f"_{key}_wrapper", wrapper)
    loop.call_soon = wrapper


def unwrap(loop, key: str) -> None:
    """Pop this key's wrapper IFF it is the top of the chain; a buried
    wrapper stays (as a pass-through) so wrappers above it survive."""
    orig = getattr(loop, f"_{key}_orig", None)
    if orig is None:
        return
    if loop.__dict__.get("call_soon") is \
            getattr(loop, f"_{key}_wrapper", None):
        loop.call_soon = orig
        setattr(loop, f"_{key}_orig", None)
        setattr(loop, f"_{key}_wrapper", None)
