"""PerfCounters: typed runtime metrics with JSON dump.

Re-creation of the reference's perf counter machinery
(src/common/perf_counters.h): counters are u64 (monotonic), gauge
(u64 up/down), time (accumulated seconds), or avg (sum + count pairs,
read as a consistent tuple); histograms are power-of-two bucketed. A
process-wide `PerfCountersCollection` aggregates per-component instances
and serves the admin-socket `perf dump` / `perf schema` commands.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Iterable

TYPE_U64 = "u64"
TYPE_GAUGE = "gauge"
TYPE_TIME = "time"
TYPE_AVG = "avg"
TYPE_HISTOGRAM = "histogram"


def pow2_bucket(value: float) -> int:
    """Power-of-two bucket index: bucket i counts values in
    [2^i, 2^(i+1)); 4096 lands in "2^12". The ONE bucketing rule —
    every histogram source (these counters, the per-client latency
    tables) must share it or cross-source merges and the exporter's
    cumulative `le` edges silently disagree."""
    return max(0, min(63, int(value).bit_length() - 1)) if value >= 1 \
        else 0


class PerfCounters:
    """One component's named counters (PerfCountersBuilder output)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._types: dict[str, str] = {}
        self._desc: dict[str, str] = {}
        self._values: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._buckets: dict[str, list[int]] = {}

    def add(self, key: str, type: str = TYPE_U64, description: str = "") -> None:
        if type not in (TYPE_U64, TYPE_GAUGE, TYPE_TIME, TYPE_AVG,
                        TYPE_HISTOGRAM):
            raise ValueError(f"unknown counter type {type}")
        with self._lock:
            if key in self._types:
                raise ValueError(f"counter {key} already exists")
            self._types[key] = type
            self._desc[key] = description
            self._values[key] = 0
            self._counts[key] = 0
            if type == TYPE_HISTOGRAM:
                self._buckets[key] = [0] * 64

    def _check(self, key: str, *allowed: str) -> str:
        t = self._types.get(key)
        if t is None:
            raise KeyError(f"no counter {key}")
        if allowed and t not in allowed:
            raise TypeError(f"counter {key} is {t}, not {allowed}")
        return t

    def inc(self, key: str, amount: int = 1) -> None:
        self._check(key, TYPE_U64, TYPE_GAUGE)
        with self._lock:
            self._values[key] += amount

    def dec(self, key: str, amount: int = 1) -> None:
        self._check(key, TYPE_GAUGE)
        with self._lock:
            self._values[key] -= amount

    def set(self, key: str, value: float) -> None:
        self._check(key, TYPE_U64, TYPE_GAUGE)
        with self._lock:
            self._values[key] = value

    def tinc(self, key: str, seconds: float) -> None:
        self._check(key, TYPE_TIME)
        with self._lock:
            self._values[key] += seconds

    def time(self, key: str):
        """Context manager accumulating elapsed wall time into a TIME
        counter."""
        counters = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                counters.tinc(key, time.perf_counter() - self.t0)
                return False

        return _Timer()

    def avg_add(self, key: str, value: float) -> None:
        self._check(key, TYPE_AVG)
        with self._lock:
            self._values[key] += value
            self._counts[key] += 1

    def hist_add(self, key: str, value: float) -> None:
        self._check(key, TYPE_HISTOGRAM)
        bucket = pow2_bucket(value)
        with self._lock:
            self._buckets[key][bucket] += 1
            self._values[key] += value
            self._counts[key] += 1

    def dump(self) -> dict:
        with self._lock:
            out = {}
            for key, t in self._types.items():
                if t == TYPE_AVG:
                    out[key] = {"avgcount": self._counts[key],
                                "sum": self._values[key]}
                elif t == TYPE_HISTOGRAM:
                    buckets = {f"2^{i}": n
                               for i, n in enumerate(self._buckets[key]) if n}
                    out[key] = {"count": self._counts[key],
                                "sum": self._values[key],
                                "buckets": buckets}
                else:
                    out[key] = self._values[key]
            return out

    def schema(self) -> dict:
        with self._lock:
            return {key: {"type": t, "description": self._desc[key]}
                    for key, t in self._types.items()}

    def reset(self) -> None:
        """Zero every counter (admin-socket `perf reset`): values, avg
        counts, and histogram buckets — the schema survives."""
        with self._lock:
            for key in self._types:
                self._values[key] = 0
                self._counts[key] = 0
                if key in self._buckets:
                    self._buckets[key] = [0] * 64


class PerfCountersCollection:
    """Process-wide registry (perf dump aggregates all components)."""

    _instance: "PerfCountersCollection | None" = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._loggers: dict[str, PerfCounters] = {}

    @classmethod
    def instance(cls) -> "PerfCountersCollection":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def create(self, name: str) -> PerfCounters:
        return self.register(PerfCounters(name))

    def register(self, pc: PerfCounters) -> PerfCounters:
        """Insert an already-built (possibly subclassed) PerfCounters —
        pull-model loggers like the copyflow ledger mirror override
        dump() and register themselves here."""
        with self._lock:
            if pc.name in self._loggers:
                raise ValueError(
                    f"perf counters {pc.name} already registered")
            self._loggers[pc.name] = pc
            return pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def get(self, name: str) -> PerfCounters | None:
        with self._lock:
            return self._loggers.get(name)

    def dump(self, logger: str | None = None) -> dict:
        with self._lock:
            items = (self._loggers.items() if logger is None
                     else [(logger, self._loggers[logger])])
        return {name: pc.dump() for name, pc in items}

    def schema(self) -> dict:
        with self._lock:
            items = list(self._loggers.items())
        return {name: pc.schema() for name, pc in items}

    def reset(self, logger: str | None = None) -> dict:
        """Zero all counters (or one logger's): `perf reset` analog."""
        with self._lock:
            items = (list(self._loggers.items()) if logger is None
                     else [(logger, self._loggers[logger])])
        for _, pc in items:
            pc.reset()
        return {"reset": [name for name, _ in items]}
