"""Shared crash-injection exception for durability tests.

One class for every storage tier (FileStore WAL window, BlueStore txc
window, LSM WAL window) so harness code can catch `SimulatedCrash` from
the package it drives without knowing which layer raised it.
"""


class SimulatedCrash(Exception):
    """Raised by a fail_* test hook at the exact point a real crash
    would interrupt a commit; the durable state before the hook must
    fully reconstruct on remount."""
